"""Named 2-D mesh construction + the `ShardPlan` that drives rule-based
parameter sharding through the captured step.

A plan binds (mesh, ordered rules, data axis) and resolves every
parameter name to a concrete `NamedSharding`. It layers over
`kvstore.capture_spec`: a KVStore with a plan attached
(`KVStore.set_shard_plan`) makes `Trainer.capture` compile the step with
per-parameter in/out shardings instead of the 1-D replicated shard_map —
the GSPMD partitioner then inserts the FSDP gather-before-use /
reduce-scatter-after-backward and the TP collectives the specs imply
(the generalisation of the hand-written psum/reduce-scatter/all-gather
lowering to arbitrary specs; arXiv:2112.01075's portable-collectives
framing). Params, grads, and optimizer state stay sharded BETWEEN steps;
only what a spec replicates is ever whole on a device.

Canonical axes: ``dp`` (data parallel — the batch shards over it) and
``tp`` (tensor parallel). `make_mesh_2d(dp=..., tp=...)` builds the
standard layout; any `jax.sharding.Mesh` whose axis names the rules
reference works.
"""
from __future__ import annotations

import itertools
import warnings

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from . import rules as _rules

__all__ = ["make_mesh_2d", "as_mesh", "ShardPlan", "plan"]

_plan_seq = itertools.count()


def make_mesh_2d(dp=-1, tp=1, devices=None):
    """The canonical ('dp', 'tp') mesh. ``dp=-1`` infers the data axis
    from the device count; sizes must multiply to at most the devices
    available."""
    from ..parallel.mesh import make_mesh
    return make_mesh({"dp": dp, "tp": tp}, devices=devices)


def as_mesh(target, devices=None):
    """Normalise a Mesh / {axis: size} dict / (dp, tp) tuple into a
    `jax.sharding.Mesh`."""
    if isinstance(target, Mesh):
        return target
    if isinstance(target, dict):
        from ..parallel.mesh import make_mesh
        return make_mesh(target, devices=devices)
    if isinstance(target, (tuple, list)) and len(target) == 2 and \
            all(isinstance(x, int) for x in target):
        return make_mesh_2d(dp=target[0], tp=target[1], devices=devices)
    raise MXNetError(f"cannot build a mesh from {target!r}; pass a "
                     f"jax.sharding.Mesh, an {{axis: size}} dict, or a "
                     f"(dp, tp) tuple")


class ShardPlan:
    """Resolved rule-driven sharding over one mesh.

    Resolution is lazy and cached per (name, shape): `spec_for` matches
    the ordered rules (first match wins, `re.search` — shard/rules.py),
    then normalises against the mesh and the concrete shape; every
    downgrade (non-divisible dim, unknown axis) and unmatched name is
    recorded in `report()` instead of raising. `sharding(name, shape)`
    returns the `NamedSharding` the captured step compiles against.

    The plan is immutable w.r.t. its mesh; `with_mesh(new_mesh)` derives
    the same rules over a different mesh — the elastic-resize primitive
    (`Trainer.resize_mesh` redistributes live state between the two
    plans' shardings via shard/redistribute.py).
    """

    def __init__(self, mesh, rules=None, data_axis=None):
        if not isinstance(mesh, Mesh):
            mesh = as_mesh(mesh)
        self.mesh = mesh
        self.rules = tuple(rules if rules is not None
                           else _rules.DEFAULT_RULES)
        # fail fast on bad rule sets; a string axis override naming an
        # axis this mesh lacks raises HERE (explicit intent — unlike a
        # PartitionSpec's unknown axis, it never downgrades silently)
        _rules.validate_rules(self.rules, mesh=mesh)
        axes = mesh.axis_names
        self.data_axis = data_axis if data_axis is not None else axes[0]
        if self.data_axis not in axes:
            raise MXNetError(f"data_axis {self.data_axis!r} is not an "
                             f"axis of the mesh {axes}")
        self._cache = {}          # (name, shape) -> PartitionSpec
        self._unmatched = []
        self._fallbacks = []
        self._warned = set()
        # debugging identity (repr/logs); NOT part of signature() — see
        # there for why cache keys are structural
        self.plan_id = next(_plan_seq)
        self._signature = None    # memoized structural signature

    # ------------------------------------------------------- resolution
    def spec_for(self, name, shape):
        """Normalised PartitionSpec for one parameter."""
        key = (name, tuple(int(s) for s in shape))
        spec = self._cache.get(key)
        if spec is None:
            specs, report = _rules.match_partition_rules(
                self.rules, {name: key[1]}, mesh=self.mesh)
            spec = self._cache[key] = specs[name]
            self._unmatched.extend(report["unmatched"])
            self._fallbacks.extend(report["fallbacks"])
            self._check_large_replicated(name, key[1], spec,
                                         report["unmatched"])
        return spec

    def _check_large_replicated(self, name, shape, spec, unmatched):
        """An unmatched (or rule-downgraded) parameter big enough that
        replicating it hurts must REPORT loudly, not vanish into the
        report dict (ISSUE 15: a 10**8-row embedding table a rule typo
        fails to match would silently replicate onto every device and
        OOM at recommender scale — long before anyone reads
        `plan.report()`; ISSUE 16: same story for a ShardedMoE expert
        bank, whose whole point is E/tp experts per device). Once per
        name; threshold via MXTPU_SHARD_WARN_BYTES (0 disables)."""
        if any(e is not None for e in tuple(spec)) or name in self._warned:
            return
        from .._env import env_int
        limit = env_int("MXTPU_SHARD_WARN_BYTES", 64 << 20, minimum=0)
        if not limit:
            return
        # dtype is unknown at rule-resolution time; 4 bytes/element is
        # the fp32 floor (fp16 tables halve it — still the right order)
        nbytes = int(np.prod(shape or (1,), dtype=np.int64)) * 4
        # a TIERED table (shard/tiered.py) keeps only hbm_rows rows per
        # shard on device — the HBM-resident bytes are what an OOM
        # warning should account, not the host-tier full table
        from . import tiered as _tiered
        hbm = _tiered.hbm_rows_for(name)
        if hbm is not None and shape and shape[0] > hbm:
            nbytes = int(hbm) * int(np.prod(shape[1:] or (1,),
                                            dtype=np.int64)) * 4
        if nbytes < limit:
            return
        self._warned.add(name)
        why = ("no partition rule matched" if name in unmatched
               else "its rule downgraded to replicated "
                    "(non-divisible dim or unknown axis)")
        kind = ("expert bank"
                if _rules.re.search(_rules.EXPERT_WEIGHT_PATTERN, name)
                else "parameter")
        warnings.warn(
            f"shard plan replicates {kind} {name!r} (~{nbytes >> 20} "
            f"MiB per device): {why}. At this size replication is "
            f"probably an OOM, not a layout choice — add or fix a rule "
            f"(shard.DEFAULT_RULES row-shards '*embed*_weight' over "
            f"'tp' and routes 'expert*_weight' to 'tp'; see "
            f"docs/PERFORMANCE.md \"Sharded embeddings\" / \"Expert "
            f"parallelism\"). Silence with MXTPU_SHARD_WARN_BYTES=0.",
            RuntimeWarning, stacklevel=4)

    def sharding(self, name, shape):
        return NamedSharding(self.mesh, self.spec_for(name, shape))

    def state_spec(self, name, param_shape, state_shape):
        """Spec for one optimizer-state leaf of a parameter: elementwise
        state (same shape as the weight) rides the weight's spec; scalars
        and shape-mismatched state replicate."""
        if tuple(state_shape) == tuple(param_shape):
            return self.spec_for(name, param_shape)
        return P()

    def batch_sharding(self):
        """Leading batch dim over the data axis, replicated over the rest
        — the in_spec captured steps compile their batches against and
        what the device prefetcher stages with."""
        return NamedSharding(self.mesh, P(self.data_axis))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    # ------------------------------------------------------- reporting
    def report(self):
        """{"unmatched": [...], "fallbacks": [...]} accumulated across
        every resolution so far (deduplicated, order-preserving)."""
        seen = set()
        unmatched = [n for n in self._unmatched
                     if not (n in seen or seen.add(n))]
        fb, seen_fb = [], set()
        for item in self._fallbacks:
            if item not in seen_fb:
                seen_fb.add(item)
                fb.append(item)
        return {"unmatched": unmatched, "fallbacks": fb}

    def describe(self, named_shapes):
        """Resolve {name: shape-bearing} eagerly; returns {name: spec}."""
        return {name: self.spec_for(name, tuple(getattr(v, "shape", v)))
                for name, v in named_shapes.items()}

    def param_bytes_per_device(self, named_arrays):
        """(per_device_bytes, total_bytes) this plan's layout costs for a
        {name: array} set — the dp/tp shard-factor savings the bench and
        acceptance tests assert on."""
        per_dev = total = 0
        for name, a in named_arrays.items():
            data = getattr(a, "_data", a)
            nbytes = int(np.prod(data.shape or (1,))) * \
                np.dtype(data.dtype).itemsize
            spec = self.spec_for(name, data.shape)
            factor = 1
            for entry in tuple(spec):
                if entry is not None:
                    factor *= _rules._axis_size(self.mesh, entry)
            total += nbytes
            per_dev += nbytes // factor
        return per_dev, total

    def with_mesh(self, mesh):
        """Same rules + data axis over a different mesh (the elastic
        resize target). The new mesh must name the data axis."""
        mesh = as_mesh(mesh)
        return ShardPlan(mesh, rules=self.rules, data_axis=self.data_axis)

    # executable cache key: a STRUCTURAL fingerprint — rules + data axis
    # + mesh axes/shape + the exact device ids in mesh order. Two plans
    # with the same fingerprint resolve every parameter to the same
    # NamedSharding, so a compiled step is reusable between them. This
    # is what makes an elastic shrink → grow-back round trip
    # (fault/supervisor.py) land back on the ORIGINAL executables
    # instead of recompiling the whole step: the regrown plan is a new
    # object, but its fingerprint equals the pre-shrink plan's. (An
    # object-identity plan_id here — the pre-PR-18 scheme — forced that
    # recompile; jax Mesh/NamedSharding equality is itself structural,
    # so keying structurally is sound.)
    def signature(self):
        sig = self._signature
        if sig is None:
            import json as _json
            rules_fp = _json.dumps(_rules.rules_to_json(self.rules),
                                   sort_keys=True)
            sig = self._signature = (
                rules_fp, self.data_axis,
                tuple(self.mesh.axis_names),
                tuple(int(self.mesh.shape[a])
                      for a in self.mesh.axis_names),
                tuple(int(d.id) for d in self.mesh.devices.flatten()))
        return sig

    def __repr__(self):
        shape = dict(self.mesh.shape)
        return (f"ShardPlan(mesh={shape}, rules={len(self.rules)}, "
                f"data_axis={self.data_axis!r})")


def plan(mesh=None, rules=None, data_axis=None, devices=None):
    """Build a `ShardPlan`. `mesh` may be a Mesh, an {axis: size} dict,
    a (dp, tp) tuple, or None — None builds the canonical 2-D mesh with
    every visible device on 'dp' and tp=1."""
    if mesh is None:
        mesh = make_mesh_2d(dp=len(devices or jax.devices()), tp=1,
                            devices=devices)
    else:
        mesh = as_mesh(mesh, devices=devices)
    return ShardPlan(mesh, rules=rules, data_axis=data_axis)
