"""im2rec: build RecordIO packs from image directories (reference:
tools/im2rec.py — same .lst / .rec / .idx formats, PIL instead of OpenCV).

Usage:
    python tools/im2rec.py PREFIX IMAGE_ROOT --list     # write PREFIX.lst
    python tools/im2rec.py PREFIX IMAGE_ROOT            # .lst -> .rec/.idx

The .lst format matches the reference: ``index\\tlabel\\trelative/path``.
Labels come from sorted subdirectory names (one class per subdir), like the
reference's --recursive mode.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# host-side tool: never touch the TPU (the axon sitecustomize would try to
# grab the chip on import otherwise)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix, root):
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    entries = []
    if classes:
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(EXTS):
                    entries.append((label_of[c], os.path.join(c, fn)))
    else:  # flat directory, label 0
        for fn in sorted(os.listdir(root)):
            if fn.lower().endswith(EXTS):
                entries.append((0, fn))
    lst_path = prefix + ".lst"
    with open(lst_path, "w") as f:
        for i, (label, rel) in enumerate(entries):
            f.write(f"{i}\t{float(label)}\t{rel}\n")
    print(f"wrote {len(entries)} entries to {lst_path}")
    return lst_path


def read_list(lst_path):
    with open(lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) >= 3:
                yield int(parts[0]), float(parts[1]), parts[-1]


def make_rec(prefix, root, quality=95, resize=None):
    import numpy as np
    from PIL import Image
    from mxnet_tpu import recordio

    lst_path = prefix + ".lst"
    if not os.path.exists(lst_path):
        make_list(prefix, root)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    for idx, label, rel in read_list(lst_path):
        img = Image.open(os.path.join(root, rel)).convert("RGB")
        if resize:
            w, h = img.size
            scale = resize / min(w, h)
            img = img.resize((int(round(w * scale)), int(round(h * scale))))
        header = recordio.IRHeader(0, label, idx, 0)
        fmt = ".png" if rel.lower().endswith(".png") else ".jpg"
        rec.write_idx(idx, recordio.pack_img(header, np.asarray(img),
                                             quality=quality, img_fmt=fmt))
        n += 1
    rec.close()
    print(f"packed {n} images into {prefix}.rec (+.idx)")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="only generate the .lst file")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=None,
                    help="resize shorter edge to this many pixels")
    args = ap.parse_args()
    if args.list:
        make_list(args.prefix, args.root)
    else:
        make_rec(args.prefix, args.root, args.quality, args.resize)


if __name__ == "__main__":
    main()
