"""Upstream-MXNet binary ``.params`` interop (reference:
src/ndarray/ndarray.cc NDArray::Save/Load, src/c_api MXNDArraySave/Load,
python/mxnet/model.py load_checkpoint).

The reference serialises NDArray lists with a dmlc::Stream layout; real
deployments have years of ``model-0000.params`` files in it. This module
reads and writes that layout so upstream checkpoints load straight into
mxnet_tpu nets (and ours export back). Dense tensors only — sparse storage
is a documented divergence (SURVEY §8).

Wire layout (all little-endian):

  list file      := [u64 0x112 magic][u64 reserved]
                    [u64 N][N x ndarray][u64 K][K x string]
  string         := [u64 len][bytes]                (dmlc string save)
  ndarray        := [u32 version magic]
                    V3 (0xF993FACA): [i32 stype]  (0 = dense; others
                                                   rejected)
                    [shape][i32 dev_type][i32 dev_id][i32 type_flag]
                    [raw bytes, C order]
  shape          := [u32 ndim][ndim x i64]          (V2/V3; V1 uses u32
                                                     dims; pre-magic
                                                     legacy: the first u32
                                                     IS ndim, u32 dims)

``arg:``/``aux:`` key prefixes follow the reference Module checkpoint
convention (model.py:save_checkpoint); gluon ``.params`` files carry bare
block-scoped names (e.g. ``resnetv10_conv2d0_weight``).
"""
from __future__ import annotations

import struct

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = ["save_params", "load_params", "load_checkpoint_params",
           "load_params_into"]

_LIST_MAGIC = 0x112
_V1 = 0xF993FAC8   # u32 dims
_V2 = 0xF993FAC9   # i64 dims
_V3 = 0xF993FACA   # + i32 storage type
_DTYPE_OF_FLAG = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
                  4: np.int32, 5: np.int8, 6: np.int64}
try:  # flag 12 = kBfloat16 (mshadow/base.h), present in upstream >= 1.6
    import ml_dtypes as _mld
    _DTYPE_OF_FLAG[12] = _mld.bfloat16
except ImportError:  # pragma: no cover
    pass
_FLAG_OF_DTYPE = {np.dtype(v): k for k, v in _DTYPE_OF_FLAG.items()}


class _Reader:
    def __init__(self, data):
        self.data = data
        self.pos = 0

    def take(self, n):
        if self.pos + n > len(self.data):
            raise MXNetError("truncated upstream .params file")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]

    def i64(self):
        return struct.unpack("<q", self.take(8))[0]


def _read_ndarray(r):
    first = r.u32()
    if first == _V3:
        stype = r.i32()
        if stype != 0:
            raise MXNetError(f"sparse storage type {stype} not supported "
                             "on TPU (dense only; SURVEY §8)")
        ndim = r.u32()
        shape = tuple(r.i64() for _ in range(ndim))
    elif first == _V2:
        ndim = r.u32()
        shape = tuple(r.i64() for _ in range(ndim))
    elif first == _V1:
        ndim = r.u32()
        shape = tuple(r.u32() for _ in range(ndim))
    else:
        # pre-magic legacy: `first` IS ndim (u32 dims)
        ndim = first
        if ndim > 32:
            raise MXNetError(f"unrecognised ndarray magic {first:#x}")
        shape = tuple(r.u32() for _ in range(ndim))
    r.i32()  # dev_type — arrays always load to the default device here
    r.i32()  # dev_id
    type_flag = r.i32()
    if type_flag not in _DTYPE_OF_FLAG:
        raise MXNetError(f"unknown type_flag {type_flag}")
    dtype = np.dtype(_DTYPE_OF_FLAG[type_flag])
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    raw = r.take(size * dtype.itemsize)
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def _write_ndarray(out, arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _FLAG_OF_DTYPE:
        # no silent float32 coercion: a round trip must preserve values
        # AND dtype semantics (the reference errors the same way)
        supported = sorted(str(np.dtype(v)) for v in _DTYPE_OF_FLAG.values())
        raise MXNetError(f"dtype {arr.dtype} has no upstream type_flag; "
                         f"supported: {supported}")
    out.append(struct.pack("<I", _V2))
    out.append(struct.pack("<I", arr.ndim))
    for d in arr.shape:
        out.append(struct.pack("<q", d))
    out.append(struct.pack("<ii", 1, 0))  # cpu(0), like reference saves
    out.append(struct.pack("<i", _FLAG_OF_DTYPE[arr.dtype]))
    out.append(arr.tobytes())


def save_params(fname, data):
    """Write a dict (or list) of NDArrays in the upstream binary layout
    (reference: MXNDArraySave). Dict keys become the saved names."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names, arrays = [], list(data)
    out = [struct.pack("<QQ", _LIST_MAGIC, 0),
           struct.pack("<Q", len(arrays))]
    for a in arrays:
        _write_ndarray(out, a.asnumpy() if hasattr(a, "asnumpy") else a)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode("utf-8")
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    with open(fname, "wb") as f:
        f.write(b"".join(out))
    return fname


def load_params(fname):
    """Read an upstream .params file: dict when names are present, else a
    list (reference: MXNDArrayLoad return convention)."""
    with open(fname, "rb") as f:
        r = _Reader(f.read())
    if r.u64() != _LIST_MAGIC:
        raise MXNetError(f"{fname}: not an upstream NDArray list file "
                         "(bad magic)")
    r.u64()  # reserved
    n = r.u64()
    arrays = [array(_read_ndarray(r)) for _ in range(n)]
    k = r.u64()
    names = []
    for _ in range(k):
        ln = r.u64()
        names.append(r.take(ln).decode("utf-8"))
    if not names:
        return arrays
    if len(names) != len(arrays):
        raise MXNetError(f"{fname}: {len(names)} names for {len(arrays)} "
                         "arrays")
    return dict(zip(names, arrays))


def load_checkpoint_params(fname):
    """Split a Module-style checkpoint into (arg_params, aux_params) by the
    'arg:'/'aux:' key prefixes (reference: model.py load_checkpoint)."""
    loaded = load_params(fname)
    if not isinstance(loaded, dict):
        raise MXNetError(f"{fname} has no names; not a checkpoint")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def _shape_known(s):
    """False for deferred-init shapes (None or 0-dims): those finalise
    from the loaded data instead of being checked against it."""
    return s is not None and all(d for d in s)


def _strip_scope(name):
    """Drop the leading block-scope prefix (`resnetv10_`, `mobilenet0_`,
    ...) so checkpoints from a differently-numbered scope still match:
    upstream and mxnet_tpu both auto-name scopes with a global counter, so
    the same architecture saved in different processes differs only there.
    Strips the first segment only when it is digit-bearing AND the tail
    still carries a digit-bearing segment (the layer identity):
    `net0_batchnorm0_running_mean` -> `batchnorm0_running_mean`, but the
    bare layer names `conv2d0_weight` / `batchnorm0_running_mean` are left
    intact — the layer counter, not a scope, carries their identity."""
    head, _, tail = name.partition("_")
    if tail and any(c.isdigit() for c in head) and \
            any(c.isdigit() for seg in tail.split("_") for c in seg):
        return tail
    return name


def load_params_into(block, fname, name_map=None, allow_missing=False,
                     ignore_extra=False):
    """Load an upstream .params (gluon save_parameters or Module
    checkpoint) into a Block. Matching order per target param: explicit
    `name_map` (upstream name per OUR name), exact name, scope-stripped
    name; every match is shape-checked. Returns the list of our param
    names that were set (reference: gluon Block.load_parameters +
    model_zoo model_store loading)."""
    arg_params, aux_params = load_checkpoint_params(fname)
    merged = {**arg_params, **aux_params}
    file_order = list(merged)
    stripped = {}
    for k in merged:
        stripped.setdefault(_strip_scope(k), []).append(k)
    params = block.collect_params()
    name_map = name_map or {}

    # Phase 1: resolve every target by name (name_map > exact > stripped)
    # WITHOUT consuming anything, so a later fallback cannot be steered by
    # a stale table.
    mapping, unresolved = {}, []
    mismatch_msg = None
    for ours in params:
        explicit = ours in name_map
        if explicit:
            src = name_map[ours]
            if src not in merged:
                raise MXNetError(f"name_map: {src!r} not in {fname}")
        elif ours in merged:
            src = ours
        else:
            cands = stripped.get(_strip_scope(ours), [])
            if len(cands) > 1:
                raise MXNetError(
                    f"ambiguous match for {ours!r} in {fname}: {cands}; "
                    "disambiguate via name_map")
            src = cands[0] if cands else None
        if src is not None and _shape_known(params[ours].shape) and \
                tuple(params[ours].shape) != tuple(merged[src].shape):
            msg = (f"shape mismatch for {ours!r}: param "
                   f"{tuple(params[ours].shape)} vs file "
                   f"{tuple(merged[src].shape)}")
            if explicit:
                raise MXNetError(msg)  # the user pinned this pairing
            # an implicit name hit with the wrong shape is counter drift,
            # not a verdict: let the positional fallback try; re-raise
            # this (better diagnostic) if it can't
            mismatch_msg = mismatch_msg or msg
            src = None
        if src is None:
            unresolved.append(ours)
        else:
            mapping[ours] = src

    # Phase 2: if names could not resolve everything, fall back to ORDERED
    # positional matching for the WHOLE file (a consistent bijection, only
    # when counts match and every shape agrees in order). Covers
    # layer-counter drift (`conv2d1_weight` net vs `conv2d0_weight` file:
    # the same architecture built twice in one process shifts the
    # NameManager counters — upstream has the identical behaviour).
    if unresolved:
        ours_order = list(params)
        def _suffix(n):
            return n.rsplit("_", 1)[-1]

        # positional bijection needs evidence it is the SAME architecture:
        # ordered shapes agree wherever our shape is known, and every pair
        # agrees on the parameter-kind suffix (weight/bias/gamma/...) —
        # without the suffix guard a fully deferred-shape net would zip
        # against any same-count checkpoint
        if len(file_order) == len(ours_order) and all(
                (not _shape_known(params[o].shape) or
                 tuple(params[o].shape) == tuple(merged[s].shape)) and
                _suffix(o) == _suffix(s)
                for o, s in zip(ours_order, file_order)):
            mapping = dict(zip(ours_order, file_order))
        elif not allow_missing:
            raise MXNetError(
                mismatch_msg or
                f"no parameter for {unresolved[0]!r} in {fname} "
                "(pass allow_missing=True to skip)")
        else:
            for ours in unresolved:
                mapping.pop(ours, None)

    # duplicate targets would silently drop data
    taken = {}
    for ours, src in mapping.items():
        if src in taken:
            raise MXNetError(f"{src!r} in {fname} matched both "
                             f"{taken[src]!r} and {ours!r}; use name_map")
        taken[src] = ours

    loaded = []
    for ours, p in params.items():
        src = mapping.get(ours)
        if src is None:
            continue
        v = merged.pop(src)
        if _shape_known(p.shape) and tuple(p.shape) != tuple(v.shape):
            raise MXNetError(f"shape mismatch for {ours!r}: param "
                             f"{tuple(p.shape)} vs file {tuple(v.shape)}")
        p.set_data(v)  # finalises deferred-shape params from the data
        loaded.append(ours)
    if merged and not ignore_extra:
        raise MXNetError(f"extra parameters in {fname}: "
                         f"{sorted(merged)[:8]}... (pass ignore_extra=True)")
    return loaded
