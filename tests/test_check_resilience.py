"""Recovery-supervisor chaos soak wired into tier-1 (ISSUE 10
acceptance): every failure domain — transient, corrupt-state, hang,
capacity loss, preemption — must auto-recover without process death,
with bitwise parity where the policy promises it, a structured crash
report on restart-budget exhaustion, and zero leaked engine tasks /
task groups / checkpoint tmp dirs. Same pattern as chaos_check /
check_dispatch; the capacity-loss phase skips cleanly under 2 devices."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_resilience  # noqa: E402


def test_resilience_soak(tmp_path):
    res = check_resilience.run(str(tmp_path), seed=0, steps=14)
    assert res["parity"] == "bitwise"
    # every parity domain recovered at least once
    for domain in ("transient", "corrupt_state", "hang", "preemption"):
        assert res["recoveries"][domain] >= 1, (domain, res)
    # conftest forks 8 CPU devices, so the sharded capacity phase RAN
    # (not skipped) and genuinely shrank the mesh to the survivors
    assert res["capacity"]["survivor_mesh"] == {"dp": 1, "tp": 1}
    assert res["recoveries"]["capacity_loss"] >= 1
    # ... and the fleet phase RAN on the 8-device mesh: a (2,2) job lost
    # a device, trained shrunk on (1,2), then regrew to the original
    # layout when the device returned — with the budget refilled and the
    # resize round trip pinned bitwise inside the tool
    assert res["fleet"]["regrown_mesh"] == {"dp": 2, "tp": 2}
    assert res["recoveries"]["capacity_gain"] >= 1
    # the rollback consulted the last-known-good journal (an intact but
    # unhealthy checkpoint was skipped) and the torn resume candidate
    # was checksum-rejected
    assert res["delta_unhealthy_skips"] >= 1
    assert res["delta_checkpoint_fallbacks"] >= 1
    # budget exhaustion produced the structured crash report
    for field in ("reason", "domain", "incidents", "metrics",
                  "engine_pending"):
        assert field in res["crash_report_fields"]


def test_resilience_cli_smoke():
    """The argv surface parses (no run: that is the test above)."""
    assert callable(check_resilience.main)
    assert check_resilience.N_BATCHES >= 4


import pytest


@pytest.fixture(autouse=True)
def _clean_faults():
    """A failing soak phase must not leave armed faults/preemption state
    for the rest of the session (the tool also cleans up in a finally;
    this is the second belt)."""
    yield
    from mxnet_tpu import fault
    fault.clear()
    fault.reset_preemption(clear_callbacks=True)
    fault.uninstall_preemption_handler()
