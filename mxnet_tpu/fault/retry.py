"""Reusable retry with exponential backoff, jitter and a deadline
(reference capability: the ps-lite van's resend/timeout loop + dmlc-core's
retrying IO streams, re-designed as one policy object).

`RetryPolicy.call(fn)` retries `fn` on the configured exception types with
``delay = min(max_delay, base_delay * multiplier**attempt)`` scaled by a
uniform jitter factor in ``[1-jitter, 1+jitter]``; a total wall-clock
`deadline` bounds the whole attempt train (a retry that would overrun the
deadline is not slept for — the last error re-raises instead).

Jitter draws from a ``seed``-able RNG so schedules are deterministic in
tests. `Preempted` / `KeyboardInterrupt` / `SystemExit` never retry —
a preemption must win over any retry loop.

Each performed retry counts into ``fault_retries{site=<name>}``; giving
up after exhausting retries counts into ``fault_retry_giveups{site=}``.

Env-tunable site defaults via `policy_from_env(prefix)`:
``<PREFIX>_RETRIES`` / ``<PREFIX>_RETRY_BASE`` / ``<PREFIX>_RETRY_MAX`` /
``<PREFIX>_RETRY_DEADLINE`` — e.g. ``MXTPU_IO_RETRIES=5``.
"""
from __future__ import annotations

import random
import time

from .._env import env_float as _env_float_knob
from .._env import env_int as _env_int_knob
# back-compat aliases: tests (and kvstore's regression suite) reach the
# one-warning-per-key set through this module's historical names
from .._env import _warned as _warned_env            # noqa: F401
from ..observability import registry as _obs_registry

__all__ = ["RetryPolicy", "retry_call", "policy_from_env"]

_reg = _obs_registry()


def _never_retry():
    from .preemption import Preempted
    return (Preempted, KeyboardInterrupt, SystemExit)


class RetryPolicy:
    def __init__(self, max_retries=4, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.5, deadline=None,
                 retry_on=(Exception,), seed=None, name="retry",
                 sleep=time.sleep):
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = None if deadline is None else float(deadline)
        self.retry_on = tuple(retry_on)
        self.name = name
        self._rng = random.Random(seed) if seed is not None else random
        self._sleep = sleep
        self._retries = _reg.counter("fault_retries", site=name)
        self._giveups = _reg.counter("fault_retry_giveups", site=name)

    def delay(self, attempt):
        """Backoff before retry number `attempt` (1-based), jittered."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def call(self, fn, *args, **kwargs):
        """Run fn(*args, **kwargs), retrying per the policy. Re-raises the
        last error when retries/deadline are exhausted."""
        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except _never_retry():
                raise
            except self.retry_on:
                attempt += 1
                if attempt > self.max_retries:
                    self._giveups.inc()
                    raise
                d = self.delay(attempt)
                if self.deadline is not None and \
                        time.monotonic() - t0 + d > self.deadline:
                    self._giveups.inc()
                    raise
                self._retries.inc()
                if d:
                    self._sleep(d)

    def wrap(self, fn):
        """Decorator form: `policy.wrap(fn)` retries every call."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        return wrapped


def retry_call(fn, *args, policy=None, **kwargs):
    """Convenience: `retry_call(fn, a, b, policy=RetryPolicy(...))`."""
    return (policy or RetryPolicy()).call(fn, *args, **kwargs)


def _env_float(key, default):
    """Historical entry point (the parser itself now lives in
    `mxnet_tpu._env`, shared by every subsystem): non-negative finite
    float with the one-warning-per-key fallback."""
    return _env_float_knob(key, default, minimum=0.0)


def policy_from_env(prefix, max_retries=4, base_delay=0.05, max_delay=2.0,
                    deadline=30.0, name=None, **kw):
    """A RetryPolicy whose knobs read ``<prefix>_RETRIES`` /
    ``_RETRY_BASE`` / ``_RETRY_MAX`` / ``_RETRY_DEADLINE`` env overrides.
    ``<prefix>_RETRIES=0`` disables retrying at that site. Malformed
    values fall back to the defaults with a one-time warning (see
    `mxnet_tpu._env`)."""
    return RetryPolicy(
        max_retries=_env_int_knob(f"{prefix}_RETRIES", int(max_retries),
                                  minimum=0),
        base_delay=_env_float(f"{prefix}_RETRY_BASE", base_delay),
        max_delay=_env_float(f"{prefix}_RETRY_MAX", max_delay),
        deadline=_env_float(f"{prefix}_RETRY_DEADLINE", deadline),
        name=name or prefix.lower().replace("mxtpu_", ""), **kw)
