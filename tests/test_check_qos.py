"""Engine QoS gate wired into tier-1 (ISSUE 7 acceptance): mixed
serve+train load with injected faults and mid-flight group cancellation
must show zero decode-class turns starved past the aging bound, bitwise-
stable decode output, and zero leaked KV pages / task groups / staging
slots (same pattern as chaos_check / check_dispatch / check_trace)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_qos  # noqa: E402


def test_qos_fairness_and_chaos_soak():
    res = check_qos.run()
    assert res["ok"], res["errors"]
    # both engine implementations passed the deterministic fairness pin
    assert set(res["fairness_engines"]) >= {"py"}
    # the FIFO control PROVES the starvation bound bites: without QoS the
    # same flood blows it, with QoS zero turns cross it
    assert res["fifo_control_worst_wait_s"] > res["starve_bound_s"]
    assert res["soak_starved_turns"] == 0
    assert res["soak_probe_turns"] > 0
    assert res["decode_dispatch_p99_s"] < res["starve_bound_s"]
    # leak gates: pages, groups (staging depth asserted inside run())
    assert res["soak_leaked_pages"] == 0
    assert res["soak_live_groups"] == 0


def test_check_qos_cli_smoke():
    assert callable(check_qos.main)
    assert check_qos.STARVE_BOUND_S > 0
