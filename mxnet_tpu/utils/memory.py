"""Per-device memory statistics (SURVEY.md §2 #10).

Reference parity: the reference exposes the storage manager's pool state via
`mx.context.gpu_memory_info(dev_id)` (python/mxnet/context.py backed by
src/storage/storage.cc). On TPU the PJRT runtime owns HBM, so the equivalent
surface is `jax.Device.memory_stats()`; this module normalises it into the
reference's (free, total) contract plus a richer stats dict.

Platforms whose PJRT client doesn't implement memory_stats (notably the CPU
test backend) get a psutil/os-based host-memory fallback so the API is
always usable.
"""
from __future__ import annotations

import os

import jax

from ..base import MXNetError

__all__ = ["memory_info", "memory_stats", "gpu_memory_info"]


def _host_memory():
    """(free, total) bytes of host RAM — fallback for backends without
    PJRT memory stats (e.g. the CPU test mesh)."""
    try:
        total = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return 0, 0
    avail = total
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                    break
    except OSError:
        pass
    return avail, total


# HBM per chip for TPU generations whose PJRT client (e.g. the axon tunnel)
# doesn't implement memory_stats(); keyed by substring of device_kind.
_HBM_TABLE = (
    ("v5 lite", 16 << 30), ("v5e", 16 << 30), ("v5p", 95 << 30),
    ("v4", 32 << 30), ("v3", 16 << 30), ("v2", 8 << 30), ("v6", 32 << 30),
)


def _hbm_from_kind(kind):
    kind = (kind or "").lower()
    for sub, size in _HBM_TABLE:
        if sub in kind:
            return size
    return 0


def _resolve_device(ctx_or_id=0):
    from ..context import Context
    if isinstance(ctx_or_id, Context):
        return ctx_or_id.jax_device
    if isinstance(ctx_or_id, jax.Device):
        return ctx_or_id
    devs = jax.devices()
    i = int(ctx_or_id)
    if i >= len(devs):
        raise MXNetError(f"device {i} not available ({len(devs)} visible)")
    return devs[i]


def memory_stats(ctx_or_id=0):
    """Raw per-device memory stats dict. Keys follow PJRT
    (`bytes_in_use`, `bytes_limit`, `peak_bytes_in_use`, ...); backends
    without PJRT stats report {'bytes_in_use': 0, 'bytes_limit': <host>}."""
    dev = _resolve_device(ctx_or_id)
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats:
        return dict(stats)
    if dev.platform != "cpu":
        hbm = _hbm_from_kind(getattr(dev, "device_kind", ""))
        if hbm:
            return {"bytes_in_use": 0, "bytes_limit": hbm,
                    "source": "device_kind table (PJRT stats unavailable)"}
    free, total = _host_memory()
    return {"bytes_in_use": max(total - free, 0), "bytes_limit": total,
            "source": "host"}


def memory_info(ctx_or_id=0):
    """(free_bytes, total_bytes) for a device — the reference's
    `gpu_memory_info` contract."""
    s = memory_stats(ctx_or_id)
    total = int(s.get("bytes_limit") or s.get("bytes_reservable_limit") or 0)
    used = int(s.get("bytes_in_use") or 0)
    return max(total - used, 0), total


# reference-named alias
gpu_memory_info = memory_info
