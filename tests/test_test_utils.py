"""mx.test_utils: the public testing surface (reference:
python/mxnet/test_utils.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, test_utils


def test_assert_almost_equal():
    test_utils.assert_almost_equal(nd.ones((2, 2)), np.ones((2, 2)))
    with pytest.raises(AssertionError):
        test_utils.assert_almost_equal(nd.ones((2, 2)),
                                       np.ones((2, 2)) + 0.1)
    with pytest.raises(AssertionError):  # shape mismatch
        test_utils.assert_almost_equal(nd.ones((2,)), np.ones((3,)))
    assert test_utils.almost_equal([1.0], [1.0 + 1e-9])
    assert test_utils.same([1, 2], [1, 2])


def test_rand_helpers():
    s = test_utils.rand_shape_nd(4, dim=5)
    assert len(s) == 4 and all(1 <= d <= 5 for d in s)
    x = test_utils.rand_ndarray((3, 4))
    assert x.shape == (3, 4) and x.dtype == np.float32


def test_check_numeric_gradient_catches_wrong_backward():
    """The checker passes a correct op and fails a deliberately-wrong
    custom gradient (the reference uses it exactly this way)."""
    test_utils.check_numeric_gradient(
        lambda a, b: (a * b).tanh(), [np.random.RandomState(0).rand(3, 2),
                                      np.random.RandomState(1).rand(3, 2)])

    from mxnet_tpu import autograd

    class BadGrad(autograd.Function):
        def forward(self, x):
            return x * x

        def backward(self, dy):
            return dy * 3.14  # wrong on purpose (should be 2x*dy)

    with pytest.raises(AssertionError):
        test_utils.check_numeric_gradient(
            lambda a: BadGrad()(a), [np.random.RandomState(2).rand(4)])


def test_check_symbolic_forward_backward():
    a = sym.Variable("a")
    b = sym.Variable("b")
    out = a * b + a
    a_np = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    b_np = np.array([[2.0, 2.0], [0.5, 1.0]], np.float32)
    test_utils.check_symbolic_forward(out, {"a": a_np, "b": b_np},
                                      [a_np * b_np + a_np])
    og = np.ones_like(a_np)
    test_utils.check_symbolic_backward(out, {"a": a_np, "b": b_np}, [og],
                                       {"a": b_np + 1.0, "b": a_np})


def test_default_context_override():
    orig = test_utils.default_context()
    try:
        test_utils.set_default_context(mx.cpu(0))
        assert test_utils.default_context().device_type == "cpu"
    finally:
        test_utils.set_default_context(None)
    assert test_utils.default_context() == orig


def test_get_mnist_trains():
    """The synthetic MNIST must be learnable (convergence smoke contract)."""
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    data = test_utils.get_mnist()
    assert data["train_data"].shape == (512, 1, 28, 28)
    net = nn.HybridSequential()
    net.add(nn.Flatten(), nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = nd.array(data["train_data"])
    y = nd.array(data["train_label"])
    for _ in range(30):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
    pred = net(nd.array(data["test_data"])).asnumpy().argmax(1)
    acc = (pred == data["test_label"]).mean()
    assert acc > 0.9, f"synthetic mnist should be learnable, acc={acc}"


def test_download_local_and_offline(tmp_path):
    import os
    import pytest as _pytest
    src = os.path.join(tmp_path, "src.txt")
    with open(src, "w") as f:
        f.write("payload")
    dst = mx.test_utils.download("file://" + src,
                                 fname=os.path.join(tmp_path, "dst.txt"))
    assert open(dst).read() == "payload"
    with _pytest.raises(mx.base.MXNetError, match="network"):
        mx.test_utils.download("http://example.com/x.bin")


def test_registry_factories_and_aliases():
    """mx.registry factories build a working register/alias/create trio;
    mx.kv and mx.img are the reference namespace aliases."""
    class Base:
        pass
    reg = mx.registry.get_register_func(Base, "thing")
    alias = mx.registry.get_alias_func(Base, "thing")
    create = mx.registry.get_create_func(Base, "thing")

    @alias("widget")
    class MyThing(Base):
        def __init__(self, x=1):
            self.x = x
    reg(MyThing)
    t = create("widget", x=5)
    assert isinstance(t, MyThing) and t.x == 5
    assert create("mything").x == 1
    with pytest.raises(mx.base.MXNetError):
        create("nope")
    with pytest.raises(mx.base.MXNetError):
        reg(int)

    assert mx.kv is mx.kvstore and mx.img is mx.image
    logger = mx.log.get_logger("t_mxlog", level=mx.log.INFO)
    assert logger.level == mx.log.INFO
    ops = mx.operator.get_all_registered_operators()
    assert "Convolution" in ops and "dot" in ops   # built-ins included
    assert set(mx.operator._registry) <= set(ops)
    assert mx.test_utils.list_gpus() == mx.test_utils.list_tpus()


def test_download_dirname_creates_directory(tmp_path):
    import os
    src = os.path.join(tmp_path, "payload.bin")
    with open(src, "wb") as f:
        f.write(b"abc")
    out_dir = os.path.join(tmp_path, "fresh_dir")
    dst = mx.test_utils.download("file://" + src, dirname=out_dir)
    assert dst == os.path.join(out_dir, "payload.bin")
    assert os.path.isdir(out_dir) and open(dst, "rb").read() == b"abc"


def test_load_frombuffer_roundtrip(tmp_path):
    import os
    f = os.path.join(tmp_path, "arrs")
    mx.nd.save(f, {"w": nd.arange(4)})
    from mxnet_tpu import engine
    engine.wait_for_all()
    with open(f + ".npz", "rb") as fh:
        out = mx.nd.load_frombuffer(fh.read())
    np.testing.assert_allclose(out["w"].asnumpy(), [0, 1, 2, 3])


def test_download_fname_plus_dirname_compose(tmp_path):
    import os
    src = os.path.join(tmp_path, "s.bin")
    open(src, "wb").write(b"q")
    dst = mx.test_utils.download("file://" + src, fname="renamed.bin",
                                 dirname=os.path.join(tmp_path, "sub"))
    assert dst == os.path.join(tmp_path, "sub", "renamed.bin")
    assert open(dst, "rb").read() == b"q"


def test_log_second_filename_attaches(tmp_path):
    import os
    f1, f2 = os.path.join(tmp_path, "a.log"), os.path.join(tmp_path, "b.log")
    lg = mx.log.get_logger("t_mxlog2", filename=f1, level=mx.log.INFO)
    lg = mx.log.get_logger("t_mxlog2", filename=f2, level=mx.log.INFO)
    lg.info("hello")
    for h in lg.handlers:
        h.flush()
    assert "hello" in open(f2).read()


def test_parse_log_tool(tmp_path):
    """tools/parse_log.py parses Speedometer/validation lines (reference
    tools/parse_log.py contract)."""
    import os
    import subprocess
    import sys as _sys
    log = os.path.join(tmp_path, "train.log")
    with open(log, "w") as f:
        f.write(
            "INFO:root:Epoch[0] Batch [50] Speed: 2500.00 samples/sec\t"
            "accuracy=0.800000\n"
            "INFO:root:Epoch[0] Batch [100] Speed: 2700.00 samples/sec\t"
            "accuracy=0.850000\n"
            "INFO:root:Epoch[0] Validation-accuracy=0.820000\n"
            "INFO:root:Epoch[1] Batch [50] Speed: 2600.00 samples/sec\t"
            "accuracy=0.900000\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "parse_log.py"),
         log, "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    lines = out.stdout.strip().splitlines()
    assert lines[0] == "epoch,speed(avg),train-accuracy,val-accuracy"
    assert lines[1].startswith("0,2600.0,0.85000,0.82000")
    assert lines[2].startswith("1,2600.0,0.90000,nan")
