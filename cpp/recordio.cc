// Native RecordIO reader (reference: dmlc-core/src/recordio.cc +
// include/dmlc/recordio.h — re-implemented for the TPU framework's host
// data path; NOT a translation: mmap + one upfront offset index instead of
// dmlc's stream splitter, because the consumer is a Python DataLoader that
// wants zero-copy random access).
//
// Format (shared with mxnet_tpu/recordio.py):
//   record := [u32 kMagic][u32 lrec][payload][pad to 4B]
//   lrec   := (cflag << 29) | length ; cflag 0 whole, 1/2/3 multi-part
//
// C ABI (ctypes-consumed by mxnet_tpu/recordio.py):
//   MXTPURecOpen(path)            -> handle (nullptr on error)
//   MXTPURecCount(h)              -> int64 number of logical records
//   MXTPURecGet(h, i, &ptr, &len) -> 0 ok / -1 bad index / 1 multipart
//       (ptr points INTO the mmap for single-part records: zero copy)
//   MXTPURecGetCopy(h, i, buf, cap) -> bytes written or <0 (handles
//       multi-part by stitching; call with buf=null to query size)
//   MXTPURecClose(h)
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Part {
  uint64_t off;   // payload offset in file
  uint32_t len;   // payload length
};

struct Record {
  std::vector<Part> parts;  // 1 part for cflag==0 records
  uint64_t total = 0;
};

struct RecFile {
  int fd = -1;
  const uint8_t* base = nullptr;
  uint64_t size = 0;
  std::vector<Record> records;
};

bool BuildIndex(RecFile* f) {
  uint64_t pos = 0;
  Record cur;
  bool in_multi = false;
  while (pos + 8 <= f->size) {
    uint32_t magic, lrec;
    std::memcpy(&magic, f->base + pos, 4);
    std::memcpy(&lrec, f->base + pos + 4, 4);
    if (magic != kMagic) return false;
    const uint32_t cflag = lrec >> 29;
    const uint32_t len = lrec & kLenMask;
    const uint64_t payload = pos + 8;
    if (payload + len > f->size) return false;
    switch (cflag) {
      case 0:
        if (in_multi) return false;
        f->records.push_back({{{payload, len}}, len});
        break;
      case 1:
        if (in_multi) return false;
        in_multi = true;
        cur = Record();
        cur.parts.push_back({payload, len});
        cur.total = len;
        break;
      case 2:
      case 3:
        if (!in_multi) return false;
        cur.parts.push_back({payload, len});
        cur.total += len;
        if (cflag == 3) {
          f->records.push_back(std::move(cur));
          in_multi = false;
        }
        break;
      default:
        return false;
    }
    pos = payload + len + ((4 - len % 4) % 4);
  }
  return !in_multi && pos == f->size;
}

}  // namespace

extern "C" {

void* MXTPURecOpen(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* f = new RecFile();
  f->fd = fd;
  f->size = static_cast<uint64_t>(st.st_size);
  if (f->size > 0) {
    void* m = mmap(nullptr, f->size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
      ::close(fd);
      delete f;
      return nullptr;
    }
    f->base = static_cast<const uint8_t*>(m);
    // the DataLoader reads records in roughly ascending order
    madvise(m, f->size, MADV_WILLNEED);
  }
  if (!BuildIndex(f)) {
    if (f->base) munmap(const_cast<uint8_t*>(f->base), f->size);
    ::close(fd);
    delete f;
    return nullptr;
  }
  return f;
}

int64_t MXTPURecCount(void* h) {
  return static_cast<RecFile*>(h)->records.size();
}

int MXTPURecGet(void* h, int64_t i, const uint8_t** ptr, uint64_t* len) {
  auto* f = static_cast<RecFile*>(h);
  if (i < 0 || static_cast<uint64_t>(i) >= f->records.size()) return -1;
  const Record& r = f->records[i];
  if (r.parts.size() != 1) return 1;  // multipart: use MXTPURecGetCopy
  *ptr = f->base + r.parts[0].off;
  *len = r.parts[0].len;
  return 0;
}

int64_t MXTPURecGetCopy(void* h, int64_t i, uint8_t* buf, uint64_t cap) {
  auto* f = static_cast<RecFile*>(h);
  if (i < 0 || static_cast<uint64_t>(i) >= f->records.size()) return -1;
  const Record& r = f->records[i];
  if (buf == nullptr) return static_cast<int64_t>(r.total);
  if (cap < r.total) return -2;
  uint64_t w = 0;
  for (const Part& p : r.parts) {
    std::memcpy(buf + w, f->base + p.off, p.len);
    w += p.len;
  }
  return static_cast<int64_t>(w);
}

void MXTPURecClose(void* h) {
  auto* f = static_cast<RecFile*>(h);
  if (f->base) munmap(const_cast<uint8_t*>(f->base), f->size);
  if (f->fd >= 0) ::close(f->fd);
  delete f;
}

}  // extern "C"
