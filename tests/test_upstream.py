"""Upstream-MXNet binary .params interop (reference: NDArray::Save/Load,
model.py load_checkpoint): byte-level round trips, legacy version reading,
and loading a whole zoo checkpoint into a net."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, upstream


def test_roundtrip_dict_and_list(tmp_path):
    f = str(tmp_path / "w.params")
    d = {"a": nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)),
         "b": nd.array(np.ones((4,), np.float16)),
         "c": nd.array(np.arange(5, dtype=np.int32))}
    upstream.save_params(f, d)
    out = upstream.load_params(f)
    assert set(out) == {"a", "b", "c"}
    for k in d:
        assert out[k].dtype == d[k].dtype
        np.testing.assert_array_equal(out[k].asnumpy(), d[k].asnumpy())
    # list form: no names block -> list comes back
    f2 = str(tmp_path / "l.params")
    upstream.save_params(f2, [d["a"], d["b"]])
    out2 = upstream.load_params(f2)
    assert isinstance(out2, list) and len(out2) == 2


def _legacy_file(path, version):
    """Hand-craft a one-array file in an older per-array layout."""
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1)]
    if version == "v3":
        out.append(struct.pack("<I", 0xF993FACA))
        out.append(struct.pack("<i", 0))                      # dense stype
        out.append(struct.pack("<I", 2))
        out += [struct.pack("<q", d) for d in arr.shape]
    elif version == "v1":
        out.append(struct.pack("<I", 0xF993FAC8))
        out.append(struct.pack("<I", 2))
        out += [struct.pack("<I", d) for d in arr.shape]
    else:  # pre-magic legacy: first u32 IS ndim
        out.append(struct.pack("<I", 2))
        out += [struct.pack("<I", d) for d in arr.shape]
    out.append(struct.pack("<ii", 1, 0))
    out.append(struct.pack("<i", 0))                          # float32
    out.append(arr.tobytes())
    out.append(struct.pack("<Q", 1))
    out.append(struct.pack("<Q", 1))
    out.append(b"w")
    open(path, "wb").write(b"".join(out))
    return arr


@pytest.mark.parametrize("version", ["v3", "v1", "legacy"])
def test_reads_all_ndarray_versions(tmp_path, version):
    f = str(tmp_path / f"{version}.params")
    arr = _legacy_file(f, version)
    out = upstream.load_params(f)
    np.testing.assert_array_equal(out["w"].asnumpy(), arr)


def test_sparse_stype_rejected(tmp_path):
    f = str(tmp_path / "s.params")
    out = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
           struct.pack("<I", 0xF993FACA), struct.pack("<i", 1)]  # row_sparse
    open(f, "wb").write(b"".join(out))
    with pytest.raises(mx.MXNetError, match="sparse"):
        upstream.load_params(f)


def test_checkpoint_arg_aux_split(tmp_path):
    f = str(tmp_path / "ck.params")
    upstream.save_params(f, {
        "arg:fc_weight": nd.ones((2, 2)),
        "aux:bn_moving_mean": nd.zeros((2,))})
    arg, aux = upstream.load_checkpoint_params(f)
    assert list(arg) == ["fc_weight"] and list(aux) == ["bn_moving_mean"]


def test_zoo_checkpoint_loads_identical_logits(tmp_path):
    """The VERDICT r2 item 8 acceptance: an upstream-format file written
    under a DIFFERENT scope prefix (as another process would produce)
    loads into resnet18_v1 and reproduces the exact logits of direct
    set_data."""
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    src = resnet18_v1(classes=10)
    src.initialize()
    x = nd.random.uniform(shape=(2, 3, 32, 32))
    ref = src(x).asnumpy()

    # simulate an upstream save: gluon-style bare names, different scope
    # counter (resnetv10_ -> resnetv17_), arg/aux split like a Module ckpt
    f = str(tmp_path / "resnet18-0000.params")
    blob = {}
    for k, v in src.collect_params().items():
        kind = "aux" if "running_" in k else "arg"
        blob[f"{kind}:{k.replace('resnetv10_', 'resnetv17_', 1)}"] = v.data()
    upstream.save_params(f, blob)

    dst = resnet18_v1(classes=10)
    dst.initialize()
    dst(x)  # materialise shapes
    assert not np.allclose(dst(x).asnumpy(), ref)
    loaded = upstream.load_params_into(dst, f)
    assert len(loaded) == len(src.collect_params())
    np.testing.assert_allclose(dst(x).asnumpy(), ref, rtol=1e-6)


def test_load_into_shape_mismatch_and_missing(tmp_path):
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4, in_units=3)
    net.initialize()
    f = str(tmp_path / "bad.params")
    upstream.save_params(f, {"weight": nd.ones((5, 3))})
    with pytest.raises(mx.MXNetError, match="shape mismatch"):
        upstream.load_params_into(net, f, name_map={
            list(net.collect_params())[0]: "weight"})
    f2 = str(tmp_path / "other.params")
    upstream.save_params(f2, {"unrelated_tensor": nd.ones((2,))})
    with pytest.raises(mx.MXNetError, match="no parameter"):
        upstream.load_params_into(net, f2)
    assert upstream.load_params_into(net, f2, allow_missing=True,
                                     ignore_extra=True) == []


def test_bn_stats_match_across_scoping(tmp_path):
    """Scoped file into a bare-named net: running_mean/var (multi-segment
    suffixes) must match via scope-strip like gamma/beta do."""
    from mxnet_tpu.gluon import nn
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = nd.random.uniform(shape=(2, 3, 4, 4))
    net(x)
    ours = list(net.collect_params())
    f = str(tmp_path / "bn.params")
    vals = {k: nd.random.uniform(shape=v.shape)
            for k, v in net.collect_params().items()}
    # a scoped save, plus one extra tensor so positional fallback CANNOT
    # kick in — name matching alone must succeed
    blob = {f"model0_{k}": v for k, v in vals.items()}
    blob["model0_unrelated_extra0_weight"] = nd.ones((7,))
    upstream.save_params(f, blob)
    loaded = upstream.load_params_into(net, f, ignore_extra=True)
    assert sorted(loaded) == sorted(ours)
    for k in ours:
        np.testing.assert_allclose(
            net.collect_params()[k].data().asnumpy(), vals[k].asnumpy())


def test_positional_fallback_is_all_or_nothing(tmp_path):
    """A partially-matching file must not crash with a stale positional
    table (regression: KeyError when a name match consumed a key the
    positional table still referenced)."""
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=4), nn.Dense(4, in_units=4))
    net.initialize()
    ours = list(net.collect_params())
    # file where one DRIFTED key collides with a real param name (it
    # holds a different position's tensor) while the ordered shape+suffix
    # sequence still aligns -> the consistent positional bijection must
    # win over the stale name match, with no KeyError
    f = str(tmp_path / "mix.params")
    vals = [nd.random.uniform(shape=net.collect_params()[k].shape)
            for k in ours]
    keys = [ours[2], "drift0_bias", "drift1_weight", "drift1_bias"]
    upstream.save_params(f, dict(zip(keys, vals)))
    loaded = upstream.load_params_into(net, f)
    assert sorted(loaded) == sorted(ours)
    for k, v in zip(ours, vals):
        np.testing.assert_allclose(
            net.collect_params()[k].data().asnumpy(), v.asnumpy())


def test_unsupported_dtype_raises(tmp_path):
    with pytest.raises(mx.MXNetError, match="type_flag"):
        upstream.save_params(str(tmp_path / "b.params"),
                             {"m": np.zeros((2,), dtype=np.uint32)})


def test_load_params_malformed_raises_cleanly(tmp_path):
    """Truncated/garbage .params files raise MXNetError at every cut
    point — never a hang or a bare struct/Index error (same contract the
    ONNX decoder pins)."""
    from mxnet_tpu.upstream import save_params, load_params
    p = {"arg:w": nd.array(np.random.randn(4, 3).astype(np.float32)),
         "aux:m": nd.array(np.zeros(3, np.float32))}
    good = str(tmp_path / "u.params")
    save_params(good, p)
    raw = open(good, "rb").read()
    bad = str(tmp_path / "bad.params")
    for cut in (1, 8, len(raw) // 3, len(raw) // 2, len(raw) - 2):
        open(bad, "wb").write(raw[:cut])
        with pytest.raises(mx.base.MXNetError):
            load_params(bad)
    open(bad, "wb").write(b"\xff" * 64)
    with pytest.raises(mx.base.MXNetError):
        load_params(bad)
