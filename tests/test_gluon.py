"""Gluon core tests (reference model: tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn


def test_dense_shapes_and_forward():
    layer = nn.Dense(4, in_units=3)
    layer.initialize()
    x = nd.ones((2, 3))
    y = layer(x)
    assert y.shape == (2, 4)


def test_dense_deferred_init():
    layer = nn.Dense(5)
    layer.initialize()
    y = layer(nd.ones((2, 7)))
    assert y.shape == (2, 5)
    assert layer.weight.shape == (5, 7)


def test_sequential_and_params():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    y = net(nd.ones((4, 6)))
    assert y.shape == (4, 3)
    params = net.collect_params()
    assert len(params) == 4  # 2 weights + 2 biases
    names = list(params.keys())
    assert any("weight" in n for n in names)


def test_hybridize_parity():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = nd.array(np.random.rand(5, 8).astype(np.float32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    assert np.allclose(y_eager, y_hybrid, atol=1e-5)
    # second call uses the cached executable
    y2 = net(x).asnumpy()
    assert np.allclose(y_hybrid, y2)


def test_hybridize_backward():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    net.hybridize()
    x = nd.array([[1.0, 2.0]])
    with autograd.record():
        y = net(x)
    y.backward()
    assert np.allclose(net.weight.grad().asnumpy(), [[1.0, 2.0]])


def test_batchnorm_running_stats():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = nd.array(np.random.rand(8, 3, 4, 4).astype(np.float32) * 5 + 2)
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm, 0)  # stats updated
    # inference mode uses running stats
    y = bn(x)
    assert y.shape == x.shape


def test_batchnorm_hybrid_stats():
    bn = nn.BatchNorm(in_channels=2)
    bn.initialize()
    bn.hybridize()
    x = nd.array(np.random.rand(4, 2, 3, 3).astype(np.float32) + 10)
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert rm.mean() > 0.5  # moved toward ~10 batch mean


def test_conv2d():
    conv = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    conv.initialize()
    x = nd.ones((2, 3, 16, 16))
    y = conv(x)
    assert y.shape == (2, 8, 16, 16)
    conv_s = nn.Conv2D(4, kernel_size=3, strides=2)
    conv_s.initialize()
    y2 = conv_s(nd.ones((1, 3, 8, 8)))
    assert y2.shape == (1, 4, 3, 3)


def test_conv2d_nhwc():
    conv = nn.Conv2D(8, kernel_size=3, padding=1, layout="NHWC")
    conv.initialize()
    y = conv(nd.ones((2, 16, 16, 3)))
    assert y.shape == (2, 16, 16, 8)


def test_pooling():
    x = nd.ones((1, 2, 8, 8))
    assert nn.MaxPool2D(2, 2)(x).shape == (1, 2, 4, 4)
    assert nn.AvgPool2D(2, 2)(x).shape == (1, 2, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (1, 2, 1, 1)


def test_embedding_dropout_layernorm():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    y = emb(nd.array([1, 2, 3]))
    assert y.shape == (3, 4)
    ln = nn.LayerNorm(in_channels=4)
    ln.initialize()
    z = ln(y)
    assert np.allclose(z.asnumpy().mean(-1), 0, atol=1e-5)
    do = nn.Dropout(0.5)
    with autograd.record():
        d = do(y)
    assert d.shape == y.shape


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    f = str(tmp_path / "net.params.npz")
    net.save_parameters(f)
    w_before = net[0].weight.data().asnumpy()

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.initialize()
    net2.load_parameters(f)
    # prefixes differ but structural (strip-prefix) names must map — load by
    # matching relative names requires same architecture
    assert np.allclose(net2[0].weight.data().asnumpy(), w_before)


def test_trainer_step_sgd():
    net = nn.Dense(1, in_units=1, use_bias=False)
    net.initialize(mx.init.Constant(2.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.array([[1.0]])
    with autograd.record():
        y = net(x)          # y = 2x
        loss = (y * y).sum()  # dL/dw = 2*y*x = 4
    loss.backward()
    trainer.step(1)
    assert np.allclose(net.weight.data().asnumpy(), [[2.0 - 0.4]])


def test_mlp_convergence():
    """End-to-end: MLP learns a separable toy problem (SURVEY.md §4)."""
    np.random.seed(0)
    n = 256
    x = np.random.randn(n, 10).astype(np.float32)
    w_true = np.random.randn(10, 3).astype(np.float32)
    labels = np.argmax(x @ w_true, axis=1).astype(np.float32)

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    xs, ys = nd.array(x), nd.array(labels)
    for _ in range(60):
        with autograd.record():
            out = net(xs)
            loss = loss_fn(out, ys)
        loss.backward()
        trainer.step(n)
    preds = net(xs).asnumpy().argmax(1)
    acc = (preds == labels).mean()
    assert acc > 0.9, f"accuracy {acc}"


def test_block_repr_and_summary():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    assert "Dense" in repr(net)
    out = net.summary()
    assert "Total params" in out


def test_clip_global_norm():
    a = nd.ones((2,)) * 3
    b = nd.ones((2,)) * 4
    total = gluon.utils.clip_global_norm([a, b], 1.0)
    assert abs(total - np.sqrt(9 * 2 + 16 * 2)) < 1e-4
    new_norm = np.sqrt((a.asnumpy() ** 2).sum() + (b.asnumpy() ** 2).sum())
    assert new_norm <= 1.0 + 1e-5


def test_split_and_load():
    data = nd.arange(0, 12).reshape((6, 2))
    parts = gluon.utils.split_and_load(data, [mx.cpu(), mx.cpu()])
    assert len(parts) == 2 and parts[0].shape == (3, 2)


def test_extract_pure_fn_training_aux():
    """extract_pure_fn(training=True) returns BN running-stat updates so an
    exported train step can carry them (VERDICT r1 weak #5)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.block import extract_pure_fn

    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.BatchNorm(), nn.Dense(4))
    net.initialize()
    x = mx.nd.random.uniform(shape=(16, 5))
    net(x)
    fn, params = extract_pure_fn(net, x, training=True)
    assert len(fn.aux_indices) == 2  # running_mean, running_var
    out, aux = jax.jit(fn)(params, x._data)
    assert out.shape == (16, 4) and len(aux) == 2
    # updated stats differ from the init values (mean 0 / var 1)
    before = [params[i] for i in fn.aux_indices]
    changed = [not jnp.allclose(b, a) for b, a in zip(before, aux)]
    assert all(changed)
    # eval path keeps the old contract: bare outputs
    fn_eval, params = extract_pure_fn(net, x)
    y = fn_eval(params, x._data)
    assert y.shape == (16, 4)


def test_export_imports_roundtrip(tmp_path):
    """HybridBlock.export writes a real symbol.json + checkpoint-style
    params that SymbolBlock.imports reloads to identical outputs
    (reference: the export/imports deployment pair)."""
    from mxnet_tpu.gluon import SymbolBlock
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = nd.random.uniform(shape=(2, 8))
    expect = net(x).asnumpy()

    path = str(tmp_path / "model")
    net.export(path, epoch=3)
    import os
    assert os.path.exists(path + "-symbol.json")
    assert os.path.exists(path + "-0003.params.npz")

    loaded = SymbolBlock.imports(path + "-symbol.json", ["data"],
                                 path + "-0003.params.npz")
    got = loaded(x).asnumpy()
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_imports_fallback_and_no_params(tmp_path):
    """Non-symbolic exports warn and are rejected by imports with a clear
    error; imports without a params file yields uninitialized Parameters
    (round-2 review findings)."""
    import warnings
    from mxnet_tpu.gluon import SymbolBlock
    # a Lambda over raw NDArray ops has no symbolic trace -> fallback
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Lambda(lambda x: x * x.sigmoid()))
    net.initialize()
    net(nd.ones((2, 3)))
    path = str(tmp_path / "bnnet")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        net.export(path)
    assert any("no symbolic trace" in str(x.message) for x in w)
    with pytest.raises(mx.base.MXNetError):
        SymbolBlock.imports(path + "-symbol.json", ["data"])

    # symbolic net, no params file: uninitialized Parameters exist
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4))
    net2.initialize()
    net2(nd.ones((2, 3)))
    p2 = str(tmp_path / "ok")
    net2.export(p2)
    blk = SymbolBlock.imports(p2 + "-symbol.json", ["data"])
    assert len(blk.collect_params()) == 2  # weight+bias, no data


def test_export_imports_resnet(tmp_path):
    """Model-zoo nets (conv/BN/pool) export to a real symbol.json with aux
    states and reload to identical outputs — the full deployment path."""
    from mxnet_tpu.gluon import SymbolBlock
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    net = resnet18_v1()
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, 64, 64))
    expect = net(x).asnumpy()

    path = str(tmp_path / "resnet18")
    net.export(path)
    loaded = SymbolBlock.imports(path + "-symbol.json", ["data"],
                                 path + "-0000.params.npz")
    got = loaded(x).asnumpy()
    np.testing.assert_allclose(got, expect, rtol=2e-3, atol=2e-4)
    # aux states (BN running stats) rode the aux: prefix
    import numpy as _np
    with _np.load(path + "-0000.params.npz") as f:
        keys = list(f.keys())
    assert any(k.startswith("aux:") for k in keys)
    assert any(k.startswith("arg:") for k in keys)


def test_transformer_export_symbolblock_roundtrip(tmp_path):
    """HybridBlock.export with input_shapes ships the transformer's
    sinusoid tables (collect_constants) in the params file, so
    SymbolBlock.imports reloads and reproduces the trained logits —
    the reference deployment pair for seq2seq."""
    import numpy as np
    from mxnet_tpu.models.transformer import TransformerNMT
    from mxnet_tpu.gluon.block import SymbolBlock
    net = TransformerNMT(vocab_size=25, units=16, hidden=32, num_layers=1,
                         num_heads=4, max_length=10, dropout=0.0)
    net.initialize()
    rng = np.random.RandomState(2)
    B, S = 2, 6
    src = nd.array(rng.randint(0, 25, (B, S)).astype(np.float32))
    tgt = nd.array(rng.randint(0, 25, (B, S)).astype(np.float32))
    ref = net(src, tgt).asnumpy()
    path = str(tmp_path / "nmt")
    net.export(path, num_inputs=2, input_shapes=[(B, S), (B, S)])
    loaded = SymbolBlock.imports(f"{path}-symbol.json", ["data", "data1"],
                                 f"{path}-0000.params.npz")
    got = loaded(src, tgt).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_exported_constants_frozen_on_reimport(tmp_path):
    """Shipped constants (const: prefix) reload grad_req='null' — a
    Trainer on the re-imported transformer must NOT update the sinusoid
    tables (r4 review finding: they came back as trainable args)."""
    import numpy as np
    from mxnet_tpu.models.transformer import TransformerNMT
    from mxnet_tpu.gluon.block import SymbolBlock
    net = TransformerNMT(vocab_size=20, units=16, hidden=32, num_layers=1,
                         num_heads=4, max_length=10, dropout=0.0)
    net.initialize()
    path = str(tmp_path / "nmtf")
    net.export(path, num_inputs=2, input_shapes=[(2, 5), (2, 5)])
    loaded = SymbolBlock.imports(f"{path}-symbol.json", ["data", "data1"],
                                 f"{path}-0000.params.npz")
    consts = {k: p for k, p in loaded.collect_params().items()
              if k.endswith("pos_table")}
    assert consts and all(p.grad_req == "null" for p in consts.values())
    rng = np.random.RandomState(0)
    src = nd.array(rng.randint(0, 20, (2, 5)).astype(np.float32))
    tgt = nd.array(rng.randint(0, 20, (2, 5)).astype(np.float32))
    lab = nd.array(rng.randint(0, 20, (2, 5)).astype(np.float32))
    before = {k: p.data().asnumpy().copy() for k, p in consts.items()}
    tr = gluon.Trainer(loaded.collect_params(), "sgd",
                       {"learning_rate": 0.5})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        out = loaded(src, tgt)
        L = lossf(out.reshape((-1, 20)), lab.reshape((-1,))).mean()
    L.backward()
    tr.step(2)
    for k, p in consts.items():
        np.testing.assert_array_equal(p.data().asnumpy(), before[k])


def test_bert_export_symbolblock_roundtrip(tmp_path):
    """BERT deploys through the reference export/imports pair too: the
    symbolic trace (decomposed flash attention) exports with shape
    hints and reloads as one Executor, ragged valid_length included."""
    import numpy as np
    from mxnet_tpu.models.bert import BERTModel
    from mxnet_tpu.gluon.block import SymbolBlock
    net = BERTModel(vocab_size=40, units=32, hidden_size=64, num_layers=2,
                    num_heads=4, max_length=12, dropout=0.0)
    net.initialize()
    rng = np.random.RandomState(6)
    B, S = 2, 9
    tok = nd.array(rng.randint(0, 40, (B, S)).astype(np.float32))
    seg = nd.array(np.zeros((B, S), np.float32))
    vl = nd.array(np.array([9, 4], np.float32))
    ref_seq, ref_pool = net(tok, seg, vl)
    path = str(tmp_path / "bert")
    net.export(path, num_inputs=3, input_shapes=[(B, S), (B, S), (B,)])
    loaded = SymbolBlock.imports(f"{path}-symbol.json",
                                 ["data", "data1", "data2"],
                                 f"{path}-0000.params.npz")
    got_seq, got_pool = loaded(tok, seg, vl)
    np.testing.assert_allclose(got_pool.asnumpy(), ref_pool.asnumpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_seq.asnumpy(), ref_seq.asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_bert_classifier_export_symbolblock_roundtrip(tmp_path):
    """The finetune deployment path: BERTClassifier (bert + pooled-output
    head) exports symbolically and reloads through SymbolBlock."""
    import numpy as np
    from mxnet_tpu.models.bert import BERTModel, BERTClassifier
    from mxnet_tpu.gluon.block import SymbolBlock
    bert = BERTModel(vocab_size=30, units=32, hidden_size=64, num_layers=1,
                     num_heads=4, max_length=10, dropout=0.0)
    clf = BERTClassifier(bert, num_classes=3, dropout=0.0)
    clf.initialize()
    rng = np.random.RandomState(8)
    B, S = 2, 7
    tok = nd.array(rng.randint(0, 30, (B, S)).astype(np.float32))
    seg = nd.array(np.zeros((B, S), np.float32))
    vl = nd.array(np.array([7, 3], np.float32))
    ref = clf(tok, seg, vl).asnumpy()
    path = str(tmp_path / "bclf")
    clf.export(path, num_inputs=3, input_shapes=[(B, S), (B, S), (B,)])
    loaded = SymbolBlock.imports(f"{path}-symbol.json",
                                 ["data", "data1", "data2"],
                                 f"{path}-0000.params.npz")
    got = loaded(tok, seg, vl).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
