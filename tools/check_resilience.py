#!/usr/bin/env python
"""Resilience check: drive the recovery supervisor (ISSUE 10) through a
seeded chaos soak covering EVERY failure domain, and assert each one
auto-recovers without process death:

  * **transient**  — an injected `kv.collective` raise mid-allreduce is
    retried on the same batch (bitwise parity with the fault-free run);
  * **corrupt_state** — a NaN storm (`grad.nan`) poisons the params; the
    deferred health check lets an INTACT-but-unhealthy checkpoint land
    first, so the rollback must consult the last-known-good journal,
    skip it (``checkpoint_unhealthy_skips``), restore the older healthy
    step and replay to bitwise parity;
  * **hang** — a `kv.timeout` stall trips `MXTPU_COLLECTIVE_TIMEOUT_MS`
    → typed `CollectiveTimeout` → watchdog post-mortem written → in-
    process restart from checkpoint, bitwise parity;
  * **preemption** — an injected SIGTERM mid-run produces the emergency
    checkpoint and a resumable exit; the simulated restart must resume
    past a deliberately TORN higher-step checkpoint
    (``checkpoint_fallbacks``) and finish at bitwise parity;
  * **capacity_loss** — a `device.lost` fire on a mesh device shrinks a
    rule-sharded (dp=2) trainer to the survivors via
    `Trainer.resize_mesh` and training CONTINUES (no bitwise promise —
    the reduction geometry changed; finiteness + progress asserted);
  * **capacity_gain** (fleet phase, >= 4 devices) — a (2,2) mesh loses
    a device, shrinks to (1,2), then the device RETURNS mid-run
    (`fault.clear` unmasks it); the grow-back probe must reverse the
    shrink to the original (2,2) layout over the original device ids
    with ``shard_host_gather_bytes`` pinned at zero across the whole
    episode, refill the restart budget, and count ``fault_regrows``.
    The pure resize round trip (no intervening steps) is additionally
    pinned BITWISE — the parity redistribute promises; training across
    the degraded window is finiteness-only, same as capacity_loss;
  * **exhaustion** — an unbounded NaN source against a restart budget of
    1 must exit through `RecoveryExhausted` with a parseable structured
    crash report and ``fault_restart_budget_remaining`` == 0.

Plus the leak gate: zero pending engine tasks, zero live task groups,
and zero leftover checkpoint tmp dirs after the whole soak.

Standalone:  python tools/check_resilience.py [--seed N] [--steps N]
(one JSON line on stdout; exit 0 = every domain recovered). Wired into
tier-1 by tests/test_check_resilience.py. Capacity-loss phase skips
cleanly under 2 devices (same discipline as check_dispatch's shard
phase).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile


def _force_cpu():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            flags + " --xla_force_host_platform_device_count=8"
    import jax
    jax.config.update("jax_platforms", "cpu")


N_BATCHES = 6
BATCH = 8
FEATS = 32
CLASSES = 4


def make_data(seed):
    """Deterministic in-memory batch list; the replayable factory is
    `lambda: iter(data)` — every run (and every rollback replay) sees
    the identical stream."""
    import numpy as np
    from mxnet_tpu import nd
    rng = np.random.RandomState(seed)
    return [(nd.array(rng.randn(BATCH, FEATS).astype(np.float32)),
             nd.array(rng.randint(0, CLASSES, BATCH).astype(np.float32)))
            for _ in range(N_BATCHES)]


def build(seed):
    """Deterministic net + trainer (momentum SGD: optimizer STATE must
    survive every rollback/restart too). 'ici' + fused=False so the
    per-param allreduce path — where kv.collective / kv.timeout fire —
    actually runs."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=FEATS),
            nn.Dense(CLASSES, in_units=16))
    net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    net(nd.zeros((1, FEATS)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore="ici", fused=False)
    return net, trainer


def make_step(net, trainer, lossf):
    from mxnet_tpu import autograd

    def step(batch):
        x, y = batch
        with autograd.record():
            loss = lossf(net(x), y).mean()
        loss.backward()
        trainer.step(BATCH)
        return loss
    return step


def params_list(net):
    import numpy as np
    return [np.asarray(p.data().asnumpy())
            for p in net.collect_params().values()]


def assert_parity(clean, got, phase):
    import numpy as np
    bad = [i for i, (a, b) in enumerate(zip(clean, got))
           if not np.array_equal(a, b)]
    if bad:
        raise AssertionError(f"{phase}: params diverged from the "
                             f"fault-free run at positions {bad}")


def _metric(name, **labels):
    from mxnet_tpu.observability import registry
    return registry().counter(name, **labels).value


def _find_tmp_dirs(root):
    leaks = []
    for dirpath, dirnames, _ in os.walk(root):
        for d in dirnames:
            if d.startswith(".tmp-"):
                leaks.append(os.path.join(dirpath, d))
    return leaks


def run(workdir=None, seed=0, steps=14):
    """Execute the soak; returns the result dict (raises on any
    recovery/parity/leak failure). Armed faults and preemption state
    are cleaned up on EVERY exit path — a failing phase must not leave
    e.g. a prob=1.0 grad.nan spec poisoning the rest of the pytest
    session."""
    from mxnet_tpu import fault
    try:
        return _run_phases(workdir, seed, steps)
    finally:
        fault.clear()
        fault.reset_preemption(clear_callbacks=True)
        fault.uninstall_preemption_handler()


def _run_phases(workdir, seed, steps):
    import numpy as np
    from mxnet_tpu import fault, gluon, engine
    from mxnet_tpu.fault.watchdog import StepWatchdog
    import jax

    owns_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="mxtpu_resilience_")
    os.makedirs(workdir, exist_ok=True)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    data = make_data(seed)
    factory = lambda: iter(data)    # noqa: E731

    rng = np.random.RandomState(seed + 1)
    # 4 params/step on this net -> per-hit schedules for kv points
    nan_at = int(rng.randint(3, 6)) * 2 - 1       # odd: see corrupt phase
    transient_step = int(rng.randint(2, steps - 1))
    hang_step = int(rng.randint(2, steps - 1))
    preempt_at = int(rng.randint(4, steps - 2))
    loss_at = int(rng.randint(2, steps - 2))
    params_per_step = 4

    groups0 = engine.active_groups()
    recovered = {}

    def supervise(net, trainer, ckpt, **kw):
        kw.setdefault("checkpoint_every", 2)
        kw.setdefault("backoff_base", 0.0)
        kw.setdefault("emergency_save", False)
        step = make_step(net, trainer, lossf)
        return fault.run_supervised(trainer, step, factory, steps,
                                    checkpoint_dir=ckpt, **kw)

    # ----------------------------------------------------- clean run
    fault.clear()
    fault.reset_preemption(clear_callbacks=True)
    net, trainer = build(seed)
    rep, _ = supervise(net, trainer, None)
    if rep["outcome"] != "completed" or rep["applied"] != steps:
        raise AssertionError(f"clean run did not complete: {rep}")
    clean = params_list(net)
    clean_loss = rep["final_loss"]

    # ----------------------------------------------------- transient
    fault.inject("kv.collective",
                 at=[(transient_step - 1) * params_per_step + 1])
    net, trainer = build(seed)
    rep, _ = supervise(net, trainer, os.path.join(workdir, "ck_transient"))
    fault.clear()
    if rep["recoveries"]["transient"] < 1:
        raise AssertionError(f"transient recovery not recorded: {rep}")
    assert_parity(clean, params_list(net), "transient")
    recovered["transient"] = rep["recoveries"]["transient"]

    # ------------------------------------------- corrupt state (NaN)
    # grad.nan at an ODD step + checkpoint_every=2 + check_every=2: the
    # poisoned loss is RECORDED at the next (even) step, the periodic
    # save lands an intact-but-unhealthy checkpoint, and only then does
    # the health check fire — rollback must skip the unhealthy step via
    # the journal, restore the older healthy one, and replay
    unh0 = _metric("checkpoint_unhealthy_skips")
    fault.inject("grad.nan", at=[nan_at])
    net, trainer = build(seed)
    rep, _ = supervise(net, trainer, os.path.join(workdir, "ck_corrupt"),
                       check_every=2)
    fault.clear()
    if rep["recoveries"]["corrupt_state"] < 1:
        raise AssertionError(f"corrupt-state recovery not recorded: {rep}")
    if _metric("checkpoint_unhealthy_skips") - unh0 < 1:
        raise AssertionError("rollback never consulted the health "
                             "journal (checkpoint_unhealthy_skips flat)")
    assert_parity(clean, params_list(net), "corrupt_state")
    recovered["corrupt_state"] = rep["recoveries"]["corrupt_state"]

    # ---------------------------------------------------------- hang
    wd_dir = os.path.join(workdir, "watchdog")
    os.environ["MXTPU_COLLECTIVE_TIMEOUT_MS"] = "120"
    to0 = _metric("kv_collective_timeouts", op="allreduce")
    try:
        fault.inject("kv.timeout",
                     at=[(hang_step - 1) * params_per_step + 1],
                     action="stall", delay=0.6)
        net, trainer = build(seed)
        rep, _ = supervise(net, trainer, os.path.join(workdir, "ck_hang"),
                           watchdog=StepWatchdog(timeout_ms=0,
                                                 snapshot_dir=wd_dir))
        fault.clear()
    finally:
        del os.environ["MXTPU_COLLECTIVE_TIMEOUT_MS"]
    if rep["recoveries"]["hang"] < 1:
        raise AssertionError(f"hang recovery not recorded: {rep}")
    if _metric("kv_collective_timeouts", op="allreduce") - to0 < 1:
        raise AssertionError("CollectiveTimeout never fired")
    snaps = [f for f in os.listdir(wd_dir) if f.startswith("watchdog-")] \
        if os.path.isdir(wd_dir) else []
    if not snaps:
        raise AssertionError("hang recovery wrote no post-mortem snapshot")
    assert_parity(clean, params_list(net), "hang")
    recovered["hang"] = rep["recoveries"]["hang"]

    # ----------------------------------------------------- preemption
    ck_pre = os.path.join(workdir, "ck_preempt")
    fb0 = _metric("checkpoint_fallbacks")
    fault.inject("preempt.sigterm", at=[preempt_at + 1], action="sigterm")
    net, trainer = build(seed)
    pre_rep, _ = supervise(net, trainer, ck_pre, emergency_save=True)
    if pre_rep["outcome"] != "preempted":
        raise AssertionError(f"SIGTERM never preempted the run: "
                             f"{pre_rep}")
    preempted_at = pre_rep["applied"]
    fault.clear()
    fault.reset_preemption(clear_callbacks=True)
    fault.uninstall_preemption_handler()
    # torn checkpoint at a HIGHER step: resume must skip it
    torn = os.path.join(ck_pre, str(steps + 100))
    os.makedirs(torn, exist_ok=True)
    with open(os.path.join(torn, "junk"), "wb") as f:
        f.write(b"\x00torn")
    net, trainer = build(seed + 999)     # different init: restore must win
    rep, _ = supervise(net, trainer, ck_pre, emergency_save=True)
    fault.reset_preemption(clear_callbacks=True)
    fault.uninstall_preemption_handler()
    if rep["outcome"] != "completed" or rep["resumed_from"] != preempted_at:
        raise AssertionError(f"resume after preemption failed: {rep}")
    if _metric("checkpoint_fallbacks") - fb0 < 1:
        raise AssertionError("torn checkpoint skip not counted")
    assert_parity(clean, params_list(net), "preemption")
    if pre_rep["recoveries"]["preemption"] < 1:
        raise AssertionError("preemption not counted as a recovered "
                             f"incident: {pre_rep['recoveries']}")
    recovered["preemption"] = pre_rep["recoveries"]["preemption"]

    # -------------------------------------------------- capacity loss
    capacity = "skipped"
    if jax.device_count() >= 2:
        net, trainer = build(seed)
        plan = trainer.shard(mesh={"dp": 2, "tp": 1})
        cstep = trainer.capture(lambda x, y: lossf(net(x), y).mean())
        mesh_ids = [d.id for d in plan.mesh.devices.flatten()]
        fault.inject("device.lost", at=[loss_at + 1], device=mesh_ids[-1])
        step_fn = lambda b: cstep(b[0], b[1])       # noqa: E731
        rep, _ = fault.run_supervised(
            trainer, step_fn, factory, steps,
            checkpoint_dir=os.path.join(workdir, "ck_capacity"),
            checkpoint_every=4, backoff_base=0.0, emergency_save=False)
        fault.clear()
        if rep["outcome"] != "completed" or \
                rep["recoveries"]["capacity_loss"] < 1:
            raise AssertionError(f"capacity-loss recovery failed: {rep}")
        new_shape = dict(trainer.shard_plan.mesh.shape)
        if new_shape.get("dp") != 1:
            raise AssertionError(f"mesh did not shrink: {new_shape}")
        finals = params_list(net)
        if not all(np.isfinite(a).all() for a in finals):
            raise AssertionError("post-shrink params not finite")
        if rep["final_loss"] is None or not np.isfinite(rep["final_loss"]):
            raise AssertionError("post-shrink loss not finite")
        capacity = {"survivor_mesh": new_shape,
                    "final_loss": rep["final_loss"]}
        recovered["capacity_loss"] = rep["recoveries"]["capacity_loss"]
    else:
        capacity = f"skipped ({jax.device_count()} devices)"

    # ------------------------------------- fleet grow-back (capacity_gain)
    fleet = "skipped"
    if jax.device_count() >= 4 and steps >= 12:
        hg0 = _metric("shard_host_gather_bytes")
        rg0 = _metric("fault_regrows")
        net, trainer = build(seed)
        plan = trainer.shard(mesh={"dp": 2, "tp": 2})
        orig_axes = {k: int(v) for k, v in plan.mesh.shape.items()}
        orig_ids = [d.id for d in plan.mesh.devices.flatten()]
        cstep = trainer.capture(lambda x, y: lossf(net(x), y).mean())
        fault.inject("device.lost", at=[4], device=orig_ids[-1])
        applied = {"n": 0}

        def fleet_step(b):
            # capacity "returns" a couple of shrunk steps after the
            # loss: clearing the spec unmasks the lost device, which is
            # what arms the supervisor's grow-back probe
            if applied["n"] >= 6 and fault.lost_devices():
                fault.clear("device.lost")
            applied["n"] += 1
            return cstep(b[0], b[1])

        rep, sup = fault.run_supervised(
            trainer, fleet_step, factory, steps,
            checkpoint_dir=os.path.join(workdir, "ck_fleet"),
            checkpoint_every=4, backoff_base=0.0, emergency_save=False,
            restart_budget=3, regrow_cooldown=2, regrow_hysteresis=2)
        fault.clear()
        if rep["outcome"] != "completed" or \
                rep["recoveries"]["capacity_loss"] < 1:
            raise AssertionError(f"fleet phase never lost capacity: {rep}")
        if rep["recoveries"]["capacity_gain"] < 1:
            raise AssertionError(f"grow-back never happened: {rep}")
        if _metric("fault_regrows") - rg0 < 1:
            raise AssertionError("fault_regrows counter flat after regrow")
        regrown_axes = {k: int(v)
                        for k, v in trainer.shard_plan.mesh.shape.items()}
        regrown_ids = [d.id
                       for d in trainer.shard_plan.mesh.devices.flatten()]
        if regrown_axes != orig_axes or regrown_ids != orig_ids:
            raise AssertionError(
                f"regrow did not restore the pre-shrink layout: "
                f"{regrown_axes} over {regrown_ids} != {orig_axes} over "
                f"{orig_ids}")
        if rep["budget_remaining"] != 3:
            raise AssertionError(
                f"regrow did not refill the restart budget: "
                f"{rep['budget_remaining']} != 3")
        gains = [i for i in sup.incidents()
                 if i["domain"] == "capacity_gain" and i.get("recovered")]
        if not gains:
            raise AssertionError("no capacity_gain incident recorded")
        finals = params_list(net)
        if not all(np.isfinite(a).all() for a in finals):
            raise AssertionError("post-regrow params not finite")
        # the parity redistribute DOES promise: a pure shrink -> grow
        # round trip with no intervening optimizer steps is bitwise, and
        # neither direction may gather through the host
        trainer.resize_mesh({"dp": 1, "tp": 2},
                            devices=[d for d in plan.mesh.devices.flatten()
                                     if d.id in orig_ids[:2]])
        trainer.resize_mesh(orig_axes,
                            devices=list(plan.mesh.devices.flatten()))
        assert_parity(finals, params_list(net), "fleet regrow round-trip")
        if _metric("shard_host_gather_bytes") - hg0 != 0:
            raise AssertionError(
                f"grow-back episode gathered "
                f"{_metric('shard_host_gather_bytes') - hg0} bytes "
                f"through the host (promised zero)")
        fleet = {"regrown_mesh": regrown_axes,
                 "regrows": rep["recoveries"]["capacity_gain"],
                 "final_loss": rep["final_loss"]}
        recovered["capacity_gain"] = rep["recoveries"]["capacity_gain"]
    else:
        fleet = f"skipped ({jax.device_count()} devices, {steps} steps)"

    # ----------------------------------------------------- exhaustion
    from mxnet_tpu.observability import registry
    crash_dir = os.path.join(workdir, "crash")
    fault.inject("grad.nan", prob=1.0)
    net, trainer = build(seed)
    step = make_step(net, trainer, lossf)
    try:
        fault.run_supervised(trainer, step, factory, steps,
                             checkpoint_dir=os.path.join(workdir, "ck_ex"),
                             checkpoint_every=2, restart_budget=1,
                             backoff_base=0.0, emergency_save=False,
                             crash_dir=crash_dir)
        raise AssertionError("unbounded NaN source did not exhaust the "
                             "restart budget")
    except fault.RecoveryExhausted as e:
        fault.clear()
        if not e.report_path or not os.path.exists(e.report_path):
            raise AssertionError(f"no crash report on disk: {e}")
        with open(e.report_path) as f:
            report = json.load(f)
        for field in ("reason", "domain", "incidents", "metrics",
                      "engine_pending", "budget_remaining"):
            if field not in report:
                raise AssertionError(f"crash report missing {field!r}")
        if report["reason"] != "restart budget exhausted":
            raise AssertionError(f"wrong crash reason: {report['reason']}")
        if registry().gauge("fault_restart_budget_remaining").value != 0:
            raise AssertionError("budget gauge not zero after exhaustion")

    # ------------------------------------------------------ leak gate
    engine.wait_for_all()
    if engine.pending_tasks() != 0:
        raise AssertionError(f"{engine.pending_tasks()} engine tasks "
                             f"leaked")
    if engine.active_groups() != groups0:
        raise AssertionError(
            f"task groups leaked: {engine.active_groups()} != {groups0}")
    tmp_leaks = _find_tmp_dirs(workdir)
    if tmp_leaks:
        raise AssertionError(f"checkpoint tmp dirs leaked: {tmp_leaks}")

    result = {
        "metric": "resilience_soak",
        "value": 1,
        "seed": seed,
        "steps": steps,
        "parity": "bitwise",            # transient/corrupt/hang/preempt
        "clean_loss": clean_loss,
        "recoveries": recovered,
        "preempted_after": preempted_at,
        "capacity": capacity,
        "fleet": fleet,
        "crash_report_fields": sorted(report.keys()),
        "delta_checkpoint_fallbacks": _metric("checkpoint_fallbacks") - fb0,
        "delta_unhealthy_skips": _metric("checkpoint_unhealthy_skips")
        - unh0,
    }
    if owns_dir:
        shutil.rmtree(workdir, ignore_errors=True)
    return result


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    seed, steps = 0, 14
    if "--seed" in argv:
        seed = int(argv[argv.index("--seed") + 1])
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    _force_cpu()
    try:
        res = run(seed=seed, steps=steps)
    except AssertionError as e:
        print(f"check_resilience: FAIL: {e}", file=sys.stderr)
        return 1
    print(json.dumps(res))
    print(f"check_resilience: OK (seed={seed}, domains="
          f"{sorted(res['recoveries'])})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
