"""Checkpoint/resume tests (SURVEY.md §2 #36, §5)."""
import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, checkpoint, gluon
from mxnet_tpu.gluon import nn


def test_save_load_checkpoint_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "model")
        arg = {"w": nd.array([1.0, 2.0]), "b": nd.array([0.5])}
        aux = {"mean": nd.array([0.1])}
        checkpoint.save_checkpoint(prefix, 3, None, arg, aux)
        sym, arg2, aux2 = checkpoint.load_checkpoint(prefix, 3)
        np.testing.assert_allclose(arg2["w"].asnumpy(), [1.0, 2.0])
        np.testing.assert_allclose(aux2["mean"].asnumpy(), [0.1])


def test_gluon_save_load_parameters():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "net.params.npz")
        net = nn.Dense(3, in_units=2)
        net.initialize(mx.init.Normal(1.0))
        net.save_parameters(path)
        net2 = nn.Dense(3, in_units=2)
        net2.load_parameters(path)
        np.testing.assert_allclose(net.weight.data().asnumpy(),
                                   net2.weight.data().asnumpy())


def test_sharded_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        params = {"layer": {"w": jnp.arange(6.0).reshape(2, 3),
                            "b": jnp.zeros(3)}}
        checkpoint.save_sharded(d, 100, params)
        template = {"layer": {"w": jnp.zeros((2, 3)), "b": jnp.ones(3)}}
        restored = checkpoint.load_sharded(d, 100, template)
        np.testing.assert_allclose(np.asarray(restored["layer"]["w"]),
                                   np.arange(6.0).reshape(2, 3))


def test_checkpoint_manager_rolls():
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        for step in (1, 2, 3):
            mgr.save(step, {"w": jnp.full((2,), float(step))})
        assert mgr.steps() == [2, 3]
        step, restored = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 3
        np.testing.assert_allclose(np.asarray(restored["w"]), [3.0, 3.0])


def test_sharded_checkpoint_of_sharded_params():
    """Save params laid out on an 8-device mesh; restore matches."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"dp": 8})
    w = jnp.arange(32.0).reshape(8, 4)
    sharded = jax.device_put(w, NamedSharding(mesh, P("dp", None)))
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_sharded(d, 0, {"w": sharded})
        restored = checkpoint.load_sharded(d, 0, {"w": jnp.zeros((8, 4))})
        np.testing.assert_allclose(np.asarray(restored["w"]), np.asarray(w))


# ------------------------- preemption-safe checkpointing (ISSUE 3) ------
def _corrupt_one_payload_byte(step_dir):
    for dirpath, _, files in os.walk(step_dir):
        for f in files:
            full = os.path.join(dirpath, f)
            if f != checkpoint.MANIFEST_NAME and os.path.getsize(full) > 4:
                blob = open(full, "rb").read()
                with open(full, "wb") as fh:
                    fh.write(bytes([blob[0] ^ 0xFF]) + blob[1:])
                return full
    raise AssertionError("no payload file to corrupt")


def test_atomic_save_writes_manifest_and_validates():
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_sharded(d, 7, {"w": jnp.arange(4.0)},
                                extras={"meta.json": b"{}"})
        step_dir = os.path.join(d, "7")
        assert os.path.exists(os.path.join(step_dir,
                                           checkpoint.MANIFEST_NAME))
        assert checkpoint.validate_checkpoint(step_dir) == []
        assert checkpoint.read_extra(d, 7, "meta.json") == b"{}"
        # no tmp dirs left behind
        assert not [n for n in os.listdir(d) if n.startswith(".tmp")]


def test_validate_detects_corruption_and_tears():
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_sharded(d, 1, {"w": jnp.arange(8.0)})
        step_dir = os.path.join(d, "1")
        _corrupt_one_payload_byte(step_dir)
        errs = checkpoint.validate_checkpoint(step_dir)
        assert errs and "checksum" in " ".join(errs)
        with pytest.raises(mx.MXNetError, match="invalid checkpoint"):
            checkpoint.load_sharded(d, 1, {"w": jnp.zeros(8)})
        # a bare dir (torn before the manifest landed) is invalid too
        os.makedirs(os.path.join(d, "2"))
        assert checkpoint.validate_checkpoint(os.path.join(d, "2"))


def test_restore_latest_falls_back_to_newest_valid():
    from mxnet_tpu.observability import registry
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=5)
        for s in (1, 2, 3):
            mgr.save(s, {"w": jnp.full((2,), float(s))})
        _corrupt_one_payload_byte(os.path.join(d, "3"))
        fb0 = registry().counter("checkpoint_fallbacks").value
        step, restored = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 2
        np.testing.assert_allclose(np.asarray(restored["w"]), [2.0, 2.0])
        assert registry().counter("checkpoint_fallbacks").value == fb0 + 1
        assert mgr.valid_steps() == [1, 2]


def test_retention_recomputes_after_save_never_deletes_new():
    """Satellite: re-saving an existing step must not make max_to_keep
    off by one, and the just-written step always survives pruning."""
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        for s in (1, 2, 3):
            mgr.save(s, {"w": jnp.full((2,), float(s))})
        assert mgr.steps() == [2, 3]
        mgr.save(3, {"w": jnp.full((2,), 3.5)})   # re-save existing step
        assert mgr.steps() == [2, 3]
        mgr.save(1, {"w": jnp.full((2,), 1.5)})   # older than survivors
        assert 1 in mgr.steps() and len(mgr.steps()) == 2


def test_retention_never_deletes_pre_manifest_dirs():
    """Manifest-less step dirs (pre-manifest layout, or torn) are
    excluded from the retention quota but NEVER auto-deleted — an
    upgrade must not destroy old-format resume points."""
    with tempfile.TemporaryDirectory() as d:
        legacy = os.path.join(d, "10")
        os.makedirs(legacy)
        with open(os.path.join(legacy, "payload"), "wb") as f:
            f.write(b"old-format checkpoint")
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        for s in (20, 21, 22):
            mgr.save(s, {"w": jnp.full((2,), float(s))})
        assert os.path.exists(legacy)          # survived every prune
        assert mgr.steps() == [10, 21, 22]     # quota counted valid only


def test_async_save_via_engine_and_wait():
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        fut = mgr.save(5, {"w": jnp.arange(4.0)}, _async=True)
        mgr.wait()
        assert fut.done() and fut.exception() is None
        assert mgr.valid_steps() == [5]
        step, restored = mgr.restore_latest({"w": jnp.zeros(4)})
        assert step == 5
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.arange(4.0))


def test_async_save_failure_surfaces_and_recovers():
    from mxnet_tpu import fault, engine
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        fault.inject("checkpoint.save", times=10)   # out-retries policy
        try:
            mgr.save(6, {"w": jnp.arange(4.0)}, _async=True)
            with pytest.raises(fault.FaultInjected):
                mgr.wait()
        finally:
            fault.clear()
            engine.clear_failures()
        assert mgr.valid_steps() == []
        mgr.save(6, {"w": jnp.arange(4.0)})       # sync re-save recovers
        assert mgr.valid_steps() == [6]
        step, _ = mgr.restore_latest({"w": jnp.zeros(4)})
        assert step == 6


def test_async_save_error_survives_later_saves():
    """wait()'s re-raise contract: a failed async save must surface even
    when more saves were queued after it finished failing."""
    from mxnet_tpu import fault, engine
    import time
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=5)
        fault.inject("checkpoint.save", times=10)
        try:
            fut = mgr.save(1, {"w": jnp.arange(2.0)}, _async=True)
            while not fut.done():
                time.sleep(0.01)
        finally:
            fault.clear()
        mgr.save(2, {"w": jnp.arange(2.0)}, _async=True)  # compacts queue
        with pytest.raises(fault.FaultInjected):
            mgr.wait()
        engine.clear_failures()
        mgr.wait()                       # drained: contract reset


def test_save_retries_injected_fault():
    from mxnet_tpu import fault
    from mxnet_tpu.observability import registry
    with tempfile.TemporaryDirectory() as d:
        r0 = registry().counter("fault_retries", site="checkpoint").value
        fault.inject("checkpoint.save", times=1)
        try:
            checkpoint.save_sharded(d, 4, {"w": jnp.arange(4.0)})
        finally:
            fault.clear()
        assert checkpoint.validate_checkpoint(os.path.join(d, "4")) == []
        assert registry().counter("fault_retries",
                                  site="checkpoint").value >= r0 + 1


def test_emergency_save_on_sigterm():
    import signal
    from mxnet_tpu import fault
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=3)
        state = {"step": 11, "w": jnp.full((2,), 11.0)}
        mgr.enable_emergency_save(
            params_fn=lambda: {"w": state["w"]},
            step_fn=lambda: state["step"],
            extras_fn=lambda: {"meta.json": b'{"emergency": true}'})
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            assert fault.preempted()
            with pytest.raises(fault.Preempted):
                fault.check_preempted()
        finally:
            mgr.disable_emergency_save()
            fault.reset_preemption(clear_callbacks=True)
            fault.uninstall_preemption_handler()
        assert mgr.valid_steps() == [11]
        assert mgr.read_extra(11, "meta.json") == b'{"emergency": true}'
        step, restored = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 11
        np.testing.assert_allclose(np.asarray(restored["w"]), [11., 11.])


def test_resharded_restore_onto_different_device_count():
    """Restore-template sharding wins: params saved from an 8-device
    mesh restore onto a 2-device mesh (and back to 1) numerically
    equal — the portable-redistribution resume path."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel.mesh import make_mesh
    mesh8 = make_mesh({"dp": 8})
    w = jnp.arange(64.0).reshape(8, 8)
    sharded = jax.device_put(w, NamedSharding(mesh8, P("dp", None)))
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_sharded(d, 0, {"w": sharded})
        mesh2 = make_mesh({"dp": 2})
        tmpl2 = {"w": jax.device_put(jnp.zeros((8, 8)),
                                     NamedSharding(mesh2, P("dp", None)))}
        out2 = checkpoint.load_sharded(d, 0, tmpl2)
        assert len(out2["w"].sharding.device_set) == 2
        np.testing.assert_allclose(np.asarray(out2["w"]), np.asarray(w))
        out1 = checkpoint.load_sharded(d, 0, {"w": jnp.zeros((8, 8))})
        np.testing.assert_allclose(np.asarray(out1["w"]), np.asarray(w))


def test_async_save_rejected_by_bounded_queue_falls_back_sync():
    """QoS backpressure (ISSUE 7 review): an async save whose engine push
    is REJECTED by a bounded background class (reject policy) falls back
    to a synchronous save — the checkpoint lands, wait() stays clean,
    and the deferred prune self-heals on the next unthrottled save."""
    import threading
    import time
    from mxnet_tpu import engine
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        gate = threading.Event()
        [engine.push(gate.wait) for _ in range(engine.num_workers())]
        time.sleep(0.05)
        engine.push(lambda: None, priority=engine.PRIORITY_BACKGROUND)
        time.sleep(0.05)
        prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1,
                                      "reject")
        try:
            fut = mgr.save(1, {"w": jnp.ones(2)}, _async=True)
            # sync fallback: the step is on disk before any engine drain
            assert fut.done() and not checkpoint.validate_checkpoint(
                os.path.join(d, "1"))
            fut2 = mgr.save(2, {"w": jnp.full((2,), 2.0)}, _async=True)
            assert fut2.done()
        finally:
            engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
            gate.set()
            engine.wait_for_all()
        mgr.wait()
        assert mgr.steps() == [1, 2]
        # prunes were deferred (their pushes rejected too); the next
        # unthrottled save recomputes retention over the full listing
        mgr.save(3, {"w": jnp.full((2,), 3.0)})
        assert mgr.steps() == [2, 3]
        step, restored = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 3


def test_rejected_save_fallback_orders_after_queued_save_of_same_step():
    """Regression (ISSUE 7 review): the reject-policy sync-save fallback
    serializes on the step's file_var — with a save of step N queued
    behind a wedged engine, a rejected re-save of the SAME step must
    wait for it instead of writing the step dir concurrently (two
    writers interleaving in the deterministic tmp dir would rename a
    torn tree)."""
    import threading
    import time
    from mxnet_tpu import engine
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=4)
        gate = threading.Event()
        [engine.push(gate.wait) for _ in range(engine.num_workers())]
        time.sleep(0.05)
        first = mgr.save(1, {"w": jnp.ones(2)}, _async=True)  # queued
        prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1,
                                      "reject")
        results = {}

        def resave():
            results["fut"] = mgr.save(1, {"w": jnp.full((2,), 2.0)},
                                      _async=True)

        t = threading.Thread(target=resave)
        try:
            t.start()
            time.sleep(0.2)
            # the fallback must be PARKED behind the queued save, not
            # already done (the old code wrote immediately, racing it)
            assert t.is_alive()
            assert not first.done()
        finally:
            engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
            gate.set()
            t.join(timeout=30)
        assert not t.is_alive()
        first.result(timeout=30)
        results["fut"].result(timeout=30)
        engine.wait_for_all()
        # last writer wins, and the step validates (no torn tree)
        assert not checkpoint.validate_checkpoint(os.path.join(d, "1"))
        step, restored = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 1
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.full((2,), 2.0))


def test_cancel_pending_and_emergency_save_cancels_queued_saves():
    """cancel_pending(): queued-not-started async saves resolve to
    engine.CANCELLED (no failure, nothing written); the emergency-save
    callback calls it so stale queued saves cannot compete with the
    SIGTERM save for workers/disk."""
    import threading
    from mxnet_tpu import engine, fault
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=5)
        cb = mgr.enable_emergency_save(
            params_fn=lambda: {"w": jnp.full((2,), 9.0)},
            step_fn=lambda: 9)
        gate = threading.Event()
        eng = engine._get()
        blockers = [engine.push(gate.wait) for _ in range(eng.workers)]
        try:
            futs = [mgr.save(s, {"w": jnp.full((2,), float(s))},
                             _async=True) for s in (1, 2)]
            # cancelled members settle at DISPATCH (a worker pops the
            # skip): open the gate shortly after cb() cancels them, so
            # its bounded drain completes without waiting out the timeout
            threading.Timer(0.3, gate.set).start()
            cb()   # emergency: cancel queued saves, then save step 9 inline
            for f in futs:
                assert f.result(timeout=10) is engine.CANCELLED
        finally:
            gate.set()
            mgr.disable_emergency_save()
            fault.reset_preemption(clear_callbacks=True)
            fault.uninstall_preemption_handler()
        engine.wait_for_all()
        assert mgr.valid_steps() == [9]        # cancelled saves never wrote
        assert engine.failures() == []         # cancelled is not a failure


# --------------------------------------- ISSUE 10: last-known-good journal
def test_health_journal_save_read_and_healthy_steps():
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=10)
        mgr.save(1, {"w": jnp.ones(2)}, health={"healthy": True,
                                                "loss": 0.5})
        mgr.save(2, {"w": jnp.ones(2) * 2},
                 health={"healthy": False, "loss": float("nan")})
        mgr.save(3, {"w": jnp.ones(2) * 3})       # pre-journal: trusted
        assert mgr.read_health(1)["loss"] == 0.5
        assert mgr.read_health(3) is None
        assert checkpoint.is_healthy(mgr.read_health(3))
        assert not checkpoint.is_healthy(mgr.read_health(2))
        assert mgr.healthy_steps() == [1, 3]


def test_restore_latest_healthy_skips_unhealthy_counts_metric():
    from mxnet_tpu.observability import registry
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=10)
        mgr.save(1, {"w": jnp.ones(2)}, health={"healthy": True})
        mgr.save(2, {"w": jnp.ones(2) * 2}, health={"healthy": False})
        u0 = registry().counter("checkpoint_unhealthy_skips").value
        step, params = mgr.restore_latest_healthy({"w": jnp.zeros(2)})
        assert step == 1
        np.testing.assert_array_equal(np.asarray(params["w"]), [1, 1])
        assert registry().counter(
            "checkpoint_unhealthy_skips").value == u0 + 1
        # plain restore_latest ignores the journal (newest valid wins)
        step, _ = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 2


def test_restore_latest_healthy_fallback_and_strict():
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=10)
        mgr.save(4, {"w": jnp.ones(2)}, health={"healthy": False})
        # nothing healthy: default degrades to newest merely-valid...
        step, params = mgr.restore_latest_healthy({"w": jnp.zeros(2)})
        assert step == 4
        # ...strict returns nothing instead
        step, params = mgr.restore_latest_healthy({"w": jnp.zeros(2)},
                                                  strict=True)
        assert step is None and params is None


def test_restore_scan_validates_every_candidate():
    """Regression (ISSUE 10 satellite): the descending fallback scan
    must re-validate the manifest sha256 of EVERY candidate it tries —
    two differently-corrupted newest steps are both detected and
    counted, and the scan lands on the third."""
    from mxnet_tpu.observability import registry
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=10)
        for s in (1, 2, 3):
            mgr.save(s, {"w": jnp.full((2,), float(s))},
                     health={"healthy": True})
        # newest: torn (manifest gone); second: silent byte corruption
        # only a real checksum re-validation can catch
        os.remove(os.path.join(d, "3", checkpoint.MANIFEST_NAME))
        with open(os.path.join(d, "2", checkpoint.HEALTH_NAME), "ab") as f:
            f.write(b" ")
        c0 = registry().counter("checkpoint_fallbacks").value
        step, params = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 1
        np.testing.assert_array_equal(np.asarray(params["w"]), [1, 1])
        assert registry().counter("checkpoint_fallbacks").value == c0 + 2
        # the healthy scan applies the same discipline
        c1 = registry().counter("checkpoint_fallbacks").value
        step, _ = mgr.restore_latest_healthy({"w": jnp.zeros(2)})
        assert step == 1
        assert registry().counter("checkpoint_fallbacks").value == c1 + 2


def test_health_extra_name_collision_raises():
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d)
        with pytest.raises(mx.base.MXNetError):
            mgr.save(1, {"w": jnp.ones(2)},
                     extras={checkpoint.HEALTH_NAME: b"{}"},
                     health={"healthy": True})


def test_emergency_save_records_health():
    from mxnet_tpu import fault
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d)
        try:
            mgr.enable_emergency_save(
                params_fn=lambda: {"w": jnp.ones(2)},
                step_fn=lambda: 7,
                health_fn=lambda: {"healthy": False, "loss": 1e30})
            os.kill(os.getpid(), __import__("signal").SIGTERM)
            for _ in range(1000):
                if fault.preempted():
                    break
            assert fault.preempted()
            h = mgr.read_health(7)
            assert h is not None and h["healthy"] is False
        finally:
            mgr.disable_emergency_save()
            fault.reset_preemption(clear_callbacks=True)
            fault.uninstall_preemption_handler()


def test_restore_latest_healthy_fallback_counts_each_corrupt_once():
    """Regression: the no-healthy-step fallback reuses the candidates
    the first pass already validated — a torn step is checksum-counted
    into checkpoint_fallbacks exactly ONCE, not once per pass."""
    from mxnet_tpu.observability import registry
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=10)
        mgr.save(1, {"w": jnp.ones(2)}, health={"healthy": False})
        mgr.save(2, {"w": jnp.ones(2) * 2}, health={"healthy": False})
        os.remove(os.path.join(d, "2", checkpoint.MANIFEST_NAME))  # torn
        c0 = registry().counter("checkpoint_fallbacks").value
        u0 = registry().counter("checkpoint_unhealthy_skips").value
        step, params = mgr.restore_latest_healthy({"w": jnp.zeros(2)})
        assert step == 1                      # merely-valid fallback
        np.testing.assert_array_equal(np.asarray(params["w"]), [1, 1])
        assert registry().counter("checkpoint_fallbacks").value == c0 + 1
        assert registry().counter(
            "checkpoint_unhealthy_skips").value == u0 + 1


def test_retention_pins_newest_healthy_step():
    """Regression: pruning must not evict the last known-good step — a
    streak of unhealthy saves (NaN storm, deferred health check) keeps
    the newest HEALTHY checkpoint alive beyond the quota."""
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        mgr.save(1, {"w": jnp.ones(2)}, health={"healthy": True})
        for s in (2, 3, 4):
            mgr.save(s, {"w": jnp.ones(2) * s}, health={"healthy": False})
        assert 1 in mgr.steps()               # pinned past the quota
        step, params = mgr.restore_latest_healthy({"w": jnp.zeros(2)})
        assert step == 1
        np.testing.assert_array_equal(np.asarray(params["w"]), [1, 1])
        # a new healthy save releases the pin: quota applies again
        mgr.save(5, {"w": jnp.ones(2) * 5}, health={"healthy": True})
        mgr.save(6, {"w": jnp.ones(2) * 6}, health={"healthy": True})
        assert 1 not in mgr.steps()


def test_retention_exact_quota_when_saves_healthy():
    """Regression: the last-known-good pin engages only during an
    unhealthy streak — steady-state healthy saves keep max_to_keep
    exact (max_to_keep=1 holds exactly one step)."""
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=1)
        for s in range(1, 5):
            mgr.save(s, {"w": jnp.ones(2) * s}, health={"healthy": True})
        assert mgr.steps() == [4]
        # pre-journal saves (no health=) behave identically
        mgr.save(5, {"w": jnp.ones(2) * 5})
        assert mgr.steps() == [5]


def test_health_extra_forbidden_even_without_health_kwarg():
    """Regression: a forged health.json cannot be smuggled through
    extras when health= is omitted — same input, same refusal."""
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d)
        with pytest.raises(mx.base.MXNetError):
            mgr.save(1, {"w": jnp.ones(2)},
                     extras={checkpoint.HEALTH_NAME: b'{"healthy": false}'})


# ------------------------------------------- quantization scheme (ISSUE 14)
def test_manifest_records_quantization_scheme():
    """int8-quantized params document their scheme in the manifest the
    way partition specs do: auto-derived from storage dtypes, readable
    back, absent for fp-only trees."""
    with tempfile.TemporaryDirectory() as d:
        params = {"w": jnp.arange(12, dtype=jnp.int8).reshape(3, 4),
                  "w_scale": jnp.ones((3,), jnp.float32),
                  "b": jnp.zeros((4,), jnp.float32)}
        checkpoint.save_sharded(d, 0, params)
        scheme = checkpoint.saved_quantization(d, 0)
        assert scheme["dtype"] == "int8"
        assert scheme["leaves"]["w"] == {"dtype": "int8",
                                         "shape": [3, 4]}
        # scale/bias leaves are fp — not part of the quantized set
        assert "w_scale" not in scheme["leaves"]
        # a matching template restores
        t = {"w": jnp.zeros((3, 4), jnp.int8),
             "w_scale": jnp.zeros((3,), jnp.float32),
             "b": jnp.zeros((4,), jnp.float32)}
        out = checkpoint.load_sharded(d, 0, t)
        assert int(np.asarray(out["w"]).sum()) == 66
        # fp-only trees record an EXPLICIT empty scheme ("known full
        # precision"); quantization=False omits the key entirely
        checkpoint.save_sharded(d, 1, {"a": jnp.zeros((2,), jnp.float32)})
        assert checkpoint.saved_quantization(d, 1) == {
            "dtype": None, "leaves": {}}
        checkpoint.save_sharded(d, 2, params, quantization=False)
        assert checkpoint.saved_quantization(d, 2) is None
        # no recorded scheme = UNKNOWN, never a refusal: the opted-out
        # int8 save still restores into a matching int8 template
        assert checkpoint.quantization_mismatches(
            os.path.join(d, "2"), t) == []
        out2 = checkpoint.load_sharded(d, 2, t)
        assert int(np.asarray(out2["w"]).sum()) == 66


def test_quantization_mismatch_refused_preflight():
    """A scheme-mismatched restore is refused PRE-FLIGHT with leaf names
    (instead of an XLA/orbax dtype-shape error), and the diagnosis names
    every direction: quantized-saved vs fp template, fp-saved vs
    quantized template, and shape drift."""
    with tempfile.TemporaryDirectory() as d:
        params = {"w": jnp.zeros((3, 4), jnp.int8),
                  "b": jnp.zeros((4,), jnp.float32)}
        checkpoint.save_sharded(d, 0, params)
        fp_t = {"w": jnp.zeros((3, 4), jnp.float32),
                "b": jnp.zeros((4,), jnp.float32)}
        with pytest.raises(mx.base.MXNetError, match="quantization"):
            checkpoint.load_sharded(d, 0, fp_t)
        diag = checkpoint.quantization_mismatches(
            os.path.join(d, "0"), fp_t)
        assert any("w" in line and "full precision" in line
                   for line in diag)
        # shape drift
        shp_t = {"w": jnp.zeros((6, 4), jnp.int8),
                 "b": jnp.zeros((4,), jnp.float32)}
        diag = checkpoint.quantization_mismatches(
            os.path.join(d, "0"), shp_t)
        assert any("template wants" in line for line in diag)
        # the reverse direction: fp checkpoint into a quantized template
        checkpoint.save_sharded(d, 1, fp_t)
        diag = checkpoint.quantization_mismatches(
            os.path.join(d, "1"), params)
        assert any("saved it full precision" in line for line in diag)
        with pytest.raises(mx.base.MXNetError, match="quantization"):
            checkpoint.load_sharded(d, 1, params)
