#!/usr/bin/env python
"""Captured-step dispatch-budget checker (ISSUE 4 acceptance; same tier-1
wiring pattern as chaos_check/check_trace).

Trains a small MLP N steps twice — once through the captured one-
executable step (`Trainer.capture`) and once through the imperative
record/backward/step() loop — asserting that

  * a warm captured step stays within the dispatch budget (<= 2
    trainer-issued device dispatches per step; in practice exactly 1,
    the `captured_step` launch),
  * the captured step never silently falls back to the imperative path,
  * the capture cache compiles ONCE (every warm step is a jit-cache hit),
  * final parameters MATCH the imperative run to tight tolerance.

ISSUE 5 extension — the warm-step budget also covers the INPUT side:
with the device prefetcher (`mxnet_tpu.prefetch.DevicePrefetcher`)
feeding the captured step, a warm step must perform ZERO synchronous
host->device transfers (the `prefetch_h2d_sync` counter stays flat),
while a host-path control batch must trip the same detector (proving
the zero is a measurement, not a dead counter). Runs over the 'ici'
mesh when >= 2 devices are available (the sharded-placement path),
single-device otherwise.

ISSUE 8 extension — the warm-step budget also covers the RULE-SHARDED
captured step: with a (2,2) ('dp','tp') shard plan (mxnet_tpu/shard/)
attached, a warm step must stay within the same dispatch budget, do zero
synchronous H2D when the device prefetcher feeds it, and genuinely
reduce per-device parameter bytes (>= 4 devices; skipped below that).

ISSUE 15 extension — the warm-step budget also covers the SHARDED-
EMBEDDING captured step: a DLRM-style model with a `ShardedEmbedding`
table row-sharded over 'tp' (vocab >> batch) must hold the same <=2
dispatch budget warm, do zero synchronous H2D with the device
prefetcher staging integer index batches, shrink per-device embedding
bytes (`embed_param_bytes_frac` < 1), and its backward must fit under
the bytes of ONE dense (V, D) table gradient — the in-HLO proof that
the sparse fast path never materialises an O(vocab) cotangent
(>= 4 devices; skipped below).

ISSUE 16 extension — the warm-step budget also covers the EXPERT-
PARALLEL MoE captured step: a `ShardedMoE` layer with its expert banks
row-sharded over 'tp' on the (2,2) mesh (the 2-all-to-all token-routing
path live, publishing as `moe_step`) must hold the same <=2 dispatch
budget warm and do zero synchronous H2D with the device prefetcher
(>= 4 devices; skipped below).

ISSUE 19 extension — the warm-step budget also covers the TIERED
embedding captured step: a `ShardedEmbedding(tiered=True, hbm_rows=N)`
table — host-resident cold rows behind a fixed device hot cache, fed
through the engine-prefetched `RowPrefetcher` — must hold the same <=2
dispatch budget on a warm all-hit step with ZERO synchronous H2D (a hot
step touches only slots already on device), and a forced miss step's
asynchronous row staging must stay bounded by the touched-row bytes
(>= 4 devices; skipped below).

ISSUE 6 extension — the warm-step budget also covers the SERVE decode
loop: a warm continuous-batching decode turn must be at most ONE device
dispatch (the shared ragged-paged-attention decode executable), the
decode executable must never RETRACE while slot occupancy and page
tables vary mid-flight (mixed-length admissions/evictions between
steps), and the KV page pool must return to zero pages in use once
every request completes.

ISSUE 12 extension — the serving FAST PATH: speculative decode holds
the same <=1 dispatch per warm turn with ZERO retraces of the widened
verify executable across varying draft acceptance (and must actually
accept drafts, or the zero would be vacuous); a prefix-cache-warm
request takes STRICTLY fewer prefill (decode-turn) dispatches than the
cold control while a cache-disabled control shows no reduction; page
refcounts return to exactly the cache-held baseline after every
request and to zero after close().

Standalone:

    JAX_PLATFORMS=cpu python tools/check_dispatch.py [--steps N] [--budget B]

exit 0 = within budget + parity, 1 = violation (details on stderr).
Prints one JSON line with the measured numbers on stdout.
"""
from __future__ import annotations

import json
import os
import sys

DEFAULT_STEPS = 5
DISPATCH_BUDGET = 2


def run(steps=DEFAULT_STEPS, budget=DISPATCH_BUDGET):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd, profiler

    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(16, 32).astype(np.float32))
    y = nd.array(rng.randint(0, 8, 16).astype(np.float32))
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()

    def build():
        mx.random.seed(0)
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        net(X)
        return net

    errors = []

    # -- captured ----------------------------------------------------------
    net_c = build()
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.05, "momentum": 0.9})
    step = tr_c.capture(lambda a, b: lossf(net_c(a), b).mean())
    step(X, y)                              # compile
    per_step = []
    for _ in range(steps):
        profiler.reset_dispatches()
        step(X, y)
        per_step.append(profiler.dispatch_count())
        if step.last_fallback_reason is not None:
            errors.append(f"captured step fell back: "
                          f"{step.last_fallback_reason}")
    worst = max(per_step)
    if worst > budget:
        errors.append(f"captured dispatch budget exceeded: {worst}/step "
                      f"(budget {budget}; per-step {per_step})")
    if step.cache_size != 1:
        errors.append(f"capture cache grew to {step.cache_size} entries "
                      f"for a fixed-shape loop (expected 1)")

    # -- imperative twin ---------------------------------------------------
    net_i = build()
    tr_i = gluon.Trainer(net_i.collect_params(), "sgd",
                         {"learning_rate": 0.05, "momentum": 0.9})
    with autograd.record():
        L = lossf(net_i(X), y).mean()
    L.backward()
    tr_i.step(16)                           # warm the fused-kernel cache
    imp_per_step = None
    for _ in range(steps):
        with autograd.record():
            L = lossf(net_i(X), y).mean()
        L.backward()
        profiler.reset_dispatches()
        tr_i.step(16)
        imp_per_step = profiler.dispatch_count()

    # both nets have now taken exactly steps+1 updates
    max_dev = 0.0
    for pc, pi in zip(net_c.collect_params().values(),
                      net_i.collect_params().values()):
        a, b = pc.data().asnumpy(), pi.data().asnumpy()
        dev = float(np.max(np.abs(a - b) / (np.abs(b) + 1e-6)))
        max_dev = max(max_dev, dev)
        if not np.allclose(a, b, rtol=1e-4, atol=1e-6):
            errors.append(f"parity violation on {pc.name}: "
                          f"max rel dev {dev:.2e}")
            break

    prefetch_res = _run_prefetch_phase(steps, errors)
    shard_res = _run_shard_phase(steps, errors)
    shard_res.update(_run_embed_phase(errors))
    shard_res.update(_run_moe_phase(errors))
    shard_res.update(_run_tiered_phase(errors))
    serve_res = _run_serve_phase(errors)
    serve_res.update(_run_serve_fastpath_phase(errors))
    serve_res.update(_run_serve_int8_phase(errors))

    res = {
        "steps": steps,
        "captured_dispatches_per_step": worst,
        "captured_per_step": per_step,
        "imperative_dispatches_per_step": imp_per_step,
        "budget": budget,
        "max_rel_dev": max_dev,
    }
    res.update(prefetch_res)
    res.update(shard_res)
    res.update(serve_res)
    res["errors"] = errors
    res["ok"] = not errors
    return res


def _run_prefetch_phase(steps, errors):
    """Zero-synchronous-H2D budget for the device-prefetched input path
    (ISSUE 5): warm captured steps fed by a DevicePrefetcher must leave
    the `prefetch_h2d_sync` counter flat; a host-path batch through the
    same warm step must move it (detector liveness control)."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.observability import registry
    from mxnet_tpu.prefetch import DevicePrefetcher

    sync = registry().counter("prefetch_h2d_sync")
    rng = np.random.RandomState(1)
    Xh = rng.randn(16, 32).astype(np.float32)
    yh = rng.randint(0, 8, 16).astype(np.float32)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()

    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net(nd.array(Xh))

    on_mesh = len(jax.devices()) >= 2
    if on_mesh:
        from mxnet_tpu.parallel.mesh import make_mesh
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore="ici")
        tr._kvstore.set_mesh(make_mesh({"dp": 2}))
    else:
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05})
    step = tr.capture(lambda a, b: lossf(net(a), b).mean())
    step(nd.array(Xh), nd.array(yh))            # compile

    # control: host batches through the WARM step must fire the detector
    # (mesh: per-step device_put sharding; 1-device: raw-numpy convert)
    base = sync.value
    if on_mesh:
        step(nd.array(Xh), nd.array(yh))
    else:
        step(Xh, yh)
    detector_fires = sync.value > base
    if not detector_fires:
        errors.append("sync-H2D detector did not fire on host-path "
                      "batches (the zero below would be vacuous)")

    # device-prefetched loop: every warm step must be transfer-free
    pf = DevicePrefetcher(((Xh, yh) for _ in range(steps)),
                          capture_spec=tr._kvstore if on_mesh else None)
    worst_sync = 0
    try:
        for xb, yb in pf:
            base = sync.value
            step(xb, yb)
            worst_sync = max(worst_sync, sync.value - base)
            if step.last_fallback_reason is not None:
                errors.append(f"prefetched captured step fell back: "
                              f"{step.last_fallback_reason}")
    finally:
        pf.close()
    if worst_sync:
        errors.append(f"device-prefetched warm step performed "
                      f"{worst_sync} synchronous H2D transfer(s) "
                      f"(budget 0)")
    return {
        "prefetch_sync_h2d_per_step": worst_sync,
        "prefetch_sync_h2d_budget": 0,
        "prefetch_detector_fires": detector_fires,
        "prefetch_mesh": on_mesh,
    }


def _run_shard_phase(steps, errors):
    """Rule-sharded captured step budget (ISSUE 8): on a 2-D (2,2) mesh
    with the DEFAULT_RULES shard plan, a warm captured step must stay
    within the same <=2 dispatch budget (in practice 1), do ZERO
    synchronous H2D with the device prefetcher feeding it, and actually
    reduce per-device parameter bytes below the replicated footprint.
    Needs >= 4 devices (the tier-1 conftest forks 8 CPU devices);
    single-device standalone runs report the phase skipped."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, profiler
    from mxnet_tpu.observability import registry
    from mxnet_tpu.prefetch import DevicePrefetcher

    if len(jax.devices()) < 4:
        return {"shard_mesh": False, "shard_dispatches_per_step": None,
                "shard_sync_h2d_per_step": None,
                "shard_param_bytes_frac": None}

    sync = registry().counter("prefetch_h2d_sync")
    rng = np.random.RandomState(2)
    Xh = rng.randn(16, 32).astype(np.float32)
    yh = rng.randint(0, 8, 16).astype(np.float32)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()

    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net(nd.array(Xh))

    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore="ici")
    plan = tr.shard(mesh={"dp": 2, "tp": 2})
    params = {p.name: p.data()._data
              for p in net.collect_params().values()}
    per_dev, total = plan.param_bytes_per_device(params)
    frac = per_dev / total
    if frac >= 1.0:
        errors.append(f"shard plan did not reduce per-device parameter "
                      f"bytes ({per_dev}/{total})")

    step = tr.capture(lambda a, b: lossf(net(a), b).mean())
    step(nd.array(Xh), nd.array(yh))            # compile
    worst = 0
    worst_sync = 0
    pf = DevicePrefetcher(((Xh, yh) for _ in range(steps)),
                          capture_spec=tr._kvstore)
    try:
        for xb, yb in pf:
            base = sync.value
            profiler.reset_dispatches()
            step(xb, yb)
            worst = max(worst, profiler.dispatch_count())
            worst_sync = max(worst_sync, sync.value - base)
            if step.last_fallback_reason is not None:
                errors.append(f"sharded captured step fell back: "
                              f"{step.last_fallback_reason}")
    finally:
        pf.close()
    if worst > DISPATCH_BUDGET:
        errors.append(f"sharded captured dispatch budget exceeded: "
                      f"{worst}/step (budget {DISPATCH_BUDGET})")
    if worst_sync:
        errors.append(f"sharded device-prefetched warm step performed "
                      f"{worst_sync} synchronous H2D transfer(s) "
                      f"(budget 0)")
    return {
        "shard_mesh": True,
        "shard_dispatches_per_step": worst,
        "shard_sync_h2d_per_step": worst_sync,
        "shard_param_bytes_frac": round(frac, 4),
    }


def _run_embed_phase(errors):
    """Sharded-embedding budget (ISSUE 15): a warm captured DLRM step —
    `ShardedEmbedding` table row-sharded over 'tp' on the (2,2) mesh,
    vocab >> batch so the bound below bites — must stay within the <=2
    dispatch budget, do ZERO synchronous H2D with the device prefetcher
    staging the INTEGER index batches, genuinely shrink per-device
    embedding bytes (`embed_param_bytes_frac` < 1; ~1/tp), and its
    backward must never materialise an O(vocab) dense gradient: the
    executable's temp allocation is asserted under the bytes ONE dense
    (V, D) table gradient would cost. Needs >= 4 devices; skipped
    cleanly below that. The model is deliberately tiny (one table, a
    1-unit tower, ~10 steps total) to stay inside the tier-1 verify
    window."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, profiler
    from mxnet_tpu.observability import registry
    from mxnet_tpu.prefetch import DevicePrefetcher
    from mxnet_tpu.shard import embedding as semb

    if len(jax.devices()) < 4:
        return {"embed_mesh": False, "embed_dispatches_per_step": None,
                "embed_sync_h2d_per_step": None,
                "embed_param_bytes_frac": None,
                "embed_backward_temp_frac": None}

    V, D, B, F = 4096, 16, 16, 4          # vocab >> B*F touched rows
    rng = np.random.RandomState(3)
    Ih = rng.randint(0, V, (B, F)).astype(np.int32)
    Xh = rng.randn(B, 4).astype(np.float32)
    yh = rng.randn(B).astype(np.float32)

    class _DLRM(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.ShardedEmbedding(V, D)
                self.top = gluon.nn.Dense(1, in_units=F * D + 4)

        def hybrid_forward(self, F_, idx, xd):
            e = self.embed(idx).reshape((idx.shape[0], -1))
            return self.top(F_.concat(e, xd, dim=1))

    mx.random.seed(0)
    net = _DLRM()
    net.initialize(mx.init.Xavier())
    net(nd.array(Ih, dtype=np.int32), nd.array(Xh))
    lossf = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="ici")
    plan = tr.shard(mesh={"dp": 2, "tp": 2})

    params = {p.name: p.data()._data
              for p in net.collect_params().values()}
    frac = semb.embed_param_bytes_frac(plan, params)
    if frac is None or frac >= 1.0:
        errors.append(f"shard plan did not reduce per-device embedding "
                      f"bytes (embed_param_bytes_frac={frac})")

    step = tr.capture(lambda a, b, c: lossf(net(a, b), c).mean())
    step(nd.array(Ih, dtype=np.int32), nd.array(Xh), nd.array(yh))
    if step.last_fallback_reason is not None:
        errors.append(f"sharded embed step fell back on compile: "
                      f"{step.last_fallback_reason}")

    sync = registry().counter("prefetch_h2d_sync")
    worst = 0
    worst_sync = 0
    pf = DevicePrefetcher(((Ih, Xh, yh) for _ in range(4)),
                          capture_spec=tr._kvstore)
    try:
        for ib, xb, yb in pf:
            base = sync.value
            profiler.reset_dispatches()
            step(ib, xb, yb)
            worst = max(worst, profiler.dispatch_count())
            worst_sync = max(worst_sync, sync.value - base)
            if step.last_fallback_reason is not None:
                errors.append(f"sharded embed step fell back: "
                              f"{step.last_fallback_reason}")
    finally:
        pf.close()
    if worst > DISPATCH_BUDGET:
        errors.append(f"sharded embed dispatch budget exceeded: "
                      f"{worst}/step (budget {DISPATCH_BUDGET})")
    if worst_sync:
        errors.append(f"device-prefetched integer index batches "
                      f"performed {worst_sync} synchronous H2D "
                      f"transfer(s) (budget 0)")

    # the no-dense-gradient proof: relower the warm executable from its
    # recorded aval skeleton (no python re-trace) and bound its TEMP
    # allocation under one dense (V, D) fp32 table gradient — at
    # vocab >> batch a backward that materialised the O(vocab) cotangent
    # could not fit the bound (actual temps are O(unique_rows * D))
    dense_grad_bytes = V * D * 4
    temp_frac = None
    from mxnet_tpu.observability import compilex
    ij = compilex.instrumented().get("sharded_embed_step")
    if ij is None or ij.last_abstract is None:
        errors.append("sharded_embed_step never registered with the "
                      "compile observatory — the sparse fast path did "
                      "not engage")
    else:
        args, kwargs = ij.last_abstract
        ma = ij.lower(*args, **kwargs).compile().memory_analysis()
        temp_frac = ma.temp_size_in_bytes / dense_grad_bytes
        if ma.temp_size_in_bytes >= dense_grad_bytes:
            errors.append(
                f"sharded embed backward temp allocation "
                f"{ma.temp_size_in_bytes} B >= one dense (V={V}, D={D}) "
                f"table gradient ({dense_grad_bytes} B) — the sparse "
                f"path is materialising an O(vocab) buffer")

    return {
        "embed_mesh": True,
        "embed_dispatches_per_step": worst,
        "embed_sync_h2d_per_step": worst_sync,
        "embed_param_bytes_frac": (None if frac is None
                                   else round(frac, 4)),
        "embed_backward_temp_frac": (None if temp_frac is None
                                     else round(temp_frac, 4)),
    }


def _run_moe_phase(errors):
    """Expert-parallel MoE budget (ISSUE 16): a warm captured step over
    a Dense stem + `ShardedMoE` layer — expert banks row-sharded over
    'tp' on the (2,2) mesh, so the 2-all-to-all token-routing path is
    live — must stay within the <=2 dispatch budget, do ZERO
    synchronous H2D with the device prefetcher staging the batches, and
    must compile as the `moe_step` executable (the routing fast path
    engaged, not the dense fallback). Needs >= 4 devices; skipped
    cleanly below that. Tiny shapes (one MoE layer, ~6 steps) to stay
    inside the tier-1 verify window."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, profiler
    from mxnet_tpu.observability import registry
    from mxnet_tpu.prefetch import DevicePrefetcher

    if len(jax.devices()) < 4:
        return {"moe_mesh": False, "moe_dispatches_per_step": None,
                "moe_sync_h2d_per_step": None}

    B, D = 8, 16
    rng = np.random.RandomState(5)
    Xh = rng.randn(B, D).astype(np.float32)
    yh = rng.randn(B, D).astype(np.float32)

    class _MoENet(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.proj = gluon.nn.Dense(D, in_units=D)
                self.moe = gluon.nn.ShardedMoE(
                    D, 16, num_experts=4, k=2, capacity_factor=1.25)

        def hybrid_forward(self, F_, x):
            return self.moe(self.proj(x))

    mx.random.seed(0)
    net = _MoENet()
    net.initialize(mx.init.Xavier())
    net(nd.array(Xh))
    lossf = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2})

    step = tr.capture(lambda a, b: lossf(net(a), b).mean())
    step(nd.array(Xh), nd.array(yh))
    if step.last_fallback_reason is not None:
        errors.append(f"moe step fell back on compile: "
                      f"{step.last_fallback_reason}")

    sync = registry().counter("prefetch_h2d_sync")
    worst = 0
    worst_sync = 0
    pf = DevicePrefetcher(((Xh, yh) for _ in range(4)),
                          capture_spec=tr._kvstore)
    try:
        for xb, yb in pf:
            base = sync.value
            profiler.reset_dispatches()
            step(xb, yb)
            worst = max(worst, profiler.dispatch_count())
            worst_sync = max(worst_sync, sync.value - base)
            if step.last_fallback_reason is not None:
                errors.append(f"moe step fell back: "
                              f"{step.last_fallback_reason}")
    finally:
        pf.close()
    if worst > DISPATCH_BUDGET:
        errors.append(f"moe dispatch budget exceeded: {worst}/step "
                      f"(budget {DISPATCH_BUDGET})")
    if worst_sync:
        errors.append(f"device-prefetched MoE batches performed "
                      f"{worst_sync} synchronous H2D transfer(s) "
                      f"(budget 0)")

    from mxnet_tpu.observability import compilex
    if compilex.instrumented().get("moe_step") is None:
        errors.append("moe_step never registered with the compile "
                      "observatory — the expert-parallel routing path "
                      "did not engage")

    return {
        "moe_mesh": True,
        "moe_dispatches_per_step": worst,
        "moe_sync_h2d_per_step": worst_sync,
    }


def _run_tiered_phase(errors):
    """Tiered-embedding budget (ISSUE 19): a captured DLRM step over a
    `ShardedEmbedding(tiered=True, hbm_rows=...)` table — host-resident
    cold rows, a fixed (hbm_rows, D)-per-shard device hot cache, the
    `RowPrefetcher` resolving next-step rows off the engine's background
    lane — must hold the same <=2 dispatch budget on a warm ALL-HIT step
    and do ZERO synchronous H2D there (the whole point of the tier: a
    hot step touches only cache slots already on device), while a forced
    MISS step's asynchronous row staging stays bounded by the touched-row
    bytes (cold stage + the cached all-hit zero block + one miss stage —
    never O(vocab)). Needs >= 4 devices; skipped cleanly below that.
    Tiny shapes (one table, 5 steps) to stay inside the tier-1 verify
    window."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, profiler
    from mxnet_tpu.observability import registry
    from mxnet_tpu.prefetch import RowPrefetcher
    from mxnet_tpu.shard import tiered as stiered

    if len(jax.devices()) < 4:
        return {"tiered_mesh": False, "tiered_dispatches_per_step": None,
                "tiered_sync_h2d_per_step": None,
                "tiered_async_h2d_bytes": None}

    V, D, B, F = 4096, 16, 16, 4
    HBM_ROWS = 48          # n_slots = tp * 48 = 96 >= B*F touched rows
    rng = np.random.RandomState(11)
    Ah = rng.randint(0, 2048, (B, F)).astype(np.int32)     # resident set
    Bh = rng.randint(2048, 4096, (B, F)).astype(np.int32)  # cold set
    yh = rng.randn(B, 1).astype(np.float32)

    class _DLRM(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.ShardedEmbedding(
                    V, D, tiered=True, hbm_rows=HBM_ROWS)
                self.top = gluon.nn.Dense(1, in_units=F * D)

        def hybrid_forward(self, F_, i):
            return self.top(self.embed(i).reshape((i.shape[0], -1)))

    mx.random.seed(0)
    net = _DLRM()
    net.initialize(mx.init.Xavier())
    lossf = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2})
    step = tr.capture(lambda i, y: lossf(net(i), y).mean())

    # batch sequence: cold-A (compile + first stage), 3x repeat-A (warm
    # ALL-HIT steps — the zero-H2D hot path under test), cold-B (a
    # forced full-miss step whose staging must stay bounded)
    seq = [Ah, Ah, Ah, Ah, Bh]
    src = ((nd.array(i, dtype=np.int32), nd.array(yh)) for i in seq)

    sync = registry().counter("prefetch_h2d_sync")
    worst = 0
    worst_sync = 0
    h2d0 = stiered._h2d_b.value
    pf = RowPrefetcher(src, tr, tables={0: net.embed})
    try:
        for k, (ib, yb) in enumerate(pf):
            base = sync.value
            profiler.reset_dispatches()
            step(ib, yb)
            if k >= 1:                    # every post-compile step
                worst = max(worst, profiler.dispatch_count())
            if 1 <= k <= 3:               # the warm all-hit steps
                worst_sync = max(worst_sync, sync.value - base)
            if step.last_fallback_reason is not None:
                errors.append(f"tiered step fell back: "
                              f"{step.last_fallback_reason}")
    finally:
        pf.close()
    h2d_total = stiered._h2d_b.value - h2d0

    if worst > DISPATCH_BUDGET:
        errors.append(f"tiered dispatch budget exceeded: {worst}/step "
                      f"(budget {DISPATCH_BUDGET})")
    if worst_sync:
        errors.append(f"tiered warm all-hit steps performed "
                      f"{worst_sync} synchronous H2D transfer(s) "
                      f"(budget 0)")
    # bounded async staging: slots (M,) int32 + one (M, D) fp32 row
    # block per stage, three stages total (cold-A, the cached all-hit
    # zero block, cold-B). A tier that shipped O(vocab) rows — or
    # restaged on every all-hit step — cannot fit this bound.
    M = B * F
    stage_bytes = M * 4 + M * D * 4
    bound = 3 * stage_bytes
    if not h2d_total:
        errors.append("tiered async H2D byte counter never moved — the "
                      "row-prefetch staging path did not engage")
    elif h2d_total > bound:
        errors.append(f"tiered async H2D traffic {h2d_total} B exceeds "
                      f"the touched-row bound ({bound} B = 3 stages of "
                      f"{stage_bytes} B) — the hot-cache tier is "
                      f"shipping more than the missed rows")

    return {
        "tiered_mesh": True,
        "tiered_dispatches_per_step": worst,
        "tiered_sync_h2d_per_step": worst_sync,
        "tiered_async_h2d_bytes": int(h2d_total),
    }


def _run_serve_phase(errors):
    """Serve decode-loop budget (ISSUE 6): warm continuous-batching decode
    turns are at most ONE dispatch (the shared paged-decode executable),
    the executable never retraces while slot occupancy and page tables
    vary, and the page pool returns to baseline when the traffic drains."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.models.transformer import TransformerNMT

    mx.random.seed(0)
    model = TransformerNMT(32, units=16, hidden=32, num_layers=1,
                           num_heads=2, max_length=32, dropout=0.0)
    model.initialize()
    srv = mx.serve.Server(model, slots=3, page_size=4, max_src_len=8,
                          max_new_tokens=12, engine_driven=False)
    sched = srv.scheduler
    rng = np.random.RandomState(0)

    # warm: one request through prefill + a decode step compiles both
    # executables
    srv.submit(rng.randint(4, 32, (5,)), max_new_tokens=4)
    sched.step()
    sched.step()
    warm_traces = srv.runtime.decode_traces

    # mixed-length traffic so occupancy and page-table contents vary
    # between steps (1 -> 3 active, staggered completions)
    for n, mt in ((3, 10), (7, 3), (6, 7), (4, 12), (8, 5)):
        srv.submit(rng.randint(4, 32, (n,)), max_new_tokens=mt)
    worst = 0
    decode_steps = 0
    for _ in range(100):
        if not sched.pending_work():
            break
        profiler.reset_dispatches()
        r = sched.step()
        if r.decoded and not r.admitted:
            # a pure decode turn: the only allowed launch is the decode
            # executable itself (admission turns additionally pay the
            # prefill executable per admitted request)
            worst = max(worst, profiler.dispatch_count())
            decode_steps += 1
    # capture BEFORE close(): Scheduler.shutdown clears queue/slots and
    # frees pages, which would mask a wedged scheduler or a leak
    undrained = sched.pending_work()
    retraces = srv.runtime.decode_traces - warm_traces
    leaked = srv.pool.in_use()
    srv.close()
    if undrained:
        errors.append("serve phase did not drain")
    if decode_steps == 0:
        errors.append("serve phase measured no pure decode turns")
    if worst > 1:
        errors.append(f"serve decode budget exceeded: {worst} "
                      f"dispatches/turn (budget 1)")
    if retraces:
        errors.append(f"serve decode executable retraced {retraces}x "
                      "across occupancy changes (budget 0)")
    if leaked:
        errors.append(f"serve phase leaked {leaked} KV pages")
    return {
        "serve_decode_dispatches_per_step": worst,
        "serve_decode_budget": 1,
        "serve_decode_steps_measured": decode_steps,
        "serve_decode_retraces": retraces,
        "serve_pages_leaked": leaked,
    }


def _run_serve_fastpath_phase(errors):
    """Serving fast-path budgets (ISSUE 12).

    SPECULATIVE decode: a width-(k+1) server's warm turns stay at ONE
    dispatch each, and the widened verify executable never retraces
    while draft acceptance varies (ragged window lengths are arguments,
    not shapes). Liveness: the run must actually accept drafted tokens
    (accept rate > 0) — a dead proposer would make the retrace zero
    vacuous.

    PREFIX cache: a request whose source+prompt prefix is cached must
    take STRICTLY fewer prefill (decode-turn) dispatches than the cold
    control — and the de-optimised control (cache disabled, identical
    request) must show NO reduction, proving the delta is the cache.
    Pages: after the traffic drains, only cache-held pages remain (each
    at refcount exactly 1), and close() returns the pool to zero."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.models.transformer import TransformerNMT

    def build(**kw):
        mx.random.seed(0)
        model = TransformerNMT(32, units=16, hidden=32, num_layers=1,
                               num_heads=2, max_length=48, dropout=0.0)
        model.initialize()
        # num_pages sized generously: page PRESSURE (cache eviction /
        # preemption) is unit-tested in tests/test_serve.py — here it
        # would let an eviction turn the warm request cold and make the
        # strictly-fewer comparison flaky
        return mx.serve.Server(model, slots=2, page_size=4, max_src_len=8,
                               max_new_tokens=8, max_prompt_len=12,
                               num_pages=16, engine_driven=False, **kw)

    rng = np.random.RandomState(3)
    src = rng.randint(4, 32, (6,)).astype(np.int32)
    prompt = rng.randint(4, 32, (9,)).astype(np.int32)

    def drain_turns(srv, *submits):
        handles = [srv.submit(s, max_new_tokens=m, prompt_tokens=p)
                   for s, m, p in submits]
        base = profiler.dispatch_count("serve_decode")
        srv.scheduler.run_until_idle()
        outs = [h.result(timeout=300) for h in handles]
        return outs, profiler.dispatch_count("serve_decode") - base

    # -- speculative server: warm-up compiles verify + prefill ---------
    srv = build(speculative_k=2)
    cold_out, cold_turns = drain_turns(srv, (src, 8, prompt))
    warm_traces = srv.runtime.verify_traces

    # warm request adopts the cached prefix; extra mixed traffic varies
    # occupancy AND draft acceptance (different prompts/sources accept
    # differently) while we hold the per-turn dispatch budget
    for s_, m_, p_ in ((src, 8, prompt),
                       (rng.randint(4, 32, (5,)), 6,
                        rng.randint(4, 32, (6,))),
                       (src, 4, prompt[:6])):
        srv.submit(s_, max_new_tokens=m_, prompt_tokens=p_)
    worst = 0
    decode_steps = 0
    for _ in range(200):
        if not srv.scheduler.pending_work():
            break
        profiler.reset_dispatches()
        r = srv.scheduler.step()
        if r.decoded and not r.admitted:
            worst = max(worst, profiler.dispatch_count())
            decode_steps += 1
    undrained = srv.scheduler.pending_work()
    retraces = srv.runtime.verify_traces - warm_traces
    drafted = srv.scheduler.spec_drafted
    accepted = srv.scheduler.spec_accepted
    accept_rate = accepted / max(drafted, 1)
    # warm twin of the cold request, measured alone for the strict
    # prefill-dispatch comparison
    warm_out, warm_turns = drain_turns(srv, (src, 8, prompt))
    in_use_drained = srv.pool.in_use()
    cache_pages = srv.prefix_cache.pages_held()
    bad_refs = [p for p in range(1, srv.pool.num_pages)
                if srv.pool.ref_count(p) not in (0, 1)]
    srv.close()
    leaked = srv.pool.in_use()

    if undrained:
        errors.append("serve fast-path phase did not drain")
    if decode_steps == 0:
        errors.append("serve fast-path phase measured no pure decode "
                      "turns")
    if worst > 1:
        errors.append(f"speculative decode budget exceeded: {worst} "
                      f"dispatches/turn (budget 1)")
    if retraces:
        errors.append(f"widened verify executable retraced {retraces}x "
                      f"across draft-acceptance variation (budget 0)")
    if accepted <= 0:
        errors.append("speculative phase accepted no drafted tokens "
                      "(the zero-retrace budget would be vacuous)")
    if warm_out != cold_out:
        errors.append("prefix-cached request output differs from the "
                      "cold control (bitwise-greedy contract broken)")
    if not warm_turns < cold_turns:
        errors.append(f"prefix cache did not reduce prefill dispatches: "
                      f"warm {warm_turns} vs cold {cold_turns} decode "
                      f"turns (budget: strictly fewer)")
    if in_use_drained != cache_pages:
        errors.append(f"drained fast-path pool holds {in_use_drained} "
                      f"pages but the cache owns {cache_pages} — "
                      f"stuck request references")
    if bad_refs:
        errors.append(f"pages with refcount > 1 after drain: {bad_refs}")
    if leaked:
        errors.append(f"serve fast-path phase leaked {leaked} KV pages "
                      f"after close()")

    # -- de-optimised control: cache disabled, identical request -------
    ctrl = build(speculative_k=2, prefix_cache=False)
    c1_out, c1_turns = drain_turns(ctrl, (src, 8, prompt))
    c2_out, c2_turns = drain_turns(ctrl, (src, 8, prompt))
    ctrl.close()
    if c1_out != cold_out or c2_out != cold_out:
        errors.append("cache-disabled control output differs (bitwise-"
                      "greedy contract broken)")
    if c2_turns < c1_turns:
        errors.append(f"cache-DISABLED control got faster on repeat "
                      f"({c2_turns} vs {c1_turns} turns) — the prefix "
                      f"reduction above proves nothing")

    return {
        "serve_spec_dispatches_per_turn": worst,
        "serve_spec_retraces": retraces,
        "serve_spec_accept_rate": round(accept_rate, 4),
        "serve_prefix_cold_turns": cold_turns,
        "serve_prefix_warm_turns": warm_turns,
        "serve_prefix_nocache_turns": c2_turns,
        "serve_fastpath_pages_leaked": leaked,
    }


def _run_serve_int8_phase(errors):
    """Quantized-serve budgets (ISSUE 14).

    DISPATCH/RETRACE: an int8-KV server's warm decode turns stay at ONE
    dispatch each and the quantized decode executable never retraces
    while occupancy and page tables vary (the per-page scale arrays are
    donated arguments, not shapes).

    CAPACITY: a fixed HBM byte budget must hold >= 1.9x the TOKENS of
    the fp32 pool (scale arrays included in the arithmetic, so the claim
    is honest — on this toolchain's fp32 pages it is ~3.5x; bf16 pages
    would make it ~1.9x), and the page accounting stays exact at that
    doubled capacity: `kv_pages_in_use` returns to 0 once the traffic
    drains and the server closes."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.models.transformer import TransformerNMT
    from mxnet_tpu.serve.quant import kv_page_bytes, token_capacity

    n_layers, heads, units, psize = 1, 2, 16, 4
    budget = 64 * kv_page_bytes(n_layers, psize, heads, units // heads,
                                "float32")
    cap_fp = token_capacity(budget, n_layers, psize, heads,
                            units // heads, "float32")
    cap_q = token_capacity(budget, n_layers, psize, heads,
                           units // heads, "int8")
    ratio = cap_q / cap_fp
    if ratio < 1.9:
        errors.append(f"int8 KV capacity ratio {ratio:.3f} < 1.9 at a "
                      f"fixed {budget}-byte budget ({cap_q} vs {cap_fp} "
                      f"tokens)")

    mx.random.seed(0)
    model = TransformerNMT(32, units=units, hidden=2 * units,
                           num_layers=n_layers, num_heads=heads,
                           max_length=32, dropout=0.0)
    model.initialize()
    srv = mx.serve.Server(model, slots=3, page_size=psize, max_src_len=8,
                          max_new_tokens=12, kv_dtype="int8",
                          kv_hbm_bytes=budget, engine_driven=False)
    if srv.pool.capacity * psize != cap_q:
        errors.append(f"kv_hbm_bytes pool sizing disagrees with "
                      f"token_capacity: {srv.pool.capacity * psize} vs "
                      f"{cap_q}")
    sched = srv.scheduler
    rng = np.random.RandomState(0)
    srv.submit(rng.randint(4, 32, (5,)), max_new_tokens=4)
    sched.step()
    sched.step()
    warm_traces = srv.runtime.decode_traces
    for n, mt in ((3, 10), (7, 3), (6, 7), (4, 12), (8, 5)):
        srv.submit(rng.randint(4, 32, (n,)), max_new_tokens=mt)
    worst = 0
    decode_steps = 0
    for _ in range(100):
        if not sched.pending_work():
            break
        profiler.reset_dispatches()
        r = sched.step()
        if r.decoded and not r.admitted:
            worst = max(worst, profiler.dispatch_count())
            decode_steps += 1
    undrained = sched.pending_work()
    retraces = srv.runtime.decode_traces - warm_traces
    # the prefix cache may legitimately hold pages after the drain; the
    # accounting bar is: nothing BEYOND the cache, and zero after close
    held = srv.pool.in_use()
    cache_pages = srv.prefix_cache.pages_held() if srv.prefix_cache \
        else 0
    srv.close()
    leaked = srv.pool.in_use()
    if undrained:
        errors.append("int8 serve phase did not drain")
    if decode_steps == 0:
        errors.append("int8 serve phase measured no pure decode turns")
    if worst > 1:
        errors.append(f"int8 serve decode budget exceeded: {worst} "
                      f"dispatches/turn (budget 1)")
    if retraces:
        errors.append(f"int8 serve decode executable retraced "
                      f"{retraces}x across occupancy changes (budget 0)")
    if held != cache_pages:
        errors.append(f"int8 pool holds {held} pages after drain but "
                      f"the cache owns {cache_pages} — stuck request "
                      f"references at 2x capacity")
    if leaked:
        errors.append(f"int8 serve phase leaked {leaked} KV pages "
                      f"after close()")
    return {
        "serve_int8_dispatches_per_step": worst,
        "serve_int8_retraces": retraces,
        "serve_int8_capacity_ratio": round(ratio, 4),
        "serve_int8_tokens_at_budget": cap_q,
        "serve_fp32_tokens_at_budget": cap_fp,
        "serve_int8_pages_leaked": leaked,
    }


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    steps, budget = DEFAULT_STEPS, DISPATCH_BUDGET
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    if "--budget" in argv:
        budget = int(argv[argv.index("--budget") + 1])
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    res = run(steps=steps, budget=budget)
    print(json.dumps(res))
    for err in res["errors"]:
        print(f"check_dispatch: {err}", file=sys.stderr)
    if res["errors"]:
        print("check_dispatch: FAIL", file=sys.stderr)
        return 1
    shard_txt = ("shard phase skipped (<4 devices)"
                 if not res["shard_mesh"] else
                 f"{res['shard_dispatches_per_step']} dispatch/step "
                 f"sharded (2,2) at "
                 f"{res['shard_param_bytes_frac']}x param bytes/dev; "
                 f"embed {res['embed_dispatches_per_step']} "
                 f"dispatch/step at {res['embed_param_bytes_frac']}x "
                 f"embed bytes/dev, backward temp "
                 f"{res['embed_backward_temp_frac']}x of one dense "
                 f"table grad; moe {res['moe_dispatches_per_step']} "
                 f"dispatch/step, {res['moe_sync_h2d_per_step']} sync "
                 f"H2D; tiered {res['tiered_dispatches_per_step']} "
                 f"dispatch/step, {res['tiered_sync_h2d_per_step']} "
                 f"sync H2D warm, {res['tiered_async_h2d_bytes']} B "
                 f"async staged")
    print(f"check_dispatch: OK ({res['captured_dispatches_per_step']} "
          f"dispatch/step captured vs "
          f"{res['imperative_dispatches_per_step']} imperative; "
          f"{res['prefetch_sync_h2d_per_step']} sync H2D/step with the "
          f"device prefetcher; {shard_txt}; "
          f"{res['serve_decode_dispatches_per_step']} dispatch/decode "
          f"turn, {res['serve_decode_retraces']} retraces serving; "
          f"speculative {res['serve_spec_dispatches_per_turn']} "
          f"dispatch/turn, {res['serve_spec_retraces']} retraces, "
          f"accept rate {res['serve_spec_accept_rate']}; prefix warm "
          f"{res['serve_prefix_warm_turns']} vs cold "
          f"{res['serve_prefix_cold_turns']} turns; int8 KV "
          f"{res['serve_int8_dispatches_per_step']} dispatch/turn at "
          f"{res['serve_int8_capacity_ratio']}x token capacity)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
