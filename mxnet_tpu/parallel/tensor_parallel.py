"""Tensor (model) parallelism over the 'tp' mesh axis.

Reference analogue: example/model-parallel (manual device placement of
layer halves). TPU-native: Megatron-style column/row parallel matmuls
expressed as sharding constraints — XLA's SPMD partitioner turns the
row-parallel contraction into a reduce-scatter/all-reduce over ICI; no
explicit collectives in user code.

Helpers here are pure functions over jax arrays plus a PartitionSpec rule
table, used by models/bert.py's tp mode and __graft_entry__.dryrun_multichip.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["column_parallel_dense", "row_parallel_dense", "shard_params",
           "tp_rules_transformer", "constrain"]


def constrain(x, mesh, spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def column_parallel_dense(x, weight, bias=None, mesh=None, tp_axis="tp"):
    """y = x @ W^T with W sharded over its OUTPUT dim -> y sharded on last
    axis. (Megatron column-parallel: no communication in forward.)"""
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return constrain(y, mesh, P(*([None] * (y.ndim - 1) + [tp_axis])))


def row_parallel_dense(x, weight, bias=None, mesh=None, tp_axis="tp"):
    """y = x @ W^T with W sharded over its INPUT dim; x arrives sharded on
    its last axis, the contraction forces an all-reduce (inserted by SPMD)."""
    y = jnp.matmul(x, weight.T)
    y = constrain(y, mesh, P(*([None] * y.ndim)))
    if bias is not None:
        y = y + bias
    return y


def tp_rules_transformer(tp_axis="tp", dp_axis=None):
    """PartitionSpec rules (regex -> spec) for a standard transformer:
    QKV & FFN-in column-parallel, attn-out & FFN-out row-parallel,
    embeddings sharded over vocab."""
    return [
        (r".*(query|key|value|qkv).*weight$", P(tp_axis, None)),
        (r".*(ffn_1|intermediate|fc1|inter).*weight$", P(tp_axis, None)),
        (r".*(proj|ffn_2|output_dense|fc2|out).*weight$", P(None, tp_axis)),
        (r".*(query|key|value|qkv|ffn_1|intermediate|fc1|inter).*bias$",
         P(tp_axis)),
        (r".*word_embed.*weight$", P(tp_axis, None)),
        (r".*", P()),
    ]


def shard_params(params, mesh, rules):
    """Apply the first matching rule per param name; device_put accordingly."""
    out = {}
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    for name, val in params.items():
        spec = P()
        for pat, s in compiled:
            if pat.match(name):
                spec = s
                break
        # drop axes that don't divide evenly (stay replicated)
        fixed = []
        for dim, ax in zip(val.shape, tuple(spec) + (None,) * val.ndim):
            if ax is not None and dim % mesh.shape[ax] != 0:
                ax = None
            fixed.append(ax)
        out[name] = jax.device_put(val, NamedSharding(mesh, P(*fixed)))
    return out
