#!/usr/bin/env python
"""Fleet recovery drill worker: one rank of a multi-process SIGKILL
drill over the file control plane.

Run under tools/launch.py with an elastic restart budget:

    python tools/launch.py -n 2 --max-restarts 1 \
        python tools/fleet_drill.py --dir /tmp/drill --die-rank 0

Each worker trains its own small model on a single-process CPU mesh (no
cross-process collectives — jax CPU has no multiprocess psum; what this
drill exercises is the CONTROL plane, not the data plane) while a
`FleetSupervisor` heartbeats into a shared `FileControlPlane` under
``<dir>/cp``. The worker whose rank is ``--die-rank`` SIGKILLs itself at
applied step ``--die-at`` on its FIRST incarnation only
(``MXTPU_RESTART_COUNT`` == 0). The drill then demands both halves of
fleet recovery:

  * **survivors** — detect the dead peer by heartbeat staleness, raise
    `HostLost` into the supervisor, bump the epoch, run the rollback
    agreement, restore the agreed step, and finish the run;
  * **the respawn** — the launcher re-execs the killed rank with
    ``MXTPU_RESTART_COUNT=1``; the reborn worker waits (bounded) for the
    published agreement and resumes from it instead of its own newest
    checkpoint.

Each worker ends by printing ONE JSON line:
    {"metric": "fleet_drill", "rank": r, "incarnation": k,
     "outcome": ..., "applied": n, "resumed_from": s,
     "host_lost_recoveries": m, "final_loss": x}
The drill passes when the launcher exits 0 and the survivor line shows
``host_lost_recoveries >= 1`` (tests/test_fleet.py, ``-m slow``).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def _force_cpu():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # fast fleet timing so the drill fits in a test window; explicit env
    # set by the caller still wins
    os.environ.setdefault("MXTPU_FLEET_HEARTBEAT_MS", "100")
    os.environ.setdefault("MXTPU_FLEET_DEADLINE_MS", "600")
    import jax
    jax.config.update("jax_platforms", "cpu")


BATCH = 8
FEATS = 16
CLASSES = 4
N_BATCHES = 4


def _build(seed):
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=FEATS),
            nn.Dense(CLASSES, in_units=8))
    net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    net(nd.zeros((1, FEATS)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore="ici", fused=False)
    return net, trainer


def _data(seed):
    import numpy as np
    from mxnet_tpu import nd
    rng = np.random.RandomState(seed)
    return [(nd.array(rng.randn(BATCH, FEATS).astype(np.float32)),
             nd.array(rng.randint(0, CLASSES, BATCH).astype(np.float32)))
            for _ in range(N_BATCHES)]


def run(args):
    from mxnet_tpu import fault, gluon, kvstore
    rank = args.rank
    world = args.world
    incarnation = int(os.environ.get("MXTPU_RESTART_COUNT", "0") or 0)
    cp = kvstore.FileControlPlane(os.path.join(args.dir, "cp"))

    if incarnation and args.join_wait_ms > 0:
        # reborn worker: give the survivors a moment to publish the
        # rollback agreement so the initial restore resumes from it
        # (best-effort — an expired wait degrades to own-newest restore)
        deadline = time.time() + args.join_wait_ms / 1000.0
        while time.time() < deadline:
            try:
                ep = int(cp.get("epoch") or 0)
            except ValueError:
                ep = 0
            if ep > 0 and cp.get(f"agreed/{ep}") is not None:
                break
            time.sleep(0.05)

    net, trainer = _build(args.seed)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    data = _data(args.seed + rank)
    factory = lambda: iter(data)    # noqa: E731
    from mxnet_tpu import autograd
    count = {"n": 0}

    def step_fn(batch):
        count["n"] += 1
        if incarnation == 0 and rank == args.die_rank and \
                count["n"] >= args.die_at:
            os.kill(os.getpid(), signal.SIGKILL)   # the drill's host loss
        x, y = batch
        with autograd.record():
            loss = lossf(net(x), y).mean()
        loss.backward()
        trainer.step(BATCH)
        if args.step_ms:
            time.sleep(args.step_ms / 1000.0)      # wall time: heartbeats
        return loss

    rep, sup = fault.run_fleet(
        trainer, step_fn, factory, args.steps, rank=rank, world=world,
        control=cp,
        checkpoint_dir=os.path.join(args.dir, f"ck-{rank}"),
        checkpoint_every=2, backoff_base=0.0, emergency_save=False)
    print(json.dumps({
        "metric": "fleet_drill",
        "rank": rank,
        "incarnation": incarnation,
        "outcome": rep["outcome"],
        "applied": rep["applied"],
        "resumed_from": rep["resumed_from"],
        "host_lost_recoveries": rep["recoveries"]["host_lost"],
        "final_loss": rep["final_loss"],
    }), flush=True)
    return 0 if rep["outcome"] == "completed" else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description="fleet SIGKILL drill worker")
    ap.add_argument("--rank", type=int,
                    default=int(os.environ.get("MXTPU_WORKER_ID", "0")))
    ap.add_argument("--world", type=int,
                    default=int(os.environ.get("MXTPU_NUM_WORKERS", "1")))
    ap.add_argument("--dir", required=True,
                    help="shared drill dir (control plane + checkpoints)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--die-at", type=int, default=6,
                    help="applied step at which --die-rank SIGKILLs "
                         "itself (first incarnation only)")
    ap.add_argument("--die-rank", type=int, default=0)
    ap.add_argument("--step-ms", type=float, default=100.0,
                    help="wall-time per step so heartbeat deadlines are "
                         "meaningful")
    ap.add_argument("--join-wait-ms", type=float, default=3000.0,
                    help="how long a respawned worker waits for the "
                         "published rollback agreement before resuming")
    args = ap.parse_args(argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    _force_cpu()
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
