"""Hung-step watchdog: a per-step deadline on the async engine.

A stuck collective or wedged engine task leaves the run silently hanging
— `engine.wait_for_all()` would block forever. `StepWatchdog.check()`
instead bounds the drain with `engine.wait_for_all_timeout`; on a stall
it writes a post-mortem snapshot (metrics registry + engine failure
report + the captured trace, when one is being recorded) and raises
`WatchdogTimeout`, so the supervisor restarts the task instead of
burning the reservation.

Wiring: `gluon.Trainer.step` calls `maybe_check()` each step, which is a
no-op unless ``MXTPU_STEP_TIMEOUT_MS`` is set (or a default watchdog was
installed via `set_default`). Loops with their own structure construct a
`StepWatchdog` directly.
"""
from __future__ import annotations

import json
import os
import time

from .. import _env
from ..base import MXNetError
from ..observability import registry as _obs_registry

__all__ = ["WatchdogTimeout", "StepWatchdog", "set_default", "maybe_check"]

_reg = _obs_registry()
_timeout_counter = _reg.counter("watchdog_timeouts")


def _warn_unwritable(path, exc):
    from ..log import get_logger
    get_logger("mxnet_tpu.fault").warning(
        "watchdog post-mortem not written (%s: %s); continuing without "
        "a snapshot", path, exc)


class WatchdogTimeout(MXNetError):
    """The engine failed to drain within the step deadline. The snapshot
    path (when one was written) is in `.snapshot_path`."""

    def __init__(self, msg, snapshot_path=None):
        self.snapshot_path = snapshot_path
        super().__init__(msg)


class StepWatchdog:
    """Per-step stall detection + post-mortem snapshot.

    `check()` is free when the engine is drained, and a pending queue
    with task COMPLETIONS since the previous check (a moving pipeline)
    is never flagged or blocked on. Only zero completions across a full
    inter-check window escalates to the bounded
    `engine.wait_for_all_timeout` drain, whose expiry dumps the snapshot
    and raises `WatchdogTimeout`.

    CONTRACT: the deadline is a bound on any single engine task that is
    the only thing in flight — set `timeout_ms` ABOVE the longest
    legitimate task (e.g. the largest async checkpoint save); a lone
    task that outlives both the inter-check window and the drain
    deadline is indistinguishable from a hang and is reported as one.

    timeout_ms: the escalation drain deadline (None reads
    ``MXTPU_STEP_TIMEOUT_MS``; 0 disables);
    snapshot_dir: where stall post-mortems are written."""

    def __init__(self, timeout_ms=None, snapshot_dir=None):
        if timeout_ms is None:
            timeout_ms = _env.env_ms("MXTPU_STEP_TIMEOUT_MS", 0.0)
        self.timeout_ms = int(timeout_ms)
        self.snapshot_dir = snapshot_dir or os.environ.get(
            "MXTPU_WATCHDOG_DIR", "/tmp/mxtpu_watchdog")
        self._last_completed = None

    @property
    def enabled(self):
        return self.timeout_ms > 0

    def check(self, step=None):
        """Returns 0 when the engine is drained, making progress, or
        drains within the deadline; raises `WatchdogTimeout` (after
        writing the post-mortem) on a genuine stall."""
        if not self.enabled:
            return 0
        from .. import engine
        completed = engine.tasks_completed()
        if engine.pending_tasks() == 0:
            self._last_completed = completed
            return 0
        if self._last_completed is None:
            # first observation of a busy queue: establish the window
            # baseline instead of escalating blind — a legitimate long
            # task started before this watchdog must get one full
            # inter-check window before it can be called a hang
            self._last_completed = completed
            return 0
        if completed > self._last_completed:
            self._last_completed = completed
            return 0
        stalled = engine.wait_for_all_timeout(self.timeout_ms)
        self._last_completed = engine.tasks_completed()
        if not stalled:
            return 0
        _timeout_counter.inc()
        path = self.dump_snapshot(step=step,
                                  reason=f"no engine progress, and the "
                                         f"pending queue did not drain "
                                         f"within {self.timeout_ms}ms")
        raise WatchdogTimeout(
            f"watchdog: step{'' if step is None else f' {step}'} exceeded "
            f"{self.timeout_ms}ms engine-drain deadline with no progress "
            f"(snapshot: {path or 'unwritable — see log'}; "
            f"engine: {engine.last_error() or 'n/a'})",
            snapshot_path=path)

    def dump_snapshot(self, step=None, reason=""):
        """Write the post-mortem: metrics snapshot, engine failure report
        and last_error as JSON, plus the in-flight trace when the tracer
        is capturing. Returns the JSON path — or None when the snapshot
        dir cannot be created or written: a read-only disk must not mask
        the `WatchdogTimeout` (or crash report) the snapshot decorates,
        so IO failures here log a warning instead of raising."""
        from .. import engine
        from ..observability import tracer
        stamp = time.strftime("%Y%m%d-%H%M%S")
        base = os.path.join(self.snapshot_dir, f"watchdog-{stamp}")
        trace_path = None
        try:
            os.makedirs(self.snapshot_dir, exist_ok=True)
            if tracer.ACTIVE:
                trace_path = base + ".trace.json"
                tracer.dump(trace_path)
        except OSError as e:
            _warn_unwritable(self.snapshot_dir, e)
            return None
        snap = {
            "time": time.time(),
            "step": step,
            "reason": reason,
            "engine_last_error": engine.last_error(),
            "engine_failures": engine.failures(),
            # per-task queue state (site/class/group/age/overdue, oldest
            # first): a stall post-mortem names WHICH task wedged the
            # drain and what was queued behind it — e.g. a stuck
            # background save ahead of high-priority decode turns
            "engine_pending": engine.pending_report(),
            "trace": trace_path,
            "metrics": _reg.snapshot(),
        }
        path = base + ".json"
        try:
            with open(path, "w") as f:
                json.dump(snap, f, indent=1, default=str)
        except OSError as e:
            _warn_unwritable(path, e)
            return None
        return path


_UNSET = object()      # not yet constructed (env decides on first use)
_DISABLED = object()   # explicitly uninstalled via set_default(None)
_default = _UNSET


def set_default(watchdog):
    """Install the watchdog `maybe_check()` consults. `None` genuinely
    uninstalls it — even with ``MXTPU_STEP_TIMEOUT_MS`` set, no default
    is reconstructed until the next `set_default(watchdog)`."""
    global _default
    _default = _DISABLED if watchdog is None else watchdog
    return watchdog


def maybe_check(step=None):
    """Trainer hook: check the default watchdog, constructing one from
    ``MXTPU_STEP_TIMEOUT_MS`` on first call. No-op (and near-free) when
    uninstalled or no deadline is configured — a 0-timeout watchdog is
    disabled."""
    global _default
    if _default is _DISABLED:
        return 0
    if _default is _UNSET:
        _default = StepWatchdog()
    return _default.check(step=step)
