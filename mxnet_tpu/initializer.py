"""Weight initializers (reference: python/mxnet/initializer.py).

Each initializer is a callable `init(name, shape, dtype, key) -> jax.Array`;
randomness comes from an explicit JAX key so deferred Gluon initialisation is
reproducible under `mx.random.seed`.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .base import _np_dtype

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "Mixed", "Load", "register", "create", "InitDesc"]

_REGISTRY = {}


class InitDesc(str):
    """Parameter-name descriptor passed to initializers (reference:
    python/mxnet/initializer.py InitDesc): a str subclass carrying the
    attr dict and the global-init flag, so name-dispatch initializers
    keep working on plain strings."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(initializer, **kwargs):
    if initializer is None:
        return Uniform(0.07)
    if isinstance(initializer, Initializer):
        return initializer
    if isinstance(initializer, str):
        name = initializer.lower()
        aliases = {"zeros": "zero", "ones": "one", "gaussian": "normal",
                   "msra": "msraprelu", "he": "msraprelu",
                   "glorot": "xavier"}
        name = aliases.get(name, name)
        if name not in _REGISTRY:
            raise ValueError(f"unknown initializer {initializer!r}; "
                             f"registered: {sorted(_REGISTRY)}")
        return _REGISTRY[name](**kwargs)
    raise TypeError(f"cannot create initializer from {type(initializer)}")


class Initializer:
    """Base initializer. Subclasses implement `_init(shape, dtype, key)`."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def init_array(self, name, shape, dtype, key):
        """Dispatch on parameter name like the reference InitDesc path:
        bias/gamma/beta/running stats get their canonical values."""
        dtype = _np_dtype(dtype)
        if name.endswith("gamma") or name.endswith("running_var") \
                or name.endswith("moving_var"):
            return jnp.ones(shape, dtype)
        if name.endswith("bias") or name.endswith("beta") \
                or name.endswith("running_mean") or name.endswith("moving_mean"):
            return jnp.zeros(shape, dtype)
        return self._init(shape, dtype, key)

    def _init(self, shape, dtype, key):
        raise NotImplementedError

    def __call__(self, name, shape, dtype, key):
        return self.init_array(name, shape, dtype, key)

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init(self, shape, dtype, key):
        return jnp.zeros(shape, dtype)


@register
class One(Initializer):
    def _init(self, shape, dtype, key):
        return jnp.ones(shape, dtype)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init(self, shape, dtype, key):
        return jnp.full(shape, self.value, dtype)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init(self, shape, dtype, key):
        return jax.random.uniform(key, shape, jnp.float32,
                                  -self.scale, self.scale).astype(dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init(self, shape, dtype, key):
        return (self.sigma * jax.random.normal(key, shape)).astype(dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init(self, shape, dtype, key):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.scale * q[:rows, :cols].reshape(shape)).astype(dtype)


@register
class Xavier(Initializer):
    """Glorot init (reference supports uniform/gaussian, avg/in/out)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _fans(self, shape):
        hw = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_out = shape[0] * hw
        fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
        return fan_in, fan_out

    def _init(self, shape, dtype, key):
        fan_in, fan_out = self._fans(shape)
        factor = {"avg": (fan_in + fan_out) / 2.0,
                  "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            out = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        else:
            out = scale * jax.random.normal(key, shape)
        return out.astype(dtype)


@register
class MSRAPrelu(Xavier):
    """He initialisation (reference: MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel for Deconvolution."""

    def _init(self, shape, dtype, key):
        weight = np.zeros(shape, dtype=np.float32)
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1.0, others 0 (reference: LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def init_array(self, name, shape, dtype, key):
        # bypass the generic name dispatch: '*bias' would zero-init and
        # defeat this initializer's whole purpose
        return self._init(shape, _np_dtype(dtype), key)

    def _init(self, shape, dtype, key):
        b = np.zeros(shape, dtype=np.float32)
        n = shape[0] // 4
        b[n:2 * n] = self.forget_bias
        return jnp.asarray(b, dtype)


class Mixed(Initializer):
    """Pattern-matched per-name initializers (reference: Mixed)."""

    def __init__(self, patterns, initializers):
        import re
        super().__init__()
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def init_array(self, name, shape, dtype, key):
        for pat, init_ in self.map:
            if pat.search(name):
                return init_.init_array(name, shape, dtype, key)
        raise ValueError(f"parameter {name} did not match any pattern")


class Load(Initializer):
    """Initialize from a dict of saved arrays by name (reference:
    initializer.Load): params present in the dict take their saved value,
    the rest fall back to `default_init` (or error)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        self.param = {}
        for name, arr in (param or {}).items():
            clean = name[4:] if name.startswith(("arg:", "aux:")) else name
            self.param[clean] = arr
        self.default_init = default_init
        self.verbose = verbose

    def init_array(self, name, shape, dtype, key):
        if name in self.param:
            arr = self.param[name]
            val = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
            if tuple(val.shape) != tuple(shape):
                raise ValueError(
                    f"Load: shape mismatch for {name}: saved {val.shape} "
                    f"vs required {shape}")
            if self.verbose:
                import logging
                logging.info("Load: initialized %s from saved params", name)
            return jnp.asarray(val, dtype=dtype)
        if self.default_init is not None:
            return self.default_init.init_array(name, shape, dtype, key)
        raise ValueError(f"Load: no saved value for {name} and no "
                         "default_init")


# NB: deliberately NOT register()ed — Load needs a saved-params dict and
# cannot be constructed from a bare name (reference does the same)


# convenience namespace mirroring mx.init.*
class _InitNamespace:
    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Orthogonal = Orthogonal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    Mixed = Mixed
    Load = Load
    Initializer = Initializer
    InitDesc = InitDesc


init = _InitNamespace
