"""Reference `contrib` operator kernels (upstream: src/operator/contrib/).

TPU-first redesigns, not translations:

- DeformableConvolution (deformable_convolution.cc / deformable_im2col.h):
  upstream materialises deformable im2col columns with a CUDA kernel, then
  GEMMs. Here the bilinear sampling is a vectorised gather over the whole
  output grid (one XLA gather) and the contraction is one einsum — the MXU
  does the GEMM, there is no per-pixel loop anywhere.
- Proposal / MultiProposal (proposal.cc, multi_proposal.cc): upstream sorts
  + NMS-es on the CPU/GPU with dynamic box counts. Here everything is
  STATIC-shape: fixed top-k budgets (lax.top_k) and the shared mask-NMS from
  detection_ops, so RPN proposal generation compiles into the same XLA
  program as the backbone (the SSD trick, applied to RCNN).
- fft / ifft (fft.cc): upstream wraps cuFFT; here it's jnp.fft with the
  reference's interleaved real/imag layout.
- count_sketch (count_sketch.cc): the hash-projection is a one-hot matmul
  (MXU) rather than scatter-adds.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .detection_ops import box_iou, nms, roi_align

__all__ = ["quantize", "quantize_v2", "dequantize", "requantize",
           "quantized_fully_connected", "quantized_conv",
           "quantized_pooling", "quantized_flatten",
           "deformable_convolution", "proposal", "multi_proposal",
           "fft", "ifft", "count_sketch", "roi_align_batched", "box_nms",
           "generate_base_anchors", "to_corner", "box_iou_generic",
           "multibox_prior_k", "multibox_target_k", "multibox_detection_k"]


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------
def _out_dim(size, k, stride, pad, dilate):
    return (size + 2 * pad - (dilate * (k - 1) + 1)) // stride + 1


def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_group=1, num_deformable_group=1):
    """Deformable conv v1 (upstream: src/operator/contrib/
    deformable_convolution.cc).

    data: (B, C, H, W); offset: (B, 2*dg*kh*kw, OH, OW) with channel
    layout [dg][kh*kw][dy, dx] (upstream's order); weight:
    (F, C/num_group, kh, kw); returns (B, F, OH, OW).

    Out-of-image samples contribute zero (upstream im2col semantics).
    """
    B, C, H, W = data.shape
    F = weight.shape[0]
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    dg = num_deformable_group
    oh = _out_dim(H, kh, sh, ph, dh)
    ow = _out_dim(W, kw, sw, pw, dw)
    K = kh * kw

    # base sampling positions: (OH, OW, K)
    oy = (jnp.arange(oh) * sh - ph)[:, None, None]
    ox = (jnp.arange(ow) * sw - pw)[None, :, None]
    ky = (jnp.arange(K) // kw) * dh
    kx = (jnp.arange(K) % kw) * dw
    base_y = (oy + ky[None, None, :]).astype(data.dtype)    # (OH, 1, K)
    base_x = (ox + kx[None, None, :]).astype(data.dtype)    # (1, OW, K)

    off = offset.reshape(B, dg, K, 2, oh, ow)
    dy = jnp.transpose(off[:, :, :, 0], (0, 3, 4, 1, 2))    # (B,OH,OW,dg,K)
    dx = jnp.transpose(off[:, :, :, 1], (0, 3, 4, 1, 2))
    sy = base_y[None, :, :, None, :] + dy                    # (B,OH,OW,dg,K)
    sx = base_x[None, :, :, None, :] + dx

    # bilinear gather with zero outside the image
    valid = ((sy > -1.0) & (sy < H) & (sx > -1.0) & (sx < W))
    y0 = jnp.floor(sy)
    x0 = jnp.floor(sx)
    wy = sy - y0
    wx = sx - x0
    y0i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
    y1i = jnp.clip(y0i + 1, 0, H - 1)
    x1i = jnp.clip(x0i + 1, 0, W - 1)
    # corner validity (zero-pad like upstream's bilinear in im2col)
    vy0 = (y0 >= 0) & (y0 <= H - 1)
    vy1 = (y0 + 1 >= 0) & (y0 + 1 <= H - 1)
    vx0 = (x0 >= 0) & (x0 <= W - 1)
    vx1 = (x0 + 1 >= 0) & (x0 + 1 <= W - 1)

    cg = C // dg         # channels per deformable group
    datag = data.reshape(B, dg, cg, H, W)

    def per_group(img, yg, xg, vg):
        # img: (cg, H, W); yg/xg/vg: (OH, OW, K) -> (OH, OW, K, cg)
        vals = img[:, yg, xg]                     # (cg, OH, OW, K)
        vals = jnp.where(vg[None], vals, 0.0)
        return jnp.moveaxis(vals, 0, -1)

    # vmap dg (img axis 0 / index axis 2), then batch
    per_image = jax.vmap(per_group, in_axes=(0, 2, 2, 2), out_axes=2)

    def gather_corner(yi, xi, v):
        # yi/xi/v: (B, OH, OW, dg, K) -> (B, OH, OW, dg, K, cg)
        return jax.vmap(per_image)(datag, yi, xi, v)

    v00 = gather_corner(y0i, x0i, valid & vy0 & vx0)
    v01 = gather_corner(y0i, x1i, valid & vy0 & vx1)
    v10 = gather_corner(y1i, x0i, valid & vy1 & vx0)
    v11 = gather_corner(y1i, x1i, valid & vy1 & vx1)
    wy_ = wy[..., None]
    wx_ = wx[..., None]
    # samples: (B, OH, OW, dg, K, cg)
    samples = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
               + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
    # columns in (C, K) order = deformable im2col
    cols = jnp.moveaxis(samples, -1, 4)           # (B, OH, OW, dg, cg, K)
    cols = cols.reshape(B, oh, ow, C, K)

    # grouped contraction on the MXU
    gc = C // num_group
    cols_g = cols.reshape(B, oh, ow, num_group, gc, K)
    w_g = weight.reshape(num_group, F // num_group, gc, kh * kw)
    out = jnp.einsum("bhwgck,gfck->bhwgf", cols_g, w_g)
    out = out.reshape(B, oh, ow, F).transpose(0, 3, 1, 2)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


# ---------------------------------------------------------------------------
# Proposal / MultiProposal (RPN)
# ---------------------------------------------------------------------------
def generate_base_anchors(feature_stride=16, scales=(8, 16, 32),
                          ratios=(0.5, 1, 2)):
    """Upstream GenerateAnchor (proposal.cc): base anchors centred on a
    feature_stride x feature_stride cell, corner format, numpy."""
    base = np.array([0, 0, feature_stride - 1, feature_stride - 1], np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    size = w * h
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            w_s, h_s = ws * s, hs * s
            anchors.append([cx - 0.5 * (w_s - 1), cy - 0.5 * (h_s - 1),
                            cx + 0.5 * (w_s - 1), cy + 0.5 * (h_s - 1)])
    return np.asarray(anchors, np.float32)


def _bbox_transform_inv(boxes, deltas):
    """Upstream BBoxTransformInv: apply (dx, dy, dw, dh) to corner boxes."""
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    pcx = dx * w + cx
    pcy = dy * h + cy
    pw = jnp.exp(jnp.clip(dw, -10.0, 10.0)) * w
    ph = jnp.exp(jnp.clip(dh, -10.0, 10.0)) * h
    return jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                      pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)], -1)


def multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                   rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                   scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                   feature_stride=16):
    """RPN proposals, batched (upstream: src/operator/contrib/
    multi_proposal.cc). STATIC shapes: fixed pre/post-NMS budgets.

    cls_prob: (B, 2A, H, W) [background scores first, foreground second —
    upstream layout]; bbox_pred: (B, 4A, H, W); im_info: (B, 3)
    [height, width, scale]. Returns (rois (B*post, 5) [batch_idx, x0..y1],
    scores (B*post, 1)). Slots past the surviving proposals repeat the
    best box (a static-shape stand-in for upstream's duplicated-sample
    padding; their score column is 0).
    """
    B, A2, H, W = cls_prob.shape
    A = A2 // 2
    base_np = generate_base_anchors(feature_stride, scales, ratios)
    if base_np.shape[0] != A:
        raise ValueError(
            f"cls_prob implies {A} anchors/position but scales x ratios "
            f"gives {base_np.shape[0]} ({len(scales)}x{len(ratios)})")
    base = jnp.asarray(base_np)
    shift_x = jnp.arange(W) * feature_stride
    shift_y = jnp.arange(H) * feature_stride
    sy, sx = jnp.meshgrid(shift_y, shift_x, indexing="ij")
    shifts = jnp.stack([sx, sy, sx, sy], -1).reshape(-1, 4)   # (HW, 4)
    anchors = (base[None, :, :] + shifts[:, None, :]).reshape(-1, 4)  # (HWA,4)
    n_total = anchors.shape[0]
    pre = min(rpn_pre_nms_top_n, n_total)
    post = min(rpn_post_nms_top_n, pre)

    def per_image(scores_map, deltas_map, info):
        # foreground scores: channels [A:2A] -> (H, W, A) -> (HWA,)
        fg = scores_map[A:].transpose(1, 2, 0).reshape(-1)
        deltas = deltas_map.reshape(A, 4, H, W).transpose(2, 3, 0, 1)
        deltas = deltas.reshape(-1, 4)
        boxes = _bbox_transform_inv(anchors, deltas)
        # clip to image
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0.0, info[1] - 1.0),
            jnp.clip(boxes[:, 1], 0.0, info[0] - 1.0),
            jnp.clip(boxes[:, 2], 0.0, info[1] - 1.0),
            jnp.clip(boxes[:, 3], 0.0, info[0] - 1.0)], -1)
        # min-size filter (scaled by im scale, upstream semantics)
        min_sz = rpn_min_size * info[2]
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        ok = (ws >= min_sz) & (hs >= min_sz)
        fg = jnp.where(ok, fg, -1.0)
        # static pre-NMS top-k
        top_s, top_i = lax.top_k(fg, pre)
        top_b = boxes[top_i]
        keep = nms(top_b, top_s, iou_threshold=threshold, max_out=post)
        kept_s = jnp.where(keep & (top_s > -1.0), top_s, 0.0)
        out_s, out_i = lax.top_k(kept_s, post)
        out_b = top_b[out_i]
        # empty slots repeat the best surviving box, score 0
        out_b = jnp.where((out_s > 0)[:, None], out_b,
                          jnp.broadcast_to(out_b[0], out_b.shape))
        return out_b, out_s[:, None]

    boxes, scores = jax.vmap(per_image)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype), post)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(B * post, 4)], -1)
    return rois, scores.reshape(B * post, 1)


def proposal(cls_prob, bbox_pred, im_info, **kwargs):
    """Single-image Proposal (upstream: proposal.cc) — batch must be 1;
    thin front over multi_proposal (identical math)."""
    assert cls_prob.shape[0] == 1, "Proposal expects batch 1; use " \
        "MultiProposal for batched inputs"
    return multi_proposal(cls_prob, bbox_pred, im_info, **kwargs)


# ---------------------------------------------------------------------------
# fft / ifft / count_sketch
# ---------------------------------------------------------------------------
def fft(data):
    """Upstream contrib.fft (fft.cc): (..., d) real -> (..., 2d) with
    interleaved [re, im] pairs along the last axis."""
    z = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([z.real, z.imag], -1)
    return out.reshape(*data.shape[:-1], 2 * data.shape[-1]).astype(
        jnp.float32)


def ifft(data):
    """Upstream contrib.ifft: (..., 2d) interleaved [re, im] -> (..., d)
    real part of the UNNORMALISED inverse transform — upstream does not
    divide by d, so ifft(fft(x)) == d * x (pinned in tests)."""
    d = data.shape[-1] // 2
    pairs = data.reshape(*data.shape[:-1], d, 2)
    z = lax.complex(pairs[..., 0].astype(jnp.float32),
                    pairs[..., 1].astype(jnp.float32))
    return (jnp.fft.ifft(z, axis=-1).real * d).astype(jnp.float32)


def count_sketch(data, h, s, out_dim):
    """Count sketch projection (upstream: count_sketch.cc): out[n, h[j]]
    += s[j] * data[n, j]. h: (d,) ints in [0, out_dim); s: (d,) signs.

    TPU design: the scatter-add is a one-hot (d, out_dim) matmul — the
    MXU eats it; no atomics, deterministic."""
    h = jnp.asarray(h).reshape(-1).astype(jnp.int32)
    s = jnp.asarray(s).reshape(-1).astype(data.dtype)
    proj = jax.nn.one_hot(h, out_dim, dtype=data.dtype) * s[:, None]
    return data @ proj


# ---------------------------------------------------------------------------
# reference-layout kernels SHARED by nd.contrib and sym.contrib (one
# implementation of each transform; the two front ends only adapt calling
# conventions)
# ---------------------------------------------------------------------------
def to_corner(x, fmt):
    """Box layout cast: 'corner' passthrough, 'center' (cx,cy,w,h) ->
    (x0,y0,x1,y1) (upstream box format attr)."""
    if fmt == "corner":
        return x
    if fmt == "center":
        half = x[..., 2:] * 0.5
        return jnp.concatenate([x[..., :2] - half, x[..., :2] + half], -1)
    raise ValueError(f"unknown box format {fmt!r}")


def box_iou_generic(lhs, rhs, format="corner"):
    """Pairwise IoU with shared leading batch dims (upstream:
    contrib.box_iou): (..., N, 4) x (..., M, 4) -> (..., N, M)."""
    a = to_corner(lhs, format)
    b = to_corner(rhs, format)
    if a.ndim <= 2 and b.ndim <= 2:
        return box_iou(a, b)
    if a.shape[:-2] != b.shape[:-2]:
        raise ValueError("box_iou batch dims must match "
                         f"({a.shape[:-2]} vs {b.shape[:-2]})")
    batch = a.shape[:-2]
    out = jax.vmap(box_iou)(a.reshape((-1,) + a.shape[-2:]),
                            b.reshape((-1,) + b.shape[-2:]))
    return out.reshape(batch + out.shape[-2:])


def multibox_prior_k(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                     offsets=(0.5, 0.5), steps=(-1.0, -1.0)):
    """Anchors for a feature map (upstream: contrib.MultiBoxPrior):
    data (B, C, H, W) -> (1, H*W*K, 4) normalised corners. `steps`
    overrides the implicit 1/feat cell stride (SSD presets pass the
    backbone stride explicitly)."""
    from .detection_ops import multibox_prior
    boxes = multibox_prior(data.shape[-2], data.shape[-1],
                           sizes=tuple(sizes), ratios=tuple(ratios),
                           offsets=tuple(offsets), steps=tuple(steps))
    boxes = jnp.asarray(boxes.clip(0.0, 1.0) if clip else boxes)
    return boxes[None]


def multibox_target_k(anchor, label, cls_pred, overlap_threshold=0.5,
                      variances=(0.1, 0.1, 0.2, 0.2)):
    """Upstream contrib.MultiBoxTarget triple: anchor (1, A, 4), label
    (B, M, 5), cls_pred (B, C+1, A) [shape source only] ->
    [loc_target (B, A*4), loc_mask (B, A*4), cls_target (B, A)]."""
    from .detection_ops import multibox_target
    cls_t, loc_t, loc_m = multibox_target(
        anchor[0], label, ious_threshold=overlap_threshold,
        variances=tuple(variances))
    B, A = cls_t.shape
    mask4 = jnp.broadcast_to(loc_m, loc_t.shape)
    return (loc_t.reshape(B, A * 4) * mask4.reshape(B, A * 4),
            mask4.reshape(B, A * 4), cls_t.astype(jnp.float32))


def multibox_detection_k(cls_prob, loc_pred, anchor, threshold=0.01,
                         nms_threshold=0.45, nms_topk=400, max_det=100,
                         variances=(0.1, 0.1, 0.2, 0.2)):
    """Upstream contrib.MultiBoxDetection with a STATIC max_det budget."""
    from .detection_ops import multibox_detection
    return multibox_detection(
        cls_prob, loc_pred, anchor[0], nms_threshold=nms_threshold,
        score_threshold=threshold, nms_topk=int(nms_topk),
        max_det=int(max_det), variances=tuple(variances))


# ---------------------------------------------------------------------------
# batched ROIAlign + reference-layout box_nms
# ---------------------------------------------------------------------------
def roi_align_batched(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
                      sample_ratio=2):
    """Upstream contrib.ROIAlign signature (roi_align.cc): data
    (B, C, H, W), rois (R, 5) [batch_idx, x0, y0, x1, y1] in input
    coords -> (R, C, ph, pw). Rows with batch_idx < 0 yield zeros
    (upstream's invalid-roi convention)."""
    idx = rois[:, 0].astype(jnp.int32)
    boxes = rois[:, 1:]
    feats = data[jnp.clip(idx, 0, data.shape[0] - 1)]  # (R, C, H, W)

    def one(f, b):
        return roi_align(f, b[None], out_size=pooled_size,
                         spatial_scale=spatial_scale,
                         sampling_ratio=sample_ratio)[0]

    out = jax.vmap(one)(feats, boxes)
    return jnp.where((idx >= 0)[:, None, None, None], out, 0.0)


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False):
    """Upstream contrib.box_nms (bounding_box.cc): data (..., N, K) rows
    holding [.., score, .., x0, y0, x1, y1, ..]; suppressed/invalid rows
    come back as all -1, survivors sorted by descending score (upstream's
    output convention)."""
    batched = data.ndim == 3
    arr = data if batched else data[None]
    _, N, K = arr.shape

    def per_batch(rows):
        scores = rows[:, score_index]
        boxes = lax.dynamic_slice_in_dim(rows, coord_start, 4, 1)
        valid = scores > valid_thresh
        s = jnp.where(valid, scores, -jnp.inf)
        if topk > 0:
            kth = lax.top_k(s, min(topk, N))[0][-1]
            s = jnp.where(s >= kth, s, -jnp.inf)
        cls = None
        if id_index >= 0 and not force_suppress:
            cls = rows[:, id_index]
            if background_id >= 0:
                s = jnp.where(cls == background_id, -jnp.inf, s)
        keep = nms(boxes, jnp.where(jnp.isfinite(s), s, -1e30), overlap_thresh,
                   max_out=N,
                   class_ids=cls.astype(jnp.int32) if cls is not None
                   else None)
        keep = keep & jnp.isfinite(s)
        # survivors first, by descending score; dead rows are -1
        order = jnp.argsort(jnp.where(keep, -scores, jnp.inf))
        out = jnp.where(keep[order][:, None], rows[order], -1.0)
        return out

    out = jax.vmap(per_batch)(arr)
    return out if batched else out[0]


# ---------------------------------------------------------------------------
# op-level quantization (reference: src/operator/quantization/quantize.cc,
# quantize_v2.cc, dequantize.cc, requantize.cc). The graph-level
# quantize_net (contrib/quantization.py) uses its own fused kernels; these
# are the documented op-level entry points with the upstream (q, min, max)
# three-output contract. int8 is symmetric (MXU-native), uint8 affine.
# ---------------------------------------------------------------------------
_INT32_QMAX = float(2 ** 31 - 1)


def int8_scale(amax):
    """Canonical symmetric-int8 scale (float value of one int8 unit).
    The ONE place this formula lives: the graph-level quantize_net
    (contrib/quantization.py _scale_of) aliases it, and the op-level
    surface below divides by it — keep them bit-identical."""
    return jnp.maximum(amax, 1e-12) / 127.0


def _scalar(r):
    return jnp.reshape(jnp.asarray(r, jnp.float32), ())


def _absmax(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


def quantize(x, mn, mx, out_type="uint8"):
    """float -> (quantized, out_min, out_max) inside [mn, mx]."""
    mn, mx = _scalar(mn), _scalar(mx)
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(mx - mn, 1e-12)
        q = jnp.clip(jnp.round((x - mn) * scale), 0, 255).astype(jnp.uint8)
        return q, mn, mx
    if out_type != "int8":
        raise MXNetError(f"quantize: out_type must be int8/uint8, "
                         f"got {out_type!r}")
    amax = _absmax(mn, mx)
    q = jnp.clip(jnp.round(x / int8_scale(amax)),
                 -127, 127).astype(jnp.int8)
    return q, -amax, amax


def quantize_v2(x, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Calibrated (attr ranges) or dynamic (data min/max) quantization."""
    if min_calib_range is None or max_calib_range is None:
        mn, mx = jnp.min(x).astype(jnp.float32), \
            jnp.max(x).astype(jnp.float32)
    else:
        mn, mx = _scalar(min_calib_range), _scalar(max_calib_range)
    return quantize(x, mn, mx, out_type=out_type)


def dequantize(q, mn, mx, out_type="float32"):
    """quantized -> float32; understands uint8 (affine), int8 (symmetric)
    and int32 (the quantized-matmul accumulator range)."""
    if out_type != "float32":
        raise MXNetError("dequantize: out_type must be float32")
    mn, mx = _scalar(mn), _scalar(mx)
    if q.dtype == jnp.uint8:
        return q.astype(jnp.float32) * (mx - mn) / 255.0 + mn
    if q.dtype == jnp.int8:
        return q.astype(jnp.float32) * int8_scale(_absmax(mn, mx))
    if q.dtype == jnp.int32:
        return q.astype(jnp.float32) * _absmax(mn, mx) / _INT32_QMAX
    raise MXNetError(f"dequantize: unsupported dtype {q.dtype}")


def requantize(q32, mn, mx, min_calib_range=None, max_calib_range=None):
    """int32 accumulator -> int8 under a new (calibrated or dynamic)
    range; returns (q8, -amax, amax)."""
    if q32.dtype != jnp.int32:
        raise MXNetError("requantize expects int32 input")
    f = dequantize(q32, mn, mx)
    if min_calib_range is not None and max_calib_range is not None:
        amax = _absmax(_scalar(min_calib_range), _scalar(max_calib_range))
    else:
        amax = jnp.max(jnp.abs(f))
    q = jnp.clip(jnp.round(f / int8_scale(amax)),
                 -127, 127).astype(jnp.int8)
    return q, -amax, amax


def split_quantized_bias(rest):
    """Decode the optional-bias positional contract shared by every
    quantized compute op: inputs are (data, weight[, bias], min_data,
    max_data, min_weight, max_weight), so a 4-long tail means no bias.
    The ONE place this decoding lives — nd and sym wrappers both call
    it."""
    return (None, rest) if len(rest) == 4 else (rest[0], rest[1:])


def _q8_scales(mn_d, mx_d, mn_w, mx_w):
    sd = int8_scale(_absmax(_scalar(mn_d), _scalar(mx_d)))
    sw = int8_scale(_absmax(_scalar(mn_w), _scalar(mx_w)))
    return sd, sw


def _q8_out_range(sd, sw):
    # the int32 accumulator's representable float range: one acc unit is
    # sd*sw, so dequantize(acc, -r, r) with r = sd*sw*INT32_QMAX recovers
    # acc*sd*sw exactly (see dequantize int32 branch)
    r = sd * sw * _INT32_QMAX
    return -r, r


def quantized_fully_connected(xq, wq, bias, mn_d, mx_d, mn_w, mx_w,
                              num_hidden=None):
    """int8 x int8 -> int32 FC (reference: quantized_fully_connected.cc).
    xq (..., K) int8, wq (num_hidden, K) int8, bias float32 or None
    (folded into the accumulator at the joint scale, upstream's int32-
    bias path). Returns (acc int32, out_min, out_max) such that
    dequantize(acc, out_min, out_max) == x_f @ w_f.T + bias up to
    quantization error."""
    if xq.dtype != jnp.int8 or wq.dtype != jnp.int8:
        raise MXNetError("quantized_fully_connected expects int8 inputs "
                         "(use quantize/quantize_v2 first)")
    sd, sw = _q8_scales(mn_d, mx_d, mn_w, mx_w)
    x2 = xq.reshape(-1, xq.shape[-1]) if xq.ndim > 2 else xq
    acc = lax.dot_general(x2, wq, (((x2.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    if bias is not None:
        acc = acc + jnp.round(bias.astype(jnp.float32)
                              / (sd * sw)).astype(jnp.int32)
    if xq.ndim > 2:
        acc = acc.reshape(xq.shape[:-1] + (wq.shape[0],))
    lo, hi = _q8_out_range(sd, sw)
    return acc, lo, hi


def quantized_conv(xq, wq, bias, mn_d, mx_d, mn_w, mx_w, kernel=None,
                   stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                   num_filter=None, layout="NCHW"):
    """int8 conv -> int32 accumulator (reference: quantized_conv.cc).
    xq NCHW/NHWC int8, wq (F, C, kh, kw) int8 (NCHW weight layout, like
    the reference). Returns (acc int32, out_min, out_max)."""
    if xq.dtype != jnp.int8 or wq.dtype != jnp.int8:
        raise MXNetError("quantized_conv expects int8 inputs")
    sd, sw = _q8_scales(mn_d, mx_d, mn_w, mx_w)
    st = tuple(stride) if not isinstance(stride, int) else (stride,) * 2
    pd = tuple(pad) if not isinstance(pad, int) else (pad,) * 2
    dl = tuple(dilate) if not isinstance(dilate, int) else (dilate,) * 2
    rhs = "OIHW"
    dn = lax.conv_dimension_numbers(
        xq.shape, wq.shape, (layout, rhs, layout))
    acc = lax.conv_general_dilated(
        xq, wq, st, [(pd[0], pd[0]), (pd[1], pd[1])],
        rhs_dilation=dl, dimension_numbers=dn,
        preferred_element_type=jnp.int32)
    if bias is not None:
        b32 = jnp.round(bias.astype(jnp.float32)
                        / (sd * sw)).astype(jnp.int32)
        acc = acc + (b32[None, :, None, None] if layout == "NCHW"
                     else b32[None, None, None, :])
    lo, hi = _q8_out_range(sd, sw)
    return acc, lo, hi


def quantized_pooling(xq, mn, mx, kernel=(2, 2), pool_type="max",
                      stride=None, pad=(0, 0), layout="NCHW"):
    """Pooling directly on the quantized domain (reference:
    quantized_pooling.cc): max-pool commutes with the monotone quantize
    map; avg-pool averages in int32 then rounds back. Ranges pass
    through unchanged."""
    if stride is None:
        stride = kernel
    st = tuple(stride) if not isinstance(stride, int) else (stride,) * 2
    kn = tuple(kernel) if not isinstance(kernel, int) else (kernel,) * 2
    pd = tuple(pad) if not isinstance(pad, int) else (pad,) * 2
    if layout == "NCHW":
        window = (1, 1) + kn
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1]))
    else:
        window = (1,) + kn + (1,)
        strides = (1,) + st + (1,)
        pads = ((0, 0), (pd[0], pd[0]), (pd[1], pd[1]), (0, 0))
    if xq.dtype == jnp.int8:
        ident, lo_q, hi_q = -128, -127, 127
    elif xq.dtype == jnp.uint8:
        ident, lo_q, hi_q = 0, 0, 255
    else:
        raise MXNetError(f"quantized_pooling: int8/uint8 input, "
                         f"got {xq.dtype}")
    if pool_type == "max":
        out = lax.reduce_window(xq, jnp.array(ident, xq.dtype), lax.max,
                                window, strides, pads)
        return out, _scalar(mn), _scalar(mx)
    if pool_type != "avg":
        raise MXNetError("quantized_pooling: pool_type max or avg")
    s = lax.reduce_window(xq.astype(jnp.int32), jnp.array(0, jnp.int32),
                          lax.add, window, strides, pads)
    n = kn[0] * kn[1]
    out = jnp.clip(jnp.round(s.astype(jnp.float32) / n),
                   lo_q, hi_q).astype(xq.dtype)
    return out, _scalar(mn), _scalar(mx)


def quantized_flatten(xq, mn, mx):
    """reference: quantized_flatten.cc — reshape, ranges untouched."""
    return xq.reshape(xq.shape[0], -1), _scalar(mn), _scalar(mx)
