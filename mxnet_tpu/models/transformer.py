"""Transformer NMT (Sockeye / gluonnlp transformer_en_de parity —
encoder-decoder with multi-head attention, label smoothing, beam search;
rebuilt TPU-first from the behavior of gluonnlp's model.transformer).

TPU-first choices:
  * sinusoidal position encodings precomputed as a static table;
  * fused QKV for self-attention, fused KV for cross-attention (MXU-sized
    matmuls);
  * causal self-attention in the decoder via ops.pallas_kernels
    flash_attention rides the Pallas kernels, with padding expressed as
    per-row kv valid lengths (scalar-prefetch masked flash path);
  * beam search is ONE jitted program: `lax.scan` over decode steps with
    static (batch, beam, max_len) shapes — no dynamic shapes, no host sync
    inside the loop.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply
from ..gluon import nn
from ..gluon.block import HybridBlock, extract_pure_fn, \
    is_symbolic as _is_symbol
from ..ops.pallas_kernels import flash_attention, \
    single_query_cached_attention
from ._sym_attention import sym_attention


def _sym_dim(s, axis):
    """Static dim of a traced Symbol via shape inference (needs shaped
    input Variables, like the BERT symbolic path)."""
    try:
        _, out_shapes, _ = s.infer_shape()
        return int(out_shapes[0][axis])
    except Exception as e:
        raise MXNetError(
            "transformer symbolic trace needs shaped input Variables "
            f"(sym.Variable(name, shape=...)): {e!r}") from e

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerNMT",
           "transformer_base", "beam_search", "beam_search_cached",
           "decode_step", "decoder_weights", "encoder_weights",
           "encode_memory", "decode_embed", "decode_project",
           "decoder_layer_qkv", "decoder_layer_self_post",
           "decoder_layer_cross", "decoder_layer_ffn", "sinusoid_table"]


def sinusoid_table(max_len, units):
    pos = np.arange(max_len)[:, None]
    dim = np.arange(units)[None, :]
    angle = pos / np.power(10000, (2 * (dim // 2)) / units)
    table = np.zeros((max_len, units), np.float32)
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def _split_heads(x, n_heads):
    b, s, d = x.shape
    return x.reshape(b, s, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, s, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * dh)


class SelfAttention(HybridBlock):
    """Fused-QKV self-attention; causal flag for decoder use."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads != 0:
            raise MXNetError("units must be divisible by num_heads")
        self._h = num_heads
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, in_units=units,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, in_units=units,
                                 prefix="proj_")
            self.dropout = nn.Dropout(dropout)

    def _symbolic_forward(self, F, x, valid_length):
        """Flash attention decomposed into named graph ops for export
        (shared decomposition: models/_sym_attention.py)."""
        qkv = self.qkv(x)
        d = self.qkv._units // 3
        q = F.slice_axis(qkv, axis=-1, begin=0, end=d)
        k = F.slice_axis(qkv, axis=-1, begin=d, end=2 * d)
        v = F.slice_axis(qkv, axis=-1, begin=2 * d, end=3 * d)
        out = sym_attention(F, q, k, v, self._h, d, length=valid_length,
                            causal=self._causal)
        return self.dropout(self.proj(out))

    def hybrid_forward(self, F, x, valid_length=None):
        if _is_symbol(x):
            return self._symbolic_forward(F, x, valid_length)
        h, causal = self._h, self._causal

        def attn(qkv_raw, *maybe_vl):
            q, k, v = jnp.split(qkv_raw, 3, axis=-1)
            q, k, v = (_split_heads(t, h) for t in (q, k, v))
            kv_len = maybe_vl[0].astype(jnp.int32) if maybe_vl else None
            out = flash_attention(q, k, v, causal=causal, kv_lengths=kv_len)
            return _merge_heads(out)

        inputs = [self.qkv(x)] +             ([valid_length] if valid_length is not None else [])
        return self.dropout(self.proj(_apply(attn, inputs)))


class CrossAttention(HybridBlock):
    """Decoder->encoder attention with fused KV projection."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._h = num_heads
        with self.name_scope():
            self.q = nn.Dense(units, flatten=False, in_units=units,
                              prefix="q_")
            self.kv = nn.Dense(2 * units, flatten=False, in_units=units,
                               prefix="kv_")
            self.proj = nn.Dense(units, flatten=False, in_units=units,
                                 prefix="proj_")
            self.dropout = nn.Dropout(dropout)

    def _symbolic_forward(self, F, x, memory, mem_valid_length):
        kv = self.kv(memory)
        d = self.kv._units // 2
        k = F.slice_axis(kv, axis=-1, begin=0, end=d)
        v = F.slice_axis(kv, axis=-1, begin=d, end=2 * d)
        out = sym_attention(F, self.q(x), k, v, self._h, d,
                            length=mem_valid_length)
        return self.dropout(self.proj(out))

    def hybrid_forward(self, F, x, memory, mem_valid_length=None):
        if _is_symbol(x):
            return self._symbolic_forward(F, x, memory, mem_valid_length)
        h = self._h

        def attn(q_raw, kv_raw, *maybe_vl):
            k, v = jnp.split(kv_raw, 2, axis=-1)
            q = _split_heads(q_raw, h)
            k = _split_heads(k, h)
            v = _split_heads(v, h)
            kv_len = maybe_vl[0].astype(jnp.int32) if maybe_vl else None
            out = flash_attention(q, k, v, kv_lengths=kv_len)
            return _merge_heads(out)

        inputs = [self.q(x), self.kv(memory)]
        if mem_valid_length is not None:
            inputs.append(mem_valid_length)
        return self.dropout(self.proj(_apply(attn, inputs)))


class _FFN(HybridBlock):
    def __init__(self, units, hidden, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn1 = nn.Dense(hidden, flatten=False, in_units=units,
                                 activation="relu", prefix="ffn1_")
            self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden,
                                 prefix="ffn2_")
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x):
        return self.dropout(self.ffn2(self.ffn1(x)))


class EncoderLayer(HybridBlock):
    def __init__(self, units, hidden, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attn = SelfAttention(units, num_heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden, dropout)
            self.ln2 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, valid_length=None):
        x = self.ln1(x + self.attn(x, valid_length))
        return self.ln2(x + self.ffn(x))


class DecoderLayer(HybridBlock):
    def __init__(self, units, hidden, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attn = SelfAttention(units, num_heads, dropout,
                                           causal=True)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.cross_attn = CrossAttention(units, num_heads, dropout)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden, dropout)
            self.ln3 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory, self_valid_length=None,
                       mem_valid_length=None):
        x = self.ln1(x + self.self_attn(x, self_valid_length))
        x = self.ln2(x + self.cross_attn(x, memory, mem_valid_length))
        return self.ln3(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden, num_heads, max_length=512,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._pos = sinusoid_table(max_length, units)
        self._scale = math.sqrt(units)
        with self.name_scope():
            self.dropout = nn.Dropout(dropout)
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(EncoderLayer(units, hidden, num_heads,
                                                 dropout))

    def collect_constants(self):
        out = super().collect_constants()
        out[self.prefix + "pos_table"] = NDArray(jnp.asarray(self._pos))
        return out

    def hybrid_forward(self, F, x, valid_length=None):
        if _is_symbol(x):
            s = _sym_dim(x, 1)
            pos = F.Variable(self.prefix + "pos_table",
                             shape=self._pos.shape)
            x = F.broadcast_add(
                x * self._scale,
                F.expand_dims(F.slice_axis(pos, axis=0, begin=0, end=s), 0))
        else:
            s = x.shape[1]
            pos, scale = self._pos, self._scale

            def add_pos(a):
                return a * scale + jnp.asarray(pos[:s])[None]

            x = _apply(add_pos, [x])
        x = self.dropout(x)
        for layer in self.layers:
            x = layer(x, valid_length)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden, num_heads, max_length=512,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._pos = sinusoid_table(max_length, units)
        self._scale = math.sqrt(units)
        with self.name_scope():
            self.dropout = nn.Dropout(dropout)
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(DecoderLayer(units, hidden, num_heads,
                                                 dropout))

    def collect_constants(self):
        out = super().collect_constants()
        out[self.prefix + "pos_table"] = NDArray(jnp.asarray(self._pos))
        return out

    def hybrid_forward(self, F, x, memory, self_valid_length=None,
                       mem_valid_length=None, position_offset=0):
        if _is_symbol(x):
            if position_offset != 0:
                raise MXNetError("symbolic decoder trace covers the "
                                 "teacher-forcing path (position_offset=0)")
            s = _sym_dim(x, 1)
            pos = F.Variable(self.prefix + "pos_table",
                             shape=self._pos.shape)
            x = F.broadcast_add(
                x * self._scale,
                F.expand_dims(F.slice_axis(pos, axis=0, begin=0, end=s), 0))
        else:
            s = x.shape[1]
            pos, scale = self._pos, self._scale
            off = position_offset

            def add_pos(a):
                return a * scale + jnp.asarray(pos[off:off + s])[None]

            x = _apply(add_pos, [x])
        x = self.dropout(x)
        for layer in self.layers:
            x = layer(x, memory, self_valid_length, mem_valid_length)
        return x


class TransformerNMT(HybridBlock):
    """Seq2seq NMT model. forward(src, tgt, src_valid_length=None) -> logits
    over the target vocabulary (teacher forcing). Source/target embeddings and
    the output projection share one weight matrix (Sockeye's
    weight-tying=src_trg_softmax)."""

    def __init__(self, vocab_size, units=512, hidden=2048, num_layers=6,
                 num_heads=8, max_length=512, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.vocab_size = vocab_size
        self._units = units
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.encoder = TransformerEncoder(num_layers, units, hidden,
                                              num_heads, max_length, dropout)
            self.decoder = TransformerDecoder(num_layers, units, hidden,
                                              num_heads, max_length, dropout)

    def encode(self, src, src_valid_length=None):
        return (self.encoder(self.embed(src), src_valid_length),
                src_valid_length)

    def project(self, x):
        """Tied output projection: logits = x @ embed.T."""
        if _is_symbol(x):
            from .. import symbol as F
            return F.batch_dot(x, F.transpose(self.embed.weight.var(),
                                              (1, 0)))
        w = self.embed.weight.data()
        return _apply(lambda a, ww: jnp.einsum("bsd,vd->bsv", a, ww), [x, w])

    def hybrid_forward(self, F, src, tgt, src_valid_length=None):
        memory, mem_vl = self.encode(src, src_valid_length)
        out = self.decoder(self.embed(tgt), memory, None, mem_vl)
        return self.project(out)


def transformer_base(vocab_size=36548, **kwargs):
    """WMT16 En-De base config (Sockeye transformer parity)."""
    return TransformerNMT(vocab_size, units=512, hidden=2048, num_layers=6,
                          num_heads=8, **kwargs)


# ---------------------------------------------------------------------------
# beam search — one jitted XLA program, static shapes
# ---------------------------------------------------------------------------
def beam_search(model: TransformerNMT, src, src_valid_length=None,
                beam_size=4, max_length=32, bos_id=2, eos_id=3, alpha=0.6):
    """Batched beam search decode.

    Returns (tokens (B, K, max_length) int32, scores (B, K) float32), beams
    sorted best-first. The whole search is one `lax.scan` over decode steps:
    at step t the decoder re-runs over the static (max_length)-padded prefix
    with a causal mask — static shapes, so XLA compiles exactly one program
    regardless of output length (KV-cache incremental decode is a further
    optimisation; reference decoders re-run the graph per step too).
    """
    fwd, params = extract_pure_fn(
        model, src, NDArray(jnp.zeros(
            (src.shape[0], max_length), jnp.int32)),
        *( [src_valid_length] if src_valid_length is not None else []))

    B = src.shape[0]
    K = beam_size
    V = model.vocab_size
    src_r = jnp.repeat(src._data, K, axis=0)              # (B*K, S)
    args = [src_r]
    if src_valid_length is not None:
        args.append(jnp.repeat(src_valid_length._data, K, axis=0))

    neg_inf = -1e9

    def step(carry, t):
        tokens, scores, done = carry                      # (B*K, L), (B*K,)
        logits = fwd(params, args[0], tokens, *args[1:])  # (B*K, L, V)
        logp = jax.nn.log_softmax(
            lax.dynamic_index_in_dim(logits, t, axis=1, keepdims=False)
            .astype(jnp.float32))                         # (B*K, V)
        # finished beams only extend with EOS at zero cost
        eos_only = jnp.full((V,), neg_inf).at[eos_id].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None], logp)
        cand = scores[:, None] + logp                     # (B*K, V)
        cand = cand.reshape(B, K * V)
        top_scores, top_idx = lax.top_k(cand, K)          # (B, K)
        beam_idx = top_idx // V                           # source beam
        tok_idx = (top_idx % V).astype(jnp.int32)
        flat_beam = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
        tokens = tokens[flat_beam]
        done = done[flat_beam]
        tokens = tokens.at[:, t + 1].set(
            jnp.where(done, tokens[:, t + 1], tok_idx.reshape(-1)))
        done = jnp.logical_or(done, tok_idx.reshape(-1) == eos_id)
        return (tokens, top_scores.reshape(-1), done), None

    tokens0 = jnp.zeros((B * K, max_length), jnp.int32).at[:, 0].set(bos_id)
    # only beam 0 of each batch is live at t=0 (all beams identical)
    scores0 = jnp.where(jnp.arange(B * K) % K == 0, 0.0, neg_inf)
    done0 = jnp.zeros((B * K,), bool)

    def run():
        (tokens, scores, done), _ = lax.scan(
            step, (tokens0, scores0, done0), jnp.arange(max_length - 1))
        lengths = jnp.argmax(tokens == eos_id, axis=1)
        lengths = jnp.where(lengths == 0, max_length, lengths + 1)
        lp = ((5.0 + lengths) / 6.0) ** alpha             # GNMT length norm
        norm = scores / lp
        norm = norm.reshape(B, K)
        order = jnp.argsort(-norm, axis=1)
        tokens = tokens.reshape(B, K, max_length)
        tokens = jnp.take_along_axis(tokens, order[:, :, None], axis=1)
        norm = jnp.take_along_axis(norm, order, axis=1)
        return tokens, norm

    tokens, norm = jax.jit(run)()
    return NDArray(tokens), NDArray(norm)


# ---------------------------------------------------------------------------
# KV-cached incremental decode (reference class: gluonnlp's decoder states /
# Sockeye inference caches). TPU-native: caches are static (B, H, Lmax, dh)
# buffers updated with dynamic_update_slice, attention over the cache is
# masked by the current step — so ONE compiled program serves every step,
# and beam search drops from O(L^3) to O(L^2) total attention work.
# ---------------------------------------------------------------------------
def _dense_w(dense):
    w = dense.weight.data()._data
    b = dense.bias.data()._data if dense.bias is not None else None
    return w, b


def _ln_w(ln):
    # epsilon rides as a WEAK-typed python float: a jnp.float32 here
    # becomes a strong scalar const baked into every serve trace
    # (graphlint MXTPU-G05); the weak literal folds into the same f32
    # rsqrt(var + eps) bitwise
    return (ln.gamma.data()._data, ln.beta.data()._data,
            float(ln._epsilon))


def decoder_weights(model):
    """Snapshot the decoder's weights as a pytree of jax arrays for the
    pure cached-decode program."""
    dec = model.decoder
    layers = []
    for layer in dec.layers:
        layers.append(dict(
            qkv=_dense_w(layer.self_attn.qkv),
            sproj=_dense_w(layer.self_attn.proj),
            q=_dense_w(layer.cross_attn.q),
            kv=_dense_w(layer.cross_attn.kv),
            cproj=_dense_w(layer.cross_attn.proj),
            ffn1=_dense_w(layer.ffn.ffn1),
            ffn2=_dense_w(layer.ffn.ffn2),
            ln1=_ln_w(layer.ln1), ln2=_ln_w(layer.ln2),
            ln3=_ln_w(layer.ln3)))
    first = dec.layers[0]
    return dict(embed=model.embed.weight.data()._data, layers=layers,
                pos=jnp.asarray(dec._pos), scale=float(dec._scale),
                num_heads=first.self_attn._h)


def encoder_weights(model):
    """Snapshot the encoder's weights as a pytree of jax arrays for the
    pure `encode_memory` program (the serving prefill executable)."""
    enc = model.encoder
    layers = []
    for layer in enc.layers:
        layers.append(dict(
            qkv=_dense_w(layer.attn.qkv),
            proj=_dense_w(layer.attn.proj),
            ffn1=_dense_w(layer.ffn.ffn1),
            ffn2=_dense_w(layer.ffn.ffn2),
            ln1=_ln_w(layer.ln1), ln2=_ln_w(layer.ln2)))
    first = enc.layers[0]
    return dict(embed=model.embed.weight.data()._data, layers=layers,
                pos=jnp.asarray(enc._pos), scale=float(enc._scale),
                num_heads=first.attn._h)


def encode_memory(weights, src, src_vl=None):
    """Pure-jax encoder forward (inference path, dropout off): src (B, S)
    int32 -> memory (B, S, U). Jittable — the serving prefill executable
    runs this + `precompute_memory_kv` as ONE program. Rides the same
    `flash_attention` the eager encoder uses, so the two paths share
    numerics."""
    h = weights["num_heads"]
    s = src.shape[1]
    x = _embed_rows(weights, src) * weights["scale"] \
        + weights["pos"][:s][None]
    kv_len = src_vl.astype(jnp.int32) if src_vl is not None else None
    for L in weights["layers"]:
        qkv = _affine(x, L["qkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (_split_heads(t, h) for t in (q, k, v))
        a = _merge_heads(flash_attention(q, k, v, kv_lengths=kv_len))
        x = _ln_apply(x + _affine(a, L["proj"]), L["ln1"])
        f = jnp.maximum(_affine(x, L["ffn1"]), 0)
        x = _ln_apply(x + _affine(f, L["ffn2"]), L["ln2"])
    return x


def _ln_apply(x, lnw):
    g, b, eps = lnw
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    return xc * jax.lax.rsqrt(var + eps) * g + b


def _affine(x, wb):
    # 3-tuple = per-output-channel int8 weight (ISSUE 14, serve/quant.py
    # snapshots): the dot runs over the exact int8 values converted
    # in-register and the scale lands as ONE epilogue multiply per
    # column — more accurate than dequantize-then-dot (integer-exact
    # accumulation) and fused by XLA into the matmul
    if len(wb) == 3:
        wq, b, s = wb
        y = (x @ wq.T.astype(x.dtype)) * s.astype(x.dtype)
    else:
        w, b = wb
        y = x @ w.T
    return y + b if b is not None else y


def _embed_rows(weights, idx):
    """Embedding gather; int8-quantized embeddings (ISSUE 14) dequantize
    the GATHERED rows with their per-vocab-row scales."""
    e = weights["embed"][idx]
    es = weights.get("embed_scale")
    if es is not None:
        e = e.astype(weights["pos"].dtype) * es[idx][..., None]
    return e


def _heads(x, h):
    b, u = x.shape
    return x.reshape(b, h, 1, u // h)


def precompute_memory_kv(weights, memory):
    """Cross-attention K/V for every layer, computed once per sequence:
    list of (k (B,H,S,dh), v (B,H,S,dh))."""
    out = []
    h = weights["num_heads"]
    for L in weights["layers"]:
        kv = _affine(memory, L["kv"])
        k, v = jnp.split(kv, 2, axis=-1)
        out.append((_split_heads(k, h), _split_heads(v, h)))
    return out


# Factored decode core (ISSUE 6 satellite): `decode_step` (the dense-cache
# beam-search path) and the serving engine's paged-KV decode
# (mxnet_tpu/serve/decode.py) compose the SAME per-layer functions below —
# only the KV-cache layout (dense (B,H,Lmax,dh) buffers vs paged page
# pools) and the attention gather differ, and the attention math itself is
# `ops.pallas_kernels.single_query_cached_attention` in both, so the two
# decoders are bitwise-identical on identical context (pinned by
# tests/test_serve.py::test_paged_decode_bitwise_parity).
def decode_embed(weights, tok_t, t):
    """Embed the current token(s) at position(s) t: tok_t (B,) int32,
    t scalar or (B,) int32 -> (B, U)."""
    return _embed_rows(weights, tok_t) * weights["scale"] \
        + weights["pos"][t]


def decode_project(weights, x):
    """Tied output projection for the decode path: (B, U) -> (B, V).
    int8-quantized embeddings (ISSUE 14): the per-vocab-row scale is the
    projection's per-OUTPUT-channel scale — one epilogue multiply after
    the integer-exact dot."""
    es = weights.get("embed_scale")
    if es is not None:
        return (x @ weights["embed"].T.astype(x.dtype)) * es.astype(x.dtype)
    return x @ weights["embed"].T


def decoder_layer_qkv(L, x):
    """Fused self-attention QKV projection: (B, U) -> three (B, U)."""
    qkv = _affine(x, L["qkv"])
    return jnp.split(qkv, 3, axis=-1)


def decoder_layer_self_post(L, x, attn):
    """Residual + proj + LN after self-attention. attn: (B, U) merged."""
    return _ln_apply(x + _affine(attn, L["sproj"]), L["ln1"])


def decoder_layer_cross(L, h, x, mk, mv, mem_vl=None):
    """Cross-attention over precomputed memory K/V (mk/mv (B,H,S,dh)) for
    one decode token x (B, U), including residual + LN."""
    qc = _heads(_affine(x, L["q"]), h)
    keep = None
    if mem_vl is not None:
        keep = (jnp.arange(mk.shape[2])[None, :]
                < mem_vl[:, None])[:, None, None, :]
    attn = _merge_heads(
        single_query_cached_attention(qc, mk, mv, keep))[:, 0]
    return _ln_apply(x + _affine(attn, L["cproj"]), L["ln2"])


def decoder_layer_cross_multi(L, h, x, mk, mv, mem_vl=None):
    """Cross-attention over precomputed memory K/V for a WINDOW of
    decode tokens (ISSUE 12's widened verify executable): x (B, W, U),
    mk/mv (B, H, S, dh). Per-token independent — each window row runs
    the same math `decoder_layer_cross` runs for its single token."""
    qc = _split_heads(_affine(x, L["q"]), h)          # (B, H, W, dh)
    keep = None
    if mem_vl is not None:
        keep = (jnp.arange(mk.shape[2])[None, :]
                < mem_vl[:, None])[:, None, None, :]
    attn = _merge_heads(
        single_query_cached_attention(qc, mk, mv, keep))  # (B, W, U)
    return _ln_apply(x + _affine(attn, L["cproj"]), L["ln2"])


def decoder_layer_ffn(L, x):
    """Position-wise FFN + residual + LN."""
    f = jnp.maximum(_affine(x, L["ffn1"]), 0)
    return _ln_apply(x + _affine(f, L["ffn2"]), L["ln3"])


def decode_step(weights, caches, mem_kv, mem_vl, tok_t, t):
    """One incremental decode step.

    caches: (k, v) stacks of shape (n_layers, B, H, Lmax, dh).
    tok_t: (B,) int32 current tokens; t: scalar step index.
    Returns (logits (B, V), new_caches)."""
    h = weights["num_heads"]
    x = decode_embed(weights, tok_t, t)
    k_caches, v_caches = caches
    new_k, new_v = [], []
    lmax = k_caches.shape[3]
    step_mask = (jnp.arange(lmax) <= t)[None, None, None, :]
    for li, L in enumerate(weights["layers"]):
        # self-attention over the cache
        q, k, v = decoder_layer_qkv(L, x)
        qh, kh, vh = (_heads(a, h) for a in (q, k, v))
        kc = lax.dynamic_update_slice(k_caches[li], kh, (0, 0, t, 0))
        vc = lax.dynamic_update_slice(v_caches[li], vh, (0, 0, t, 0))
        new_k.append(kc)
        new_v.append(vc)
        attn = _merge_heads(
            single_query_cached_attention(qh, kc, vc, step_mask))[:, 0]
        x = decoder_layer_self_post(L, x, attn)
        # cross-attention over the precomputed memory K/V
        mk, mv = mem_kv[li]
        x = decoder_layer_cross(L, h, x, mk, mv, mem_vl)
        # ffn
        x = decoder_layer_ffn(L, x)
    logits = decode_project(weights, x)
    return logits, (jnp.stack(new_k), jnp.stack(new_v))


def beam_search_cached(model, src, src_valid_length=None, beam_size=4,
                       max_length=32, bos_id=2, eos_id=3, alpha=0.6):
    """Beam search with KV caches: one jitted `lax.scan`, O(L) attention
    per step instead of re-running the decoder over the whole prefix.
    Same contract as `beam_search`."""
    weights = decoder_weights(model)
    B = src.shape[0]
    K = beam_size
    V = model.vocab_size
    h = weights["num_heads"]
    u = weights["embed"].shape[1]
    dh = u // h
    n_layers = len(weights["layers"])

    memory, _ = model.encode(src, src_valid_length)
    # project K/V once per source sequence, THEN repeat per beam — the
    # repeated copies are byte-identical, so projecting after repeat would
    # do beam_size-times redundant MXU work
    mem_kv = [(jnp.repeat(mk, K, axis=0), jnp.repeat(mv, K, axis=0))
              for mk, mv in precompute_memory_kv(weights, memory._data)]
    mem_vl = (jnp.repeat(src_valid_length._data, K, axis=0)
              if src_valid_length is not None else None)

    neg_inf = -1e9

    def step(carry, t):
        tokens, scores, done, caches = carry
        tok_t = tokens[:, t]
        logits, caches = decode_step(weights, caches, mem_kv, mem_vl,
                                     tok_t, t)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        eos_only = jnp.full((V,), neg_inf).at[eos_id].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None], logp)
        cand = (scores[:, None] + logp).reshape(B, K * V)
        top_scores, top_idx = lax.top_k(cand, K)
        beam_idx = top_idx // V
        tok_idx = (top_idx % V).astype(jnp.int32)
        flat_beam = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
        tokens = tokens[flat_beam]
        done = done[flat_beam]
        k_c, v_c = caches
        caches = (k_c[:, flat_beam], v_c[:, flat_beam])
        tokens = tokens.at[:, t + 1].set(
            jnp.where(done, tokens[:, t + 1], tok_idx.reshape(-1)))
        done = jnp.logical_or(done, tok_idx.reshape(-1) == eos_id)
        return (tokens, top_scores.reshape(-1), done, caches), None

    tokens0 = jnp.zeros((B * K, max_length), jnp.int32).at[:, 0].set(bos_id)
    scores0 = jnp.where(jnp.arange(B * K) % K == 0, 0.0, neg_inf)
    done0 = jnp.zeros((B * K,), bool)
    caches0 = (jnp.zeros((n_layers, B * K, h, max_length, dh),
                         weights["embed"].dtype),) * 2

    def run():
        (tokens, scores, done, _), _ = lax.scan(
            step, (tokens0, scores0, done0, caches0),
            jnp.arange(max_length - 1))
        lengths = jnp.argmax(tokens == eos_id, axis=1)
        lengths = jnp.where(lengths == 0, max_length, lengths + 1)
        lp = ((5.0 + lengths) / 6.0) ** alpha
        norm = (scores / lp).reshape(B, K)
        order = jnp.argsort(-norm, axis=1)
        tokens = tokens.reshape(B, K, max_length)
        tokens = jnp.take_along_axis(tokens, order[:, :, None], axis=1)
        norm = jnp.take_along_axis(norm, order, axis=1)
        return tokens, norm

    tokens, norm = jax.jit(run)()
    return NDArray(tokens), NDArray(norm)
