"""mx.image tests (reference: tests/python/unittest/test_image.py)."""
import io as _io
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _img(h=12, w=10, seed=0):
    return (np.random.RandomState(seed).rand(h, w, 3) * 255).astype(np.uint8)


def test_imread_png(tmp_path):
    from PIL import Image
    arr = _img()
    p = str(tmp_path / "x.png")
    Image.fromarray(arr).save(p)
    img = mx.image.imread(p)
    np.testing.assert_array_equal(img.asnumpy(), arr)


def test_imread_grayscale(tmp_path):
    from PIL import Image
    arr = _img()
    p = str(tmp_path / "x.png")
    Image.fromarray(arr).save(p)
    g = mx.image.imread(p, flag=0)
    assert g.shape == (12, 10, 1)


def test_imdecode_bytes():
    from PIL import Image
    arr = _img()
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    img = mx.image.imdecode(buf.getvalue())
    np.testing.assert_array_equal(img.asnumpy(), arr)


def test_imresize_and_resize_short():
    x = mx.nd.array(_img(20, 10).astype(np.float32))
    y = mx.image.imresize(x, 5, 8)
    assert y.shape == (8, 5, 3)
    z = mx.image.resize_short(x, 6)
    assert min(z.shape[0], z.shape[1]) == 6


def test_crops_and_augmenters():
    x = mx.nd.array(_img(16, 16).astype(np.float32))
    c, box = mx.image.center_crop(x, (8, 8))   # reference returns (img, box)
    assert c.shape[:2] == (8, 8)
    augs = mx.image.CreateAugmenter((3, 8, 8), rand_mirror=True,
                                    mean=np.zeros(3, np.float32),
                                    std=np.ones(3, np.float32))
    out = x
    for a in augs:
        out = a(out)
    assert out.shape[-1] == 3 or out.shape[0] == 3
