"""Profiler (reference: python/mxnet/profiler.py).

`set_config/start/stop/dumps` map onto jax.profiler (XLA/TPU traces viewable
in TensorBoard/Perfetto), plus a host-side op tally from the imperative
dispatch path for `dumps()` parity.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

__all__ = ["set_config", "start", "stop", "pause", "resume", "dumps",
           "dump", "Scope", "record_op"]

_state = {"dir": "/tmp/mxtpu_profile", "running": False,
          "ops": defaultdict(lambda: [0, 0.0]), "t0": None}


def set_config(profile_all=False, profile_symbolic=True,
               profile_imperative=True, profile_memory=True, profile_api=True,
               filename=None, **kwargs):
    if filename:
        _state["dir"] = filename.rsplit("/", 1)[0] if "/" in filename \
            else "."


def start():
    _state["running"] = True
    _state["t0"] = time.time()
    try:
        jax.profiler.start_trace(_state["dir"])
    except Exception:
        pass


def stop():
    if not _state["running"]:
        return
    _state["running"] = False
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass


def pause():
    _state["running"] = False


def resume():
    _state["running"] = True


def record_op(name, seconds):
    if _state["running"]:
        entry = _state["ops"][name]
        entry[0] += 1
        entry[1] += seconds


def dumps(reset=False):
    lines = [f"{'op':<40}{'calls':>10}{'total_ms':>14}"]
    for name, (calls, total) in sorted(_state["ops"].items(),
                                       key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40}{calls:>10}{total * 1e3:>14.3f}")
    if reset:
        _state["ops"].clear()
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Reference profiler.dump: write the op table to stderr (the
    reference writes its json trace file; jax.profiler owns trace files
    here, so dump surfaces the host-side op accounting)."""
    import sys
    print(dumps(), file=sys.stderr)


@contextlib.contextmanager
def Scope(name="profile"):
    with jax.profiler.TraceAnnotation(name):
        yield
