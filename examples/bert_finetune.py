"""Finetune BERT for sentence-pair classification (reference workflow:
gluonnlp finetune_classifier.py). A pretrained-style BERTModel gets a
BERTClassifier head; the whole train step — encoder, pooler, head, loss,
backward, update — compiles to one XLA program via hybridize().

Synthetic task (offline env): classify whether two segments share a
marker token. Exercises the real finetuning mechanics: segment ids,
valid_length masking, head-only warmup then full finetune.

Usage: python examples/bert_finetune.py [--epochs N] [--smoke]
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models.bert import BERTModel, BERTClassifier


def make_batch(rng, batch, seq_len, vocab):
    """Half the pairs share marker token 3 in both segments (label 1)."""
    tok = rng.randint(4, vocab, (batch, seq_len))
    labels = rng.randint(0, 2, batch)
    half = seq_len // 2
    seg = onp.concatenate([onp.zeros((batch, half), onp.int32),
                           onp.ones((batch, seq_len - half), onp.int32)], 1)
    for i, y in enumerate(labels):
        if y:
            tok[i, rng.randint(1, half)] = 3
            tok[i, rng.randint(half, seq_len)] = 3
    vl = rng.randint(seq_len // 2, seq_len + 1, batch)
    return (nd.array(tok, dtype="int32"), nd.array(seg, dtype="int32"),
            nd.array(vl, dtype="int32"),
            nd.array(labels.astype(onp.float32)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    if args.smoke:
        units, layers, seq_len, steps, epochs = 32, 2, 16, 4, 1
    else:
        units, layers, seq_len, steps, epochs = 64, 4, 32, 30, args.epochs

    bert = BERTModel(vocab_size=128, units=units, hidden_size=units * 4,
                     num_layers=layers, num_heads=4, max_length=seq_len,
                     dropout=0.1)
    model = BERTClassifier(bert, num_classes=2, dropout=0.1)
    model.initialize(mx.init.Normal(0.05))
    model.hybridize()

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(model.collect_params(), "adam",
                               {"learning_rate": args.lr})
    rng = onp.random.RandomState(0)
    for epoch in range(epochs):
        total, correct, lsum = 0, 0, 0.0
        for _ in range(steps):
            tok, seg, vl, y = make_batch(rng, args.batch_size, seq_len, 128)
            with mx.autograd.record():
                logits = model(tok, seg, vl)
                loss = loss_fn(logits, y)
            loss.backward()
            trainer.step(args.batch_size)
            lsum += float(loss.mean().asnumpy())
            pred = logits.asnumpy().argmax(1)
            correct += int((pred == y.asnumpy()).sum())
            total += args.batch_size
        print(f"epoch {epoch}: loss={lsum / steps:.4f} "
              f"acc={correct / total:.3f}")
    if not args.smoke:
        assert correct / total > 0.75, correct / total
    print("finetune done")


if __name__ == "__main__":
    main()
