"""Module API tests (SURVEY.md §2 #13): bind/init/fit/predict/checkpoint."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, io as mio


def _softmax_mlp():
    data = sym.Variable("data")
    w1, b1 = sym.Variable("w1"), sym.Variable("b1")
    h = sym.Activation(sym.FullyConnected(data, w1, b1, num_hidden=16),
                       act_type="relu")
    w2, b2 = sym.Variable("w2"), sym.Variable("b2")
    out = sym.FullyConnected(h, w2, b2, num_hidden=3)
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"))


def _toy_iter(n=96, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 6).astype(np.float32)
    w = rng.randn(6, 3).astype(np.float32)
    y = np.argmax(x @ w, 1).astype(np.float32)
    return mio.NDArrayIter(x, y, batch_size=batch, label_name="softmax_label")


def test_bind_and_forward():
    mod = mx.mod.Module(_softmax_mlp(), data_names=("data",),
                        label_names=("softmax_label",))
    it = _toy_iter()
    mod.bind([(d.name, d.shape) for d in it.provide_data],
             [(l.name, l.shape) for l in it.provide_label])
    mod.init_params()
    batch = next(iter(it))
    mod.forward(batch)
    out = mod.get_outputs()[0]
    assert out.shape == (32, 3)
    np.testing.assert_allclose(out.asnumpy().sum(1), np.ones(32), rtol=1e-4)


def test_fit_converges():
    mod = mx.mod.Module(_softmax_mlp())
    it = _toy_iter()
    mod.fit(it, num_epoch=30, optimizer="adam",
            optimizer_params={"learning_rate": 0.01})
    m = mx.metric.Accuracy()
    mod.score(_toy_iter(), m)
    assert m.get()[1] > 0.8, m.get()


def test_predict():
    mod = mx.mod.Module(_softmax_mlp())
    it = _toy_iter()
    mod.fit(it, num_epoch=2)
    preds = mod.predict(_toy_iter())
    assert preds.shape[0] == 96


def test_predict_trims_pad():
    """n % batch != 0: the padded last batch's wrap-around rows must not
    appear in the concatenated prediction."""
    mod = mx.mod.Module(_softmax_mlp())
    it = _toy_iter()
    mod.fit(it, num_epoch=2)
    rng = np.random.RandomState(3)
    x = rng.randn(50, 6).astype(np.float32)
    it50 = mio.NDArrayIter(x, np.zeros(50, np.float32), batch_size=32,
                           label_name="softmax_label")
    preds = mod.predict(it50)
    assert preds.shape == (50, 3)
    it32 = mio.NDArrayIter(x[:32], np.zeros(32, np.float32), batch_size=32,
                           label_name="softmax_label")
    np.testing.assert_allclose(preds.asnumpy()[:32],
                               mod.predict(it32).asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_save_load_checkpoint():
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "mod")
        mod = mx.mod.Module(_softmax_mlp())
        it = _toy_iter()
        mod.fit(it, num_epoch=2)
        mod.save_checkpoint(prefix, 2)
        arg1, _ = mod.get_params()
        mod2 = mx.mod.Module.load(prefix, 2)
        it2 = _toy_iter()
        mod2.bind([(dd.name, dd.shape) for dd in it2.provide_data],
                  [(l.name, l.shape) for l in it2.provide_label])
        mod2.init_params(arg_params=mod2._loaded_params[0],
                         aux_params=mod2._loaded_params[1])
        arg2, _ = mod2.get_params()
        for k in arg1:
            np.testing.assert_allclose(arg1[k].asnumpy(), arg2[k].asnumpy(),
                                       rtol=1e-5)


# ---------------------------------------------------------------------------
# BucketingModule (reference: module/bucketing_module.py)
# ---------------------------------------------------------------------------
def _bucket_sym_gen(seq_len):
    """Embedding -> mean over time -> FC -> SoftmaxOutput; parameter shapes
    are independent of seq_len, so buckets can share them."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed_w = mx.sym.Variable("embed_weight")
    fc_w = mx.sym.Variable("fc_weight")
    fc_b = mx.sym.Variable("fc_bias")
    x = mx.sym.Embedding(data, weight=embed_w, input_dim=10, output_dim=4)
    x = mx.sym.mean(x, axis=1)
    x = mx.sym.FullyConnected(x, weight=fc_w, bias=fc_b, num_hidden=2)
    out = mx.sym.SoftmaxOutput(x, label)
    return out, ["data"], ["softmax_label"]


def _bucket_batch(seq_len, batch=4, seed=0):
    rng = np.random.RandomState(seed + seq_len)
    data = rng.randint(0, 10, (batch, seq_len)).astype(np.float32)
    label = (data.sum(axis=1) % 2).astype(np.float32)
    return mx.io.DataBatch([mx.nd.array(data)], [mx.nd.array(label)],
                           bucket_key=seq_len)


def test_bucketing_module_two_lengths_share_params():
    mod = mx.mod.BucketingModule(_bucket_sym_gen, default_bucket_key=8)
    b8 = _bucket_batch(8)
    b5 = _bucket_batch(5)
    mod.bind([("data", (4, 8))], [("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})

    # train alternating buckets; the same parameter ARRAYS must be updated
    arg0 = {k: v.asnumpy().copy() for k, v in mod.get_params()[0].items()}
    for step in range(4):
        batch = b8 if step % 2 == 0 else b5
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    arg1 = mod.get_params()[0]
    assert set(arg1) == {"embed_weight", "fc_weight", "fc_bias"}
    assert any(not np.allclose(arg0[k], arg1[k].asnumpy()) for k in arg0)

    # the bucket-5 module sees the SAME objects (shared storage)
    m8 = mod._buckets[8]
    m5 = mod._buckets[5]
    for name in ("embed_weight", "fc_weight", "fc_bias"):
        assert m8._exec.arg_dict[name] is m5._exec.arg_dict[name]

    # forward on either bucket gives consistent predictions for equal input
    # padded to its length: run the same sequence content through both
    mod.forward(b5, is_train=False)
    out5 = mod.get_outputs()[0].asnumpy()
    assert out5.shape == (4, 2)
    np.testing.assert_allclose(out5.sum(axis=1), np.ones(4), rtol=1e-5)


def test_bucketing_module_default_key_routing():
    mod = mx.mod.BucketingModule(_bucket_sym_gen, default_bucket_key=6)
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
    mod.init_params()
    batch = _bucket_batch(6)
    batch.bucket_key = None          # no key -> default bucket
    mod.forward(batch, is_train=False)
    assert mod._curr_bucket_key == 6
    assert mod.get_outputs()[0].shape == (4, 2)


def test_sequential_module():
    """SequentialModule chains modules; backward flows input grads between
    them (reference: python/mxnet/module/sequential_module.py)."""
    from mxnet_tpu.module import SequentialModule
    rs = np.random.RandomState(3)
    x = rs.randn(64, 6).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)

    with mx.name.NameManager():
        d1 = sym.Variable("data")
        feat = sym.Activation(sym.FullyConnected(d1, num_hidden=16,
                                                 name="m1fc"),
                              act_type="relu")
        d2 = sym.Variable("mid")
        out = sym.SoftmaxOutput(sym.FullyConnected(d2, num_hidden=2,
                                                   name="m2fc"),
                                sym.Variable("softmax_label"),
                                name="softmax")
    seq = SequentialModule()
    seq.add(mx.mod.Module(feat, data_names=["data"], label_names=[]))
    seq.add(mx.mod.Module(out, data_names=["mid"],
                          label_names=["softmax_label"]))
    it = mio.NDArrayIter(x, y, batch_size=16)
    seq.fit(it, optimizer="adam",
            optimizer_params={"learning_rate": 0.02}, num_epoch=10)

    m = mx.metric.create("acc")
    it.reset()
    for batch in it:
        seq.forward(batch, is_train=False)
        seq.update_metric(m, batch.label)
    assert m.get()[1] > 0.9, m.get()
    arg_p, _ = seq.get_params()
    assert "m1fc_weight" in arg_p and "m2fc_weight" in arg_p


def test_set_params_before_first_forward():
    """bind -> set_params -> score (the classic deploy flow) must work
    without a prior forward/init_params: upstream documents set_params
    as init_params(arg_params=..., force_init=...)."""
    import numpy as np
    from mxnet_tpu import nd, sym
    from mxnet_tpu.module import Module
    from mxnet_tpu.io import NDArrayIter

    x = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(x, num_hidden=3, name="fc"),
        sym.Variable("softmax_label"), name="softmax")
    rs = np.random.RandomState(0)
    X = rs.randn(8, 4).astype(np.float32)
    y = np.zeros(8, np.float32)
    it = NDArrayIter({"data": X}, {"softmax_label": y}, batch_size=8)
    args = {"fc_weight": nd.array(rs.randn(3, 4).astype(np.float32)),
            "fc_bias": nd.array(np.zeros(3, np.float32))}

    mod = Module(net, data_names=["data"], label_names=["softmax_label"])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.set_params(args, {})          # no forward has happened yet
    mod.forward(next(iter(it)), is_train=False)
    out = mod.get_outputs()[0].asnumpy()
    want = X @ args["fc_weight"].asnumpy().T
    want = np.exp(want - want.max(1, keepdims=True))
    want /= want.sum(1, keepdims=True)
    np.testing.assert_allclose(out, want, rtol=1e-4)


def test_set_params_validates_names():
    import numpy as np
    import pytest
    from mxnet_tpu import nd, sym
    from mxnet_tpu.module import Module
    from mxnet_tpu.io import NDArrayIter
    import mxnet_tpu as mx

    x = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(x, num_hidden=3, name="fc"),
        sym.Variable("softmax_label"), name="softmax")
    it = NDArrayIter({"data": np.zeros((4, 4), np.float32)},
                     {"softmax_label": np.zeros(4, np.float32)},
                     batch_size=4)
    mod = Module(net, data_names=["data"], label_names=["softmax_label"])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    w = nd.array(np.zeros((3, 4), np.float32))
    b = nd.array(np.zeros(3, np.float32))
    with pytest.raises(mx.base.MXNetError):   # typo'd name, missing real
        mod.set_params({"fc_weigth": w, "fc_bias": b})
    with pytest.raises(mx.base.MXNetError):   # extra key
        mod.set_params({"fc_weight": w, "fc_bias": b, "bogus": b})
    mod.set_params({"fc_weight": w, "fc_bias": b})          # exact: fine
    mod.set_params({"fc_bias": b}, allow_missing=True)      # partial: ok
