"""Imperative autograd: record / pause / train_mode / backward / grad.

Reference parity: python/mxnet/autograd.py + src/imperative/imperative.cc.

TPU-native design: instead of the reference's C++ gradient tape with per-op
registered backward kernels, recording builds a lightweight Python tape of
(pure_fn, inputs, kwargs) nodes. `backward()` replays the tape as a *pure
function of the leaf arrays* and differentiates it with `jax.vjp`, so every
backward rule is XLA-generated — no hand-written backward kernels, and the
whole backward pass is fused/compiled by XLA like any other JAX program.

Mutation interplay: in-place NDArray ops rebind the underlying buffer and
re-register the new value on the tape, so each SSA version is a distinct tape
value (the reference enforces the same property via var version counters in
the ThreadedEngine).
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import numpy as np

from .base import MXNetError
from .observability import registry as _obs_registry
from .observability import compilex as _compilex

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "get_symbol",
           "Function", "vjp_cache_stats", "clear_vjp_cache"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = None
        _state.last_tape = None
    return _state


class _TapeNode:
    __slots__ = ("fn", "kwargs", "inputs", "n_out")

    def __init__(self, fn, kwargs, inputs, n_out):
        self.fn = fn            # pure: (*jax_arrays, **kwargs) -> array | tuple
        self.kwargs = kwargs
        self.inputs = inputs    # list of ('node', idx, slot)|('leaf', idx)|('const', val)
        self.n_out = n_out


class _Tape:
    def __init__(self):
        self.nodes = []
        self.leaves = []        # NDArray objects with grads attached
        self._leaf_ids = {}

    def leaf_index(self, arr):
        key = id(arr)
        if key not in self._leaf_ids:
            self._leaf_ids[key] = len(self.leaves)
            self.leaves.append(arr)
        return self._leaf_ids[key]

    # -- replay -----------------------------------------------------------
    def replay(self, leaf_values, want_entries):
        """Pure replay: leaf_values -> values at `want_entries`."""
        outs = []
        for node in self.nodes:
            args = [self._resolve(e, leaf_values, outs) for e in node.inputs]
            val = node.fn(*args, **node.kwargs)
            outs.append(val if isinstance(val, tuple) else (val,))
        return tuple(self._resolve(e, leaf_values, outs) for e in want_entries)

    @staticmethod
    def _resolve(entry, leaf_values, node_outs):
        kind = entry[0]
        if kind == "node":
            return node_outs[entry[1]][entry[2]]
        if kind == "leaf":
            return leaf_values[entry[1]]
        return entry[1]  # const

    # -- pure-replay export -----------------------------------------------
    def export(self, want_entries):
        """Export this tape as a *value-free* replay program.

        Returns `(spec, extras)` — `spec` is a `_ReplaySpec` whose
        `replay(leaf_vals, extra_vals)` recomputes `want_entries` as a pure
        function with every array VALUE (leaf, const, array-valued kwarg)
        lifted out as an argument, and whose `key` identifies the program
        structurally (node fns, static kwargs, wiring, avals) but not by
        value. `extras` is the list of lifted arrays from THIS tape; a
        structurally identical later tape yields an equal key and its own
        extras, so one jitted backward compiles once and replays every
        step. Returns `(None, None)` when a node is not structurally
        keyable (unhashable kwargs / closure over arrays)."""
        extras, nodes, key_nodes = [], [], []

        def lift(v):
            extras.append(v)
            return len(extras) - 1

        def rewrite(entry):
            if entry[0] != "const":
                return entry, entry
            v = entry[1]
            if isinstance(v, (jax.Array, np.ndarray)):
                pos = lift(v)
                return ("extra", pos), ("extra", _aval_sig(v))
            try:
                hash(v)
            except TypeError:
                return None, None
            return entry, ("const", v)

        for node in self.nodes:
            fk = _fn_key(node.fn)
            if fk is None:
                return None, None
            ins, ins_key = [], []
            for e in node.inputs:
                re_, rk = rewrite(e)
                if re_ is None:
                    return None, None
                ins.append(re_)
                ins_key.append(rk)
            skw, skw_key, akw, akw_key = {}, [], [], []
            for k, v in sorted(node.kwargs.items()):
                if isinstance(v, (jax.Array, np.ndarray)):
                    akw.append((k, lift(v)))
                    akw_key.append((k, _aval_sig(v)))
                    continue
                vk = _static_key(v)
                if vk is None:
                    return None, None
                skw[k] = v
                skw_key.append((k, vk))
            nodes.append((node.fn, skw, tuple(akw), tuple(ins), node.n_out))
            key_nodes.append((fk, tuple(skw_key), tuple(akw_key),
                              tuple(ins_key), node.n_out))
        want, want_key = [], []
        for e in want_entries:
            re_, rk = rewrite(e)
            if re_ is None:
                return None, None
            want.append(re_)
            want_key.append(rk)
        spec = _ReplaySpec(tuple(nodes), tuple(want),
                           (tuple(key_nodes), tuple(want_key)))
        return spec, extras


def _aval_sig(a):
    return (tuple(a.shape), str(getattr(a, "dtype", type(a).__name__)))


def _static_key(v, depth=0):
    """Canonical hashable key for a static (non-array) kwarg value:
    scalars key by value, lists/tuples/dicts recursively (shape lists
    etc.), anything else by value when hashable. None = unkeyable."""
    if depth > 4:
        return None
    if isinstance(v, (list, tuple)):
        parts = tuple(_static_key(x, depth + 1) for x in v)
        return None if any(p is None for p in parts) else ("seq", parts)
    if isinstance(v, dict):
        parts = tuple((k, _static_key(x, depth + 1))
                      for k, x in sorted(v.items()))
        return None if any(p is None for _, p in parts) else ("map", parts)
    try:
        hash(v)
    except TypeError:
        return None
    return ("v", v)


_HASHABLE_SCALARS = (int, float, bool, str, bytes, type(None), np.generic)


def _fn_key(fn, depth=0):
    """Structural identity for a tape node's fn: python functions key on
    (code object, closure/default scalar values — the `_binary` scalar
    lambdas are re-created per op with the scalar as a default); anything
    without a __code__ (jitted callables, custom_vjp wrappers, builtins)
    keys on object identity. Returns None when a closure/default holds
    something non-scalar (arrays), i.e. the node is not cache-keyable.
    The cache holds the fn objects strongly, so identity keys cannot be
    recycled while an entry is alive."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return ("id", id(fn))
    if depth > 3:
        return None
    parts = []
    cells = getattr(fn, "__closure__", None) or ()
    for c in cells:
        try:
            v = c.cell_contents
        except ValueError:       # empty cell
            return None
        k = _closure_val_key(v, depth)
        if k is None:
            return None
        parts.append(k)
    for v in (getattr(fn, "__defaults__", None) or ()):
        k = _closure_val_key(v, depth)
        if k is None:
            return None
        parts.append(k)
    return ("code", id(code), tuple(parts))


def _closure_val_key(v, depth):
    if isinstance(v, _HASHABLE_SCALARS):
        return ("v", v)
    if callable(v):
        return ("f", _fn_key(v, depth + 1))
    return None


class _ReplaySpec:
    """Value-free tape program (see `_Tape.export`). Holds node fns
    strongly — never array values — so a cached entry pins the ids its
    key references without leaking step data."""
    __slots__ = ("nodes", "want", "key")

    def __init__(self, nodes, want, key):
        self.nodes = nodes
        self.want = want
        self.key = key

    def replay(self, leaf_vals, extra_vals):
        outs = []

        def resolve(e):
            kind = e[0]
            if kind == "node":
                return outs[e[1]][e[2]]
            if kind == "leaf":
                return leaf_vals[e[1]]
            if kind == "extra":
                return extra_vals[e[1]]
            return e[1]  # const (hashable scalar)

        for fn, skw, akw, ins, n_out in self.nodes:
            kw = dict(skw)
            for k, pos in akw:
                kw[k] = extra_vals[pos]
            val = fn(*[resolve(e) for e in ins], **kw)
            outs.append(val if isinstance(val, tuple) else (val,))
        return tuple(resolve(e) for e in self.want)


# ---------------------------------------------------------------------------
# cached jitted backward (vjp-callable cache)
# ---------------------------------------------------------------------------
# The uncached backward() re-traces jax.vjp over the tape replay every call
# and executes both passes op-by-op — per-op dispatch on every step of an
# uncaptured training loop. This cache compiles the whole backward (replay +
# vjp) ONCE per tape structure; repeated identical-shape backward calls
# become one jitted launch with the step's values (leaves, consts, rng
# kwargs, cotangents) passed as arguments.
_VJP_CACHE_MAX = 16
_VJP_SEEN_MAX = 512
_VJP_COMPILE_AFTER = 5           # sightings before paying the jit compile
_vjp_cache = OrderedDict()       # key -> jitted (leaf_vals, extras, cots) fn
# key -> (sighting count, spec). The spec rides along purely to PIN the
# node fns whose ids the key quotes: without the strong ref, CPython
# freelists recycle a dead per-step wrapper's address and two DIFFERENT
# ephemeral programs would be conflated as a repeated sighting.
_vjp_seen = {}
_vjp_blacklist = {}              # shape-key -> consecutive miss streak
_vjp_lock = threading.Lock()
_reg = _obs_registry()
_vjp_hits = _reg.counter("autograd_vjp_cache", result="hit")
_vjp_misses = _reg.counter("autograd_vjp_cache", result="miss")


def vjp_cache_stats():
    """(hits, misses) of the cached-backward lookaside (telemetry series
    `autograd_vjp_cache{result=}` in the observability registry)."""
    return int(_vjp_hits.value), int(_vjp_misses.value)


def clear_vjp_cache():
    """Drop every cached backward program (test/bench helper)."""
    with _vjp_lock:
        _vjp_cache.clear()
        _vjp_seen.clear()
        _vjp_blacklist.clear()


def _make_backward_fn(spec):
    def bwd(leaf_vals, extra_vals, cots):
        def pure(vals):
            return spec.replay(vals, extra_vals)

        _, vjp_fn = jax.vjp(pure, list(leaf_vals))
        return vjp_fn(tuple(cots))[0]

    return _compilex.instrument(jax.jit(bwd), "autograd_backward")


def _cached_backward(spec, extras, leaf_values, cots):
    """Run the backward through the jitted cache; None = take the uncached
    path this call. Compilation is DEFERRED until a key has been seen
    `_VJP_COMPILE_AFTER` times: short-lived tapes (tests, eval snippets,
    few-step loops) never pay a jit compile, a real training loop
    compiles once early on and hits from then on. Blacklisted tape
    shapes (e.g. a fresh custom_vjp object per step keys a different
    program every call) stop being tried after 3 consecutive misses."""
    key = (spec.key,
           tuple(_aval_sig(v) for v in leaf_values),
           tuple(_aval_sig(c) for c in cots))
    # identity-free shape of the same program: when this recurs with ever-
    # new fn identities, every lookup misses — stop trying after 3 in a row
    shape_key = (len(spec.nodes), key[1], key[2], len(extras))
    hit = False
    with _vjp_lock:
        jfn = _vjp_cache.get(key)
        if jfn is not None:
            _vjp_cache.move_to_end(key)
            _vjp_blacklist.pop(shape_key, None)
            hit = True
        else:
            seen = _vjp_seen.get(key, (0, None))[0] + 1
            if seen > 1:
                # the key REPEATED: keys are stable for this tape shape —
                # a genuine repeat lifts an earlier blacklist
                _vjp_blacklist.pop(shape_key, None)
            elif _vjp_blacklist.get(shape_key, 0) >= 3:
                # blacklisted shape with yet another never-seen key: stay
                # on the cheap path, but RECORD the sighting so a stable
                # program arriving later can still prove itself above —
                # and COUNT the miss, or the telemetry would freeze while
                # a 100%-miss workload keeps running uncached
                if len(_vjp_seen) > _VJP_SEEN_MAX:
                    _vjp_seen.clear()
                _vjp_seen[key] = (seen, spec)
                _vjp_misses.inc()
                return None
            else:
                # never-seen key for this shape — ever-new fn identities
                # (fresh custom_vjp per step) look exactly like this
                if len(_vjp_blacklist) > 64:
                    _vjp_blacklist.clear()
                _vjp_blacklist[shape_key] = \
                    _vjp_blacklist.get(shape_key, 0) + 1
            if seen < _VJP_COMPILE_AFTER:
                if len(_vjp_seen) > _VJP_SEEN_MAX:
                    _vjp_seen.clear()
                _vjp_seen[key] = (seen, spec)
                jfn = None           # early sightings: defer the compile
            else:
                _vjp_seen.pop(key, None)
                while len(_vjp_cache) >= _VJP_CACHE_MAX:
                    _vjp_cache.popitem(last=False)
                jfn = _vjp_cache[key] = _make_backward_fn(spec)
    if hit:
        _vjp_hits.inc()
    else:
        _vjp_misses.inc()
    if jfn is None:
        return None
    try:
        return jfn(leaf_values, extras, cots)
    except Exception:
        # jax.jit traces lazily at this call: a tape fn that only works
        # under eager vjp (concrete-value branching, host conversions)
        # raises HERE, possibly after steps of healthy uncached
        # backwards. Drop the poisoned entry and blacklist the shape so
        # every later call takes the uncached path instead of failing
        # forever; the caller falls back to plain jax.vjp this step too.
        with _vjp_lock:
            _vjp_cache.pop(key, None)
            _vjp_blacklist[shape_key] = 3
        return None


# ---------------------------------------------------------------------------
# recording scopes
# ---------------------------------------------------------------------------
class _RecordingScope:
    """Sets recording/training flags on enter, restores them on exit.

    A scope that *starts* recording creates the tape; when that outermost
    scope exits, the finished tape is stashed in `last_tape` so that
    `backward()` can run after the `with` block (reference behaviour)."""

    def __init__(self, recording, training):
        self._rec = recording
        self._train = training
        self._created_tape = False

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
            if self._rec and st.tape is None:
                st.tape = _Tape()
                self._created_tape = True
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._prev
        if self._created_tape:
            st.last_tape = st.tape
            st.tape = None


def record(train_mode=True):
    """Scope in which imperative ops are recorded for backward().

    with autograd.record():
        y = net(x)
    y.backward()
    """
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    """Scope in which recording (and optionally training mode) is paused.
    The enclosing tape is kept; nested record() resumes onto it."""
    return _RecordingScope(False, train_mode)


def train_mode():
    """Scope forcing training mode (dropout active) without recording."""
    return _RecordingScope(None, True)


def predict_mode():
    """Scope forcing inference mode."""
    return _RecordingScope(None, False)


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    st = _st()
    prev, st.recording = st.recording, is_record
    if is_record and st.tape is None:
        st.tape = _Tape()
    return prev


def set_training(train_mode):
    st = _st()
    prev, st.training = st.training, train_mode
    return prev


# ---------------------------------------------------------------------------
# tape construction (called from ndarray op dispatch)
# ---------------------------------------------------------------------------
def _entry_for(tape, nd):
    ref = getattr(nd, "_tape_ref", None)
    if ref is not None and ref[0] is tape:
        return ref[1]
    if getattr(nd, "_grad", None) is not None or getattr(nd, "_grad_req", "null") != "null":
        entry = ("leaf", tape.leaf_index(nd))
    else:
        entry = ("const", nd._data)
    nd._tape_ref = (tape, entry)
    return entry


def record_op(fn, nd_inputs, kwargs, nd_outputs):
    """Append one executed op to the active tape (no-op when not recording)."""
    st = _st()
    tape = st.tape
    if tape is None:
        return
    inputs = [_entry_for(tape, x) for x in nd_inputs]
    idx = len(tape.nodes)
    tape.nodes.append(_TapeNode(fn, kwargs, inputs, len(nd_outputs)))
    for slot, out in enumerate(nd_outputs):
        out._tape_ref = (tape, ("node", idx, slot))


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference: autograd.mark_variables)."""
    from .base import _as_list
    variables = _as_list(variables)
    gradients = _as_list(gradients)
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad = g
        var._grad_req = req


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _active_tape():
    st = _st()
    tape = st.tape if st.tape is not None else st.last_tape
    if tape is None:
        raise MXNetError("backward() called with no recorded computation "
                         "(wrap the forward in autograd.record())")
    return tape


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of `heads` w.r.t. all attached variables on the tape.

    Replays the tape as a pure function of the leaf values and runs jax.vjp;
    gradients are accumulated into each variable's `.grad` buffer according to
    its grad_req ('write' overwrites, 'add' accumulates, 'null' skips).
    """
    from .base import _as_list
    from .ndarray import NDArray
    heads = _as_list(heads)
    tape = _active_tape()

    head_entries = []
    for h in heads:
        ref = getattr(h, "_tape_ref", None)
        if ref is None or ref[0] is not tape:
            raise MXNetError("head array was not computed inside the recorded scope")
        head_entries.append(ref[1])

    leaves = [v for v in tape.leaves if v._grad_req != "null"]
    if not leaves:
        return
    leaf_entry_idx = {id(v): i for i, v in enumerate(tape.leaves)}
    leaf_values = [v._data for v in tape.leaves]

    if head_grads is None:
        cots = tuple(jax.numpy.ones_like(h._data) for h in heads)
    else:
        hg = _as_list(head_grads)
        cots = tuple(
            (g._data if isinstance(g, NDArray) else jax.numpy.asarray(g))
            if g is not None else jax.numpy.ones_like(h._data)
            for h, g in zip(heads, hg))

    # cached path: one jitted program per tape structure (values ride in
    # as arguments) instead of a fresh vjp re-trace + per-op dispatch
    grads = None
    spec, extras = tape.export(head_entries)
    if spec is not None:
        grads = _cached_backward(spec, extras, leaf_values, cots)
    if grads is None:
        def pure(vals):
            return tape.replay(vals, head_entries)

        _, vjp_fn = jax.vjp(pure, leaf_values)
        grads = vjp_fn(cots)[0]

    for var in leaves:
        g = grads[leaf_entry_idx[id(var)]]
        if var._grad is None:
            continue
        if var._grad_req == "add":
            var._grad._rebind(var._grad._data + g)
        else:
            var._grad._rebind(jax.numpy.asarray(g, dtype=var._grad._data.dtype))

    if not retain_graph:
        st = _st()
        if st.tape is None:
            st.last_tape = None


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables (reference: autograd.grad).

    create_graph=True is supported by re-recording the gradient computation
    onto the active tape via the standard op path.
    """
    from .base import _as_list
    from .ndarray import NDArray, _wrap_apply
    heads = _as_list(heads)
    variables = _as_list(variables)
    tape = _active_tape()

    head_entries = []
    for h in heads:
        ref = getattr(h, "_tape_ref", None)
        if ref is None or ref[0] is not tape:
            raise MXNetError("head array was not computed inside the recorded scope")
        head_entries.append(ref[1])

    var_entries = []
    for v in variables:
        ref = getattr(v, "_tape_ref", None)
        if ref is not None and ref[0] is tape:
            var_entries.append(ref[1])
        else:
            var_entries.append(("leaf", tape.leaf_index(v)))
            v._tape_ref = (tape, var_entries[-1])

    # gradient as a pure function of (variable values, other leaf values)
    leaf_values = [v._data for v in tape.leaves]
    var_leaf_idx = []
    for e in var_entries:
        if e[0] != "leaf":
            raise MXNetError("autograd.grad targets must be leaf variables "
                             "(arrays used as inputs, not op outputs)")
        var_leaf_idx.append(e[1])

    if head_grads is None:
        cots = tuple(jax.numpy.ones_like(h._data) for h in heads)
    else:
        hg = _as_list(head_grads)
        cots = tuple(g._data if isinstance(g, NDArray) else jax.numpy.asarray(g)
                     for g in hg)

    def grad_fn(*var_vals):
        vals = list(leaf_values)
        for i, vi in enumerate(var_leaf_idx):
            vals[vi] = var_vals[i]

        def pure(vs):
            return tape.replay(vs, head_entries)

        _, vjp_fn = jax.vjp(pure, vals)
        gs = vjp_fn(cots)[0]
        return tuple(gs[vi] for vi in var_leaf_idx)

    if create_graph:
        outs = _wrap_apply(grad_fn, variables, {}, n_out=len(variables))
        return list(outs)
    with pause():
        outs = _wrap_apply(grad_fn, variables, {}, n_out=len(variables))
    return list(outs)


def get_symbol(x):
    """Reference parity stub: the recorded graph is a JAX trace, not an nnvm
    symbol; returns None (documented divergence)."""
    return None


# ---------------------------------------------------------------------------
# user-defined differentiable ops (reference: autograd.Function)
# ---------------------------------------------------------------------------
class Function:
    """Customised differentiation (reference: python/mxnet/autograd.py
    class Function). Subclass and implement `forward(self, *inputs)` and
    `backward(self, *output_grads)`, both over NDArrays; calling the
    instance runs forward and records the custom backward on the tape.

    TPU-native mechanics: the pair is packaged as one `jax.custom_vjp`
    pure function, so the tape's `jax.vjp` replay invokes the user backward
    exactly where the reference's tape would, and the op (with its custom
    gradient) still traces/compiles under jit. Both methods must therefore
    be expressible with traceable array ops — no host syncs (`.asnumpy()`).

    State saved in forward (e.g. `self._saved = x`) is visible in backward;
    like the reference, use one instance per call when saving state."""

    def __init__(self):
        self._n_out = None

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    # internal: run a user method over raw jax arrays, NDArray in/out
    def _run(self, method, raw):
        from .ndarray.ndarray import NDArray
        with pause():
            out = method(*[NDArray(r) for r in raw])
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o._data for o in outs)

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        fn = self

        @jax.custom_vjp
        def op(*raw):
            outs = fn._run(fn.forward, raw)
            return outs if len(outs) > 1 else outs[0]

        def op_fwd(*raw):
            return op(*raw), None

        def op_bwd(_res, g):
            gs = g if isinstance(g, tuple) else (g,)
            in_grads = fn._run(fn.backward, gs)
            if len(in_grads) != len(inputs):
                raise MXNetError(
                    f"{type(fn).__name__}.backward returned "
                    f"{len(in_grads)} grads for {len(inputs)} inputs")
            return in_grads

        op.defvjp(op_fwd, op_bwd)

        raw = [x._data for x in inputs]
        out = op(*raw)
        outs = out if isinstance(out, tuple) else (out,)
        nd_outs = tuple(NDArray(o) for o in outs)
        record_op(op, list(inputs), {}, nd_outs)
        return nd_outs[0] if len(nd_outs) == 1 else nd_outs
