"""Gluon Trainer (reference: python/mxnet/gluon/trainer.py).

Applies an optimizer to a set of Parameters after backward. Gradient
aggregation rides the KVStore: 'device'/'local' aggregate locally; 'ici'
lowers to psum over the mesh (see mxnet_tpu/kvstore.py). For the fully-fused
path (whole train step as one XLA executable) see
mxnet_tpu/parallel/data_parallel.py — this imperative Trainer matches the
reference's semantics for Gluon scripts.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt_mod
from .. import kvstore as kvs_mod
from ..observability import tracer as _tracer
from ..observability import registry as _obs_registry
from ..fault import injection as _finj
from ..fault import watchdog as _fwatchdog
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]

_reg = _obs_registry()
_steps_counter = _reg.counter("trainer_steps")
_skips_counter = _reg.counter("trainer_steps_skipped")
_steps_s_gauge = _reg.gauge("trainer_steps_per_s")
_grad_norm_gauge = _reg.gauge("trainer_grad_norm")
_grad_norm_fn = None


def _global_grad_norm(grads):
    """L2 norm over all gradients as ONE jitted launch (cached by jax.jit
    on the gradient pytree signature). Only issued while a trace is being
    captured; returns the PENDING device scalar — the gauge coerces it to
    float at snapshot time, so the step path never syncs for it."""
    global _grad_norm_fn
    import jax
    import jax.numpy as jnp
    if _grad_norm_fn is None:
        _grad_norm_fn = jax.jit(lambda gs: jnp.sqrt(sum(
            jnp.vdot(g.astype(jnp.float32), g.astype(jnp.float32)).real
            for g in gs)))
    return _grad_norm_fn(grads)


class Trainer:
    """`skip_nonfinite=True` (SURVEY.md §5 failure detection) skips the
    optimizer update when any gradient is inf/nan instead of poisoning the
    weights; when AMP installed a DynamicLossScaler (amp.init("float16")),
    step() additionally unscales gradients and drives the scaler's
    overflow-skip/halve protocol.

    `max_skipped_steps=N` escalates graceful degradation: more than N
    CONSECUTIVE skipped updates raise MXNetError (each skip also counts
    into the `trainer_steps_skipped` metric; `consecutive_skipped_steps`
    exposes the running streak so loops can retry a batch).

    `fused=True` (the default) routes step() through the multi-tensor
    subsystem (optimizer/multi_tensor.py): parameters are grouped into
    dtype-homogeneous byte-capped buckets (cap = engine.get_bulk_size()),
    each bucket's gradients allreduce as one flattened buffer, and each
    bucket's optimizer update compiles to a single jitted XLA executable —
    O(num_buckets) dispatches per step instead of O(num_params), with
    identical numerics. `fused=False` keeps the reference per-param path;
    optimizers with custom imperative update semantics fall back
    automatically (multi_tensor.supports)."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 skip_nonfinite=False, fused=True, max_skipped_steps=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict or list of Parameter")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p}")
            self._params.append(p)
            self._param2idx[p.name] = i
        optimizer_params = optimizer_params or {}
        # both int and str keys: the local updater passes int indices, the
        # kvstore updater stringifies keys — lr_mult/wd_mult lookups must
        # hit either way
        param_dict = {i: p for i, p in enumerate(self._params)}
        param_dict.update({str(i): p for i, p in enumerate(self._params)})
        self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._fused = bool(fused) and not bool(update_on_kvstore) and \
            opt_mod.multi_tensor.supports(self._optimizer)
        self._updater = opt_mod.get_updater(self._optimizer,
                                            fused=self._fused)
        self._buckets = None
        self._bucket_sig = None
        self._kvstore = kvs_mod.create(kvstore) if kvstore else None
        if compression_params:
            # reference semantics: forward to the store (previously this
            # argument was accepted and silently dropped). NB the Trainer's
            # own allreduce path uses replicated layout (grads are already
            # reduced in-step), so compression engages on stacked pushes
            # through this store — kvstore.set_gradient_compression docs.
            if self._kvstore is None:
                raise MXNetError("compression_params requires a kvstore")
            self._kvstore.set_gradient_compression(compression_params)
        self._update_on_kvstore = bool(update_on_kvstore)
        if self._update_on_kvstore:
            # reference semantics (previously accepted and ignored): the
            # optimizer runs ON the store — push applies the update to the
            # stored weight, pull brings it back (server-side update path,
            # kvstore.set_optimizer)
            if self._kvstore is None:
                raise MXNetError("update_on_kvstore=True requires a kvstore")
            self._kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = False
        self._kv_keys = set()
        self._scale = 1.0
        self._last_step_t = None   # steps/s gauge anchor
        self.skip_nonfinite = skip_nonfinite
        # graceful-degradation escalation: N+1 CONSECUTIVE skipped
        # updates (AMP overflow / nonfinite grads) raise instead of
        # silently free-running — persistent NaNs are a training outage,
        # not noise (None disables; see docs/RELIABILITY.md)
        self.max_skipped_steps = max_skipped_steps
        self._consecutive_skips = 0

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        # incremental + idempotent: deferred-init params materialise after
        # the first forward, so keys join the store as their data appears
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                if i not in self._kv_keys and p._data is not None:
                    self._kvstore.init(i, p.data())
                    self._kv_keys.add(i)
        self._kv_initialized = True

    def allreduce_grads(self):
        """Aggregate gradients across devices (reference: _allreduce_grads).
        With single-replica HBM-resident params this is a no-op; 'ici'
        sharded grads psum via the kvstore. On the fused path each
        dtype-homogeneous bucket's gradients reduce as ONE flattened
        buffer (kvstore.allreduce_flat) — one collective per bucket
        instead of one per parameter. Zero-arg on purpose: it is a
        documented gluon override point; the bucket layout comes from the
        `_get_buckets` cache, so the step()-time call does not rebuild it."""
        if _tracer.ACTIVE:
            with _tracer.span("Trainer.allreduce_grads", cat="trainer"):
                return self._allreduce_grads_impl()
        return self._allreduce_grads_impl()

    def _allreduce_grads_impl(self):
        from .. import profiler
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None or self._kvstore.type != "ici":
            return
        if self._fused:
            for bucket in self._get_buckets(self._updatable_pairs(True)):
                grads = [p._grad._data for _, p in bucket]
                # explicit layout inside allreduce_flat: Trainer gradients
                # are whole per-param arrays, never replica stacks
                reduced = self._kvstore.allreduce_flat(grads)
                for (_, p), g in zip(bucket, reduced):
                    if g is not p._grad._data:
                        p._grad._rebind(g)
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p._grad is not None:
                # explicit layout: a Trainer gradient is one whole array
                # for one parameter (possibly dim0-SHARDED for memory —
                # FSDP-style), never a stack of per-replica towers;
                # 'auto' would misread dim0 sharding as a replica stack
                # and reduce the leading dim away
                agg = self._kvstore.allreduce_([p._grad._data],
                                               layout="replicated")
                if agg is not p._grad._data:
                    profiler.record_dispatch("allreduce")
                p._grad._rebind(agg)

    def capture(self, loss_fn, sharded_update=False, grad_reduce="mean"):
        """Capture one FULL training step — `loss_fn(*batch)` forward,
        backward, in-graph 'ici' gradient reduction, AMP/nonfinite guard,
        optimizer update — as ONE jitted XLA executable with parameter and
        optimizer-state buffers donated (mxnet_tpu/cachedop.py, the
        CachedOp of the whole step). Returns a `CachedStep`; call it with
        the batch instead of the record/backward/step() triple:

            step = trainer.capture(lambda x, y: lossf(net(x), y).mean())
            for x, y in batches:
                loss = step(x, y)            # one device dispatch

        `sharded_update=True` (needs an 'ici' kvstore with a mesh)
        reduce-scatters gradients, updates each replica's weight shard and
        all-gathers the new weights inside the same program
        (arXiv:2004.13336). Unsupported configurations fall back to the
        imperative path transparently; see docs/PERFORMANCE.md."""
        from ..cachedop import CachedStep
        return CachedStep(self, loss_fn, sharded_update=sharded_update,
                          grad_reduce=grad_reduce)

    # -------------------------------------- rule-driven sharding (shard/)
    @property
    def shard_plan(self):
        """The `shard.ShardPlan` attached to this trainer's kvstore, or
        None (replicated layout)."""
        kv = self._kvstore
        return kv.shard_plan() if kv is not None and kv.type == "ici" \
            else None

    def shard(self, mesh=None, rules=None, data_axis=None):
        """Attach a rule-driven FSDP/TP shard plan (mxnet_tpu/shard/) to
        this trainer's 'ici' kvstore and move already-initialised
        parameters, gradients, and optimizer state onto their per-rule
        layouts. Captured steps (`capture`) then compile against the
        sharded layout — params/grads/state live sharded BETWEEN steps
        and per-device parameter memory drops by each rule's shard
        factor. `mesh` is a Mesh / {axis: size} dict / (dp, tp) tuple
        (None reuses the store's mesh, else builds dp x 1 over every
        device); `rules=None` uses `shard.DEFAULT_RULES`. Returns the
        plan. See docs/PERFORMANCE.md "Parameter sharding"."""
        from .. import shard as shard_mod
        from ..optimizer import multi_tensor
        kv = self._kvstore
        if kv is None or kv.type != "ici":
            raise MXNetError("Trainer.shard needs kvstore='ici' (got "
                             f"{None if kv is None else kv.type!r})")
        if self._update_on_kvstore:
            raise MXNetError("Trainer.shard is incompatible with "
                             "update_on_kvstore=True (the captured step "
                             "owns the optimizer)")
        if not multi_tensor.supports(self._optimizer):
            raise MXNetError(
                f"Trainer.shard: optimizer "
                f"{type(self._optimizer).__name__} has custom imperative "
                f"update semantics the captured step cannot reproduce — "
                f"a shard plan admits no imperative fallback")
        import jax
        if jax.process_count() > 1:
            raise MXNetError(
                "Trainer.shard: rule-driven sharding is single-controller "
                "only for now (host batches cannot be placed onto "
                "non-addressable devices); use the 1-D 'ici' mesh path "
                "on multi-host pods")
        if mesh is None and kv._mesh is not None:
            mesh = kv._mesh
        plan = shard_mod.plan(mesh, rules=rules, data_axis=data_axis)
        kv.set_shard_plan(plan)
        # tiered tables convert (or re-tier) BEFORE placement so their
        # fresh hot caches are built directly on the plan's shardings
        # and the redistribution pass no-ops over them
        shard_mod.tiered.on_plan(self, plan)
        self._place_on_plan(plan)
        return plan

    def resize_mesh(self, mesh, devices=None):
        """Elastic reshard: rebuild the active shard plan over a new mesh
        (shrink after a preemption, grow when capacity returns) and move
        live parameters, gradients, and optimizer state onto it through
        device-side collective redistribution — no host round-trip of
        the full state (shard/redistribute.py, arXiv:2112.01075;
        `shard_resharded_bytes` accounts the moved bytes). The next call
        of any captured step recompiles against the new mesh and
        training continues. Returns the new plan."""
        from .. import shard as shard_mod
        kv = self._kvstore
        old = self.shard_plan
        if old is None:
            raise MXNetError("Trainer.resize_mesh needs an active shard "
                             "plan (call Trainer.shard first)")
        new_mesh = shard_mod.as_mesh(mesh, devices=devices)
        if old.data_axis not in new_mesh.axis_names:
            raise MXNetError(
                f"resize_mesh: new mesh axes {new_mesh.axis_names} do "
                f"not include the plan's data axis {old.data_axis!r}")
        plan = old.with_mesh(new_mesh)
        kv.set_shard_plan(plan)
        shard_mod.tiered.on_plan(self, plan)
        self._place_on_plan(plan)
        return plan

    def _place_on_plan(self, plan):
        """Move every initialised param + grad + optimizer-state leaf
        onto `plan`'s shardings (collective redistribution; a leaf
        already in its target layout moves nothing)."""
        from ..shard.redistribute import redistribute
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            sh = plan.sharding(p.name, p._data.shape)
            redistribute(p._data, sh)
            if p._grad is not None:
                redistribute(p._grad, sh)
            st = self._updater.states.get(i) if self._updater is not None \
                else None
            if st is None:
                continue
            leaves = st if isinstance(st, tuple) else (st,)
            w_shape = tuple(p._data.shape)
            for s in leaves:
                if s is None:
                    continue
                from jax.sharding import NamedSharding
                redistribute(s, NamedSharding(
                    plan.mesh, plan.state_spec(p.name, w_shape,
                                               s._data.shape)))

    def step(self, batch_size, ignore_stale_grad=False):
        """Rescale gradients by 1/batch_size and apply one optimizer step.
        Under an AMP loss scaler: unscale, skip on overflow, adjust scale.
        With skip_nonfinite: skip the update when any grad is inf/nan."""
        if _tracer.ACTIVE:
            with _tracer.span("Trainer.step", cat="trainer",
                              args={"batch_size": int(batch_size),
                                    "params": len(self._params),
                                    "fused": self._fused}):
                self._step_impl(batch_size, ignore_stale_grad)
            grads = [p._grad._data for p in self._params
                     if p._grad is not None]
            if grads:
                _grad_norm_gauge.set(_global_grad_norm(grads))
        else:
            self._step_impl(batch_size, ignore_stale_grad)
        self._tick_step()

    def _step_impl(self, batch_size, ignore_stale_grad):
        self._optimizer.rescale_grad = self._scale / batch_size
        if _finj.ENABLED and _finj.should_fire("grad.nan"):
            # deterministic NaN-gradient injection (chaos testing the
            # skip_nonfinite / AMP-overflow reflexes end to end)
            for p in self._params:
                if p._grad is not None:
                    p._grad._rebind(p._grad._data * float("nan"))
        self._init_kvstore()   # incremental: picks up late-materialised params
        self.allreduce_grads()
        self._apply_update(ignore_stale_grad)

    def _tick_step(self):
        """Per-step bookkeeping shared by the imperative `step()` and the
        captured step (cachedop.py): watchdog deadline check, step
        counter, steps/s gauge."""
        import time
        _fwatchdog.maybe_check(step=int(_steps_counter.value))
        _steps_counter.inc()
        now = time.perf_counter()
        last = self._last_step_t
        self._last_step_t = now
        if last is not None and now > last:
            _steps_s_gauge.set(1.0 / (now - last))

    # ------------------------------------------ skip-streak escalation
    @property
    def consecutive_skipped_steps(self):
        return self._consecutive_skips

    def _note_skip(self, reason):
        self._consecutive_skips += 1
        _skips_counter.inc()
        if self.max_skipped_steps is not None and \
                self._consecutive_skips > self.max_skipped_steps:
            raise MXNetError(
                f"Trainer: {self._consecutive_skips} consecutive skipped "
                f"updates ({reason}) exceeds max_skipped_steps="
                f"{self.max_skipped_steps} — gradients are persistently "
                f"non-finite; lower the learning rate or restore a "
                f"checkpoint")

    def _note_applied(self):
        self._consecutive_skips = 0

    def _apply_update(self, ignore_stale_grad):
        """Guard (AMP / nonfinite) + optimizer application, shared by
        step() and update()."""
        if self._fused:
            self._fused_update(ignore_stale_grad)
            return
        if self._guard_says_skip():
            self._note_skip("AMP overflow / nonfinite gradients")
            return
        if self._update_on_kvstore:
            def apply_on_store(i, p):
                # Trainer gradients are whole per-param arrays, never
                # replica stacks: pin the layout so a dim0-sharded grad
                # is not misread as a stack (kvstore 'auto' caveat)
                self._kvstore.push(i, [p.grad()], layout="replicated")
                self._kvstore.pull(i, out=p.data())
            self._for_each_updatable(apply_on_store, ignore_stale_grad)
            self._note_applied()
            return
        self._update(ignore_stale_grad)
        self._note_applied()

    def _guard_says_skip(self):
        """Shared AMP-unscale / overflow-skip / nonfinite-skip guard for
        step() and update(). Returns True when the update must be skipped."""
        from .. import amp, profiler
        scaler = amp.scaler()
        if scaler is not None:
            # same "nonfinite_guard" tally as the fused path, so
            # fused-vs-unfused dispatch comparisons stay symmetric
            profiler.record_dispatch("nonfinite_guard")
            amp.unscale(self)
            overflow = scaler.has_overflow(self._params)
            scaler.update_scale(overflow)
            return overflow
        if self.skip_nonfinite:
            profiler.record_dispatch("nonfinite_guard")
            return amp.grads_nonfinite(self._params)
        return False

    def update(self, batch_size, ignore_stale_grad=False):
        if self._update_on_kvstore:
            raise MXNetError("update() cannot be called when "
                             "update_on_kvstore=True: the store owns the "
                             "optimizer (reference asserts the same); use "
                             "step()")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._apply_update(ignore_stale_grad)

    def _for_each_updatable(self, apply_fn, ignore_stale_grad):
        for i, p in self._updatable_pairs(ignore_stale_grad):
            apply_fn(i, p)

    def _updatable_pairs(self, ignore_stale_grad):
        pairs = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if p._grad is None:
                if ignore_stale_grad:
                    continue
                raise MXNetError(f"Parameter {p.name} has no gradient; run "
                                 f"backward first or set ignore_stale_grad")
            pairs.append((i, p))
        return pairs

    def _update(self, ignore_stale_grad=False):
        self._for_each_updatable(
            lambda i, p: self._updater(i, p.grad(), p.data()),
            ignore_stale_grad)

    # ------------------------------------------------------ fused path
    def _get_buckets(self, pairs):
        """Bucket layout for the fused path, rebuilt only when the
        parameter structure (deferred init, cast, grad_req) or the
        engine bulk-size cap changes. The O(num_params) signature scan
        per step is deliberate: Parameter has no single mutation choke
        point to hang a dirty flag on, and a missed invalidation means
        silently training with a stale layout — the scan is pure-host
        tuple building, orders of magnitude below one saved dispatch."""
        from .. import engine, profiler
        from ..optimizer import multi_tensor
        cap = engine.get_bulk_size()
        sig = (cap, tuple((i, p._struct_sig()) for i, p in pairs))
        if sig != self._bucket_sig:
            self._buckets = multi_tensor.build_buckets(pairs, cap)
            self._bucket_sig = sig
            profiler.record_buckets(
                [sum(multi_tensor._grad_nbytes(p) for _, p in b)
                 for b in self._buckets])
        return self._buckets

    def _fused_update(self, ignore_stale_grad):
        """Whole-model optimizer application in O(num_buckets) dispatches:
        one nonfinite-guard launch at most, then one fused multi-tensor
        kernel per bucket (AMP unscale folded in)."""
        from .. import amp, profiler
        buckets = self._get_buckets(self._updatable_pairs(ignore_stale_grad))
        scaler = amp.scaler()
        if scaler is None and not buckets:
            return
        inv_scale = None
        if scaler is not None:
            # same protocol (and float ordering) as the per-param guard:
            # overflow is judged and grads unscale at the PRE-update
            # scale; this runs even with zero updatable params so the
            # scaler keeps adapting exactly like the per-param path
            profiler.record_dispatch("nonfinite_guard")
            overflow = scaler.has_overflow(self._params)
            if overflow:
                amp.unscale(self)   # rare path: grads end unscaled, as in
                scaler.update_scale(True)   # the per-param path
                self._note_skip("AMP overflow")
                return
            inv_scale = 1.0 / scaler.loss_scale
            scaler.update_scale(False)
            # per-param amp.unscale touches EVERY grad; params outside
            # the buckets (grad_req="null" with an accumulated grad,
            # stale-skipped) must observe the same unscaled values —
            # one fused multi-tensor launch, same as amp.unscale
            bucketed = {id(p) for b in buckets for _, p in b}
            leftovers = [p for p in self._params
                         if p._grad is not None and id(p) not in bucketed]
            for p, g in zip(leftovers,
                            amp.unscale_arrays(
                                [p._grad._data for p in leftovers],
                                inv_scale)):
                p._grad._rebind(g)
        elif self.skip_nonfinite:
            profiler.record_dispatch("nonfinite_guard")
            if amp.grads_nonfinite(self._params):
                self._note_skip("nonfinite gradients")
                return
        if not _tracer.ACTIVE:
            for bucket in buckets:
                self._updater.update_bucket(bucket, inv_scale=inv_scale)
            self._note_applied()
            return
        for bi, bucket in enumerate(buckets):
            with _tracer.span(
                    "Trainer.fused_bucket", cat="trainer",
                    args={"bucket": bi, "params": len(bucket)}):
                self._updater.update_bucket(bucket, inv_scale=inv_scale)
        self._note_applied()

    def states_bytes(self):
        """The optimizer state as ONE bytes blob (the same pickle
        `save_states` writes) — the checkpoint-extras form: the recovery
        supervisor (fault/supervisor.py) snapshots this beside every
        periodic save so a rollback restores momentum/Adam state without
        a temp-file round trip."""
        import pickle
        if self._update_on_kvstore:
            # the state lives ON the store; reuse its pickler
            import os
            import tempfile
            fd, path = tempfile.mkstemp(suffix=".states")
            os.close(fd)
            try:
                self._kvstore.save_optimizer_states(path)
                with open(path, "rb") as f:
                    return f.read()
            finally:
                os.unlink(path)
        import numpy as np
        import jax
        states = {k: jax.tree_util.tree_map(lambda x: np.asarray(x._data), v)
                  for k, v in self._updater.states.items()}
        return pickle.dumps({"num_update": self._optimizer.num_update,
                             "states": states})

    def load_states_bytes(self, blob):
        """Inverse of `states_bytes`."""
        import pickle
        if self._update_on_kvstore:
            import os
            import tempfile
            fd, path = tempfile.mkstemp(suffix=".states")
            os.close(fd)
            try:
                with open(path, "wb") as f:
                    f.write(blob)
                self._kvstore.load_optimizer_states(path)
            finally:
                os.unlink(path)
            return
        from ..ndarray.ndarray import NDArray
        import jax.numpy as jnp
        data = pickle.loads(blob)
        self._optimizer.num_update = data["num_update"]
        self._updater.states = {
            k: tuple(NDArray(jnp.asarray(s)) for s in v)
            for k, v in data["states"].items()}

    def save_states(self, fname):
        if self._update_on_kvstore:
            # the optimizer state lives ON the store
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, "wb") as f:
            f.write(self.states_bytes())

    def load_states(self, fname):
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            self.load_states_bytes(f.read())
