"""Transformer NMT: teacher-forced training + KV-cached beam decode.

Usage: python examples/nmt_translate.py [--smoke]
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.steps = 2

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.models.transformer import (TransformerNMT,
                                              beam_search_cached)

    mx.random.seed(0)
    vocab = 200
    model = TransformerNMT(vocab, units=64, hidden=128, num_layers=2,
                           num_heads=4, max_length=64, dropout=0.1)
    model.initialize()

    rng = np.random.RandomState(0)
    B, S = 4, 16
    src = nd.array(rng.randint(4, vocab, (B, S)).astype(np.int32))
    tgt_in = nd.array(rng.randint(4, vocab, (B, S)).astype(np.int32))
    tgt_out = nd.array(rng.randint(4, vocab, (B, S)).astype(np.int32))
    svl = nd.array(np.full((B,), S, np.int32))

    trainer = mx.gluon.Trainer(model.collect_params(), "adam",
                               {"learning_rate": 3e-4})
    ce = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    for i in range(args.steps):
        with autograd.record():
            logits = model(src, tgt_in, svl)
            loss = ce(logits.reshape((-1, vocab)),
                      tgt_out.reshape((-1,))).mean()
        loss.backward()
        trainer.step(B)
        print(f"step {i}: loss={float(loss.asnumpy()):.4f}")

    tokens, scores = beam_search_cached(model, src, svl, beam_size=4,
                                        max_length=12)
    print("best beams:", tokens.asnumpy()[:, 0].tolist())


if __name__ == "__main__":
    main()
