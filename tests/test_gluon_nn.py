"""Gluon nn layer tests (SURVEY.md §2 #16): shapes, numerics vs closed
forms, hybridize parity, gradients flow through every layer family."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.gluon import nn


def _check_hybrid_parity(net, x, rtol=1e-4, atol=1e-5):
    y1 = net(x)
    net.hybridize()
    y2 = net(x)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=rtol,
                               atol=atol)
    return y2


@pytest.mark.parametrize("cls,kwargs,xshape,yshape", [
    (nn.Conv1D, dict(channels=4, kernel_size=3, padding=1), (2, 3, 8),
     (2, 4, 8)),
    (nn.Conv2D, dict(channels=4, kernel_size=3, strides=2, padding=1),
     (2, 3, 8, 8), (2, 4, 4, 4)),
    (nn.Conv3D, dict(channels=2, kernel_size=3, padding=1), (1, 2, 4, 4, 4),
     (1, 2, 4, 4, 4)),
    (nn.Conv2DTranspose, dict(channels=3, kernel_size=2, strides=2),
     (2, 4, 4, 4), (2, 3, 8, 8)),
    (nn.MaxPool2D, dict(pool_size=2, strides=2), (1, 2, 8, 8), (1, 2, 4, 4)),
    (nn.AvgPool2D, dict(pool_size=2, strides=2), (1, 2, 8, 8), (1, 2, 4, 4)),
    (nn.GlobalAvgPool2D, {}, (2, 3, 5, 5), (2, 3, 1, 1)),
    (nn.GlobalMaxPool2D, {}, (2, 3, 5, 5), (2, 3, 1, 1)),
])
def test_conv_pool_shapes(cls, kwargs, xshape, yshape):
    net = cls(**kwargs)
    net.initialize()
    x = nd.random.uniform(shape=xshape)
    y = _check_hybrid_parity(net, x)
    assert y.shape == yshape


def test_conv2d_nhwc_matches_nchw():
    kw = dict(channels=4, kernel_size=3, padding=1, use_bias=False)
    a = nn.Conv2D(layout="NCHW", in_channels=3, **kw)
    b = nn.Conv2D(layout="NHWC", in_channels=3, **kw)
    a.initialize()
    b.initialize()
    b.weight.set_data(a.weight.data().transpose((0, 2, 3, 1)))
    x = nd.random.uniform(shape=(2, 3, 6, 6))
    ya = a(x).asnumpy()
    yb = b(x.transpose((0, 2, 3, 1))).asnumpy()
    np.testing.assert_allclose(ya, yb.transpose(0, 3, 1, 2), rtol=1e-4,
                               atol=1e-5)


def test_avgpool_value():
    net = nn.AvgPool1D(pool_size=2, strides=2)
    y = net(nd.array([[[1.0, 3.0, 5.0, 7.0]]]))
    np.testing.assert_allclose(y.asnumpy(), [[[2.0, 6.0]]])


def test_batchnorm_train_vs_eval():
    net = nn.BatchNorm(axis=1, in_channels=3, momentum=0.5)
    net.initialize()
    x = nd.random.normal(2.0, 3.0, shape=(8, 3, 4, 4))
    with autograd.record():
        y = net(x)
    yn = y.asnumpy()
    assert abs(yn.mean()) < 0.1 and abs(yn.std() - 1.0) < 0.1
    # running stats moved toward batch stats (momentum 0.5: 0 -> ~1.0)
    rm = net.running_mean.data().asnumpy()
    assert rm.mean() > 0.5
    y_eval = net(x).asnumpy()          # eval mode uses running stats
    assert not np.allclose(yn, y_eval)


def test_layernorm_groupnorm_instancenorm():
    x = nd.random.normal(1.0, 2.0, shape=(4, 6, 5))
    ln = nn.LayerNorm(in_channels=5)
    ln.initialize()
    y = ln(x).asnumpy()
    np.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(-1), 1, atol=1e-2)

    gn = nn.GroupNorm(num_groups=2, in_channels=6)
    gn.initialize()
    xg = nd.random.normal(shape=(2, 6, 4, 4))
    assert gn(xg).shape == (2, 6, 4, 4)

    inorm = nn.InstanceNorm(in_channels=6)
    inorm.initialize()
    assert inorm(xg).shape == (2, 6, 4, 4)


def test_activations():
    x = nd.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(nn.Activation("relu")(x).asnumpy(),
                               [0, 0, 0, 0.5, 2.0])
    lrelu = nn.LeakyReLU(0.1)
    np.testing.assert_allclose(lrelu(x).asnumpy()[0], -0.2, rtol=1e-6)
    elu = nn.ELU(1.0)
    assert elu(x).asnumpy()[0] < 0
    selu = nn.SELU()
    assert selu(x).shape == (5,)
    sw = nn.Swish()
    np.testing.assert_allclose(sw(x).asnumpy()[2], 0.0, atol=1e-7)
    g = nn.GELU()
    assert abs(g(x).asnumpy()[2]) < 1e-6
    prelu = nn.PReLU()
    prelu.initialize()
    y = prelu(x)
    assert y.shape == (5,)


def test_embedding_grad_sparse_rows():
    net = nn.Embedding(10, 4)
    net.initialize()
    idx = nd.array([1, 3, 3], dtype="int32")
    with autograd.record():
        y = net(idx).sum()
    y.backward()
    g = net.weight.grad().asnumpy()
    assert (g[1] == 1).all() and (g[3] == 2).all() and (g[0] == 0).all()


def test_dropout_train_eval():
    net = nn.Dropout(0.5)
    x = nd.ones((1000,))
    with autograd.record(train_mode=True):
        y = net(x)
    yn = y.asnumpy()
    assert (yn == 0).mean() > 0.3            # roughly half dropped
    assert abs(yn.mean() - 1.0) < 0.2        # inverted scaling
    assert (net(x).asnumpy() == 1).all()     # identity in eval


def test_sequential_slicing_and_lambda():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=4), nn.Dense(2, in_units=4),
            nn.HybridLambda(lambda F, x: x * 2))
    net.initialize()
    assert len(net) == 3
    y = net(nd.ones((1, 4)))
    assert y.shape == (1, 2)
    sub = net[:2]
    assert len(sub) == 2


def test_concurrent():
    net = nn.Concurrent()
    net.add(nn.Dense(2, in_units=3), nn.Dense(4, in_units=3))
    net.initialize()
    y = net(nd.ones((2, 3)))
    assert y.shape == (2, 6)   # concat along axis 1


def test_reflection_pad():
    net = nn.ReflectionPad2D(1)
    x = nd.array(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    y = net(x).asnumpy()
    assert y.shape == (1, 1, 4, 4)
    assert y[0, 0, 0, 0] == 3.0  # reflected corner


def test_deferred_init_and_in_units_inference():
    net = nn.Dense(4)
    net.initialize()
    y = net(nd.ones((2, 7)))
    assert net.weight.shape == (4, 7)
    assert y.shape == (2, 4)


def test_batchnorm_fused_grad_matches_autodiff():
    """The hand-fused BN backward (custom_vjp) must match jax autodiff of
    the naive formulation to fp32 tolerance."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.nn_ops import _bn_train

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(4, 5, 6, 3).astype(np.float32))
    gamma = jnp.asarray(rng.rand(3).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(3).astype(np.float32))
    eps = 1e-5

    def fused_loss(x, g, b):
        y, _m, _v = _bn_train(x, g, b, jnp.zeros(x.shape[3]), 3, eps)
        return jnp.sum(jnp.sin(y))

    def naive_loss(x, g, b):
        axes = (0, 1, 2)
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        y = (x - mean) * jax.lax.rsqrt(var + eps) * g + b
        return jnp.sum(jnp.sin(y))

    for i, (gf, gn) in enumerate(zip(jax.grad(fused_loss, (0, 1, 2))(x, gamma, beta),
                                     jax.grad(naive_loss, (0, 1, 2))(x, gamma, beta))):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gn),
                                   rtol=2e-4, atol=2e-4, err_msg=f"arg {i}")


def test_stem_conv_s2d_equivalence():
    """stem_conv_s2d == 7x7/s2/p3 NHWC conv, values and gradients."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.nn_ops import convolution, stem_conv_s2d

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 7, 7, 3).astype(np.float32))
    ref = convolution(x, w, stride=2, pad=3, layout="NHWC")
    out = stem_conv_s2d(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    gref = jax.grad(lambda x, w: jnp.sum(
        jnp.sin(convolution(x, w, stride=2, pad=3, layout="NHWC"))),
        (0, 1))(x, w)
    gs2d = jax.grad(lambda x, w: jnp.sum(jnp.sin(stem_conv_s2d(x, w))),
                    (0, 1))(x, w)
    for a, b in zip(gs2d, gref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_batchnorm_large_mean_no_nan():
    """One-pass E[x^2]-E[x]^2 variance is clamped: huge mean, tiny std must
    not NaN (fp32 cancellation regression)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.nn_ops import _bn_train

    x = jnp.full((8, 16, 16, 4), 1000.0) + 0.01 * jnp.asarray(
        np.random.RandomState(0).randn(8, 16, 16, 4).astype(np.float32))
    g = jnp.ones((4,))
    b = jnp.zeros((4,))
    # worst case for raw moments: huge mean, tiny std, zero shift (lagging
    # running mean) — the shifted/clamped formulation must stay finite
    y, mean, var = _bn_train(x, g, b, jnp.zeros(x.shape[3]), 3, 1e-5)
    assert np.isfinite(np.asarray(y)).all()
    assert (np.asarray(var) >= 0).all()


def test_global_pool_keep_dims():
    """keep_dims=False squeezes spatial dims (round-2 review finding)."""
    from mxnet_tpu.gluon import nn
    x = mx.nd.random.uniform(shape=(2, 5, 4, 4))
    assert nn.GlobalAvgPool2D()(x).shape == (2, 5, 1, 1)
    assert nn.GlobalAvgPool2D(keep_dims=False)(x).shape == (2, 5)
    assert nn.GlobalMaxPool2D(keep_dims=False)(x).shape == (2, 5)


def test_norm_and_prelu_layers_trace_symbolically():
    """InstanceNorm/GroupNorm/PReLU emit symbol nodes matching their eager
    kernels (completes gluon layer export coverage)."""
    from mxnet_tpu import sym
    from mxnet_tpu.gluon import nn
    rs = np.random.RandomState(0)
    x_np = rs.randn(2, 6, 5, 5).astype(np.float32)
    for blk in (nn.InstanceNorm(), nn.GroupNorm(num_groups=3), nn.PReLU()):
        blk.initialize()
        x = mx.nd.array(x_np if not isinstance(blk, nn.PReLU)
                        else x_np[:, :1])
        expect = blk(x).asnumpy()
        traced = blk(sym.Variable("data"))
        bindings = {"data": x}
        for p in blk.collect_params().values():
            bindings[p.name] = p.data()
        got = traced.eval_with(bindings).asnumpy()
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)
        _, out_shapes, _ = traced.infer_shape(data=x.shape)
        assert out_shapes == [x.shape]
