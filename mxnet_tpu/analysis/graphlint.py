"""Structural linter over the lowered jaxpr + optimized HLO of the
framework's jitted executables (ISSUE 13 — the graph half of
graft-lint).

Where `astlint` reads the framework's *source*, this module reads what
the framework actually *ships to the accelerator*: for each
compilex-registered executable it AOT-traces against abstract avals
(the jaxpr re-trace is cached, so traced python does NOT re-run and
``decode_traces``-style pins hold — the PR 11 inspection discipline)
and checks the structure XLA-level speed depends on:

  MXTPU-G01  donation leak — an input leaf the framework donated
             (``args_info.donated``) that the compiled module does NOT
             alias to an output (``input_output_alias``): XLA copies
             the update path out of place instead of updating in place,
             exactly the regression class check_fusion's alias counts
             were added to catch, now attributed per executable.
  MXTPU-G02  copies above the executable's allowance, each attributed
             back to its source op via HLO metadata ``op_name`` — a
             rising copy count with a named source beats a bare number.
  MXTPU-G03  dead or duplicate collectives — a collective whose result
             feeds nothing (dead weight XLA kept), or two collectives
             with identical (op, shape, operands, groups): both burn
             interconnect for nothing.
  MXTPU-G04  unconstrained sharding — in a program where at least one
             input carries an ``mhlo.sharding`` annotation (a ShardPlan
             is in force), another input above `min_shard_bytes` with
             NO annotation: GSPMD is free to replicate it.
  MXTPU-G05  retrace hazard — a closure-captured SCALAR constant with a
             strong (non-weak) dtype in the jaxpr consts: the value is
             baked into the trace, so the next different value means a
             full re-trace + re-compile (the PR 4 weak-typed-args
             discipline).

The text analyzers (`find_*`) are pure functions over HLO / StableHLO
text so `tools/check_static.py`'s seeded-violation controls and the
tests can feed them synthetic modules; `lint_jit` wires them to a live
jitted callable. Baseline/suppression semantics are shared with astlint
through the same tools/static_baseline.json ("graph" section; an entry
is {rule, executable, key, why}).
"""
from __future__ import annotations

import re
from dataclasses import dataclass

__all__ = ["GraphFinding", "GRAPH_RULES", "find_copies",
           "find_dead_or_dup_collectives", "find_unconstrained_args",
           "find_strong_scalar_consts", "find_donation_leaks",
           "lint_hlo_texts", "lint_jit", "lint_instrumented",
           "apply_graph_baseline"]

GRAPH_RULES = {
    "MXTPU-G01": "donated input not aliased in input_output_alias",
    "MXTPU-G02": "copies above allowance (attributed to source ops)",
    "MXTPU-G03": "dead or duplicate collective",
    "MXTPU-G04": "unconstrained sharding on a large input under a plan",
    "MXTPU-G05": "strong-typed scalar closure constant (retrace hazard)",
}

# collective opcodes (async -start forms count as the op; -done halves
# are the completion marker, not a second collective)
_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather",
                "all-to-all", "collective-permute")


@dataclass
class GraphFinding:
    rule: str
    executable: str
    key: str             # stable detail fingerprint component
    message: str
    baselined: bool = False

    @property
    def fingerprint(self):
        return (self.rule, self.executable, self.key)

    def to_dict(self):
        return {"rule": self.rule, "executable": self.executable,
                "key": self.key, "message": self.message}

    def __str__(self):
        return f"{self.executable}: {self.rule} [{self.key}] " \
               f"{self.message}"


# -------------------------------------------------------- HLO text parse
# one optimized-HLO instruction: optional ROOT, %name = <shape> op(args)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*[^=]*?\s([a-z][a-z0-9\-]*)"
    r"\((.*)$")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
_METADATA_RE = re.compile(r'op_name="([^"]*)"')


def _instructions(hlo_text):
    """Yield (result, opcode, operand names, rest-of-line, is_root) for
    every instruction line of an optimized-HLO module text."""
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        result, opcode, rest = m.groups()
        # operands live before the first "), " attr break; %-names in
        # attrs (e.g. calls=%fused_computation) would inflate usage, so
        # split at the closing paren of the operand list
        depth, end = 1, len(rest)
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        operands = _OPERAND_RE.findall(rest[:end])
        yield (result, opcode, operands, rest[end:],
               line.lstrip().startswith("ROOT"))


def find_copies(hlo_text):
    """[(source op_name or '<unattributed>', count)] for every
    copy/copy-start in the module, largest first."""
    sources = {}
    for _, opcode, _, rest, _ in _instructions(hlo_text):
        if opcode not in ("copy", "copy-start"):
            continue
        m = _METADATA_RE.search(rest)
        src = m.group(1) if m else "<unattributed>"
        sources[src] = sources.get(src, 0) + 1
    return sorted(sources.items(), key=lambda kv: -kv[1])


def _references(hlo_text, result):
    """Occurrences of %result in the module BEYOND its definition —
    robust to instruction lines the structured parse can't handle (the
    ROOT tuple of a big module overflows any line regex)."""
    pat = re.compile(r"%" + re.escape(result) + r"(?![\w.\-])")
    return len(pat.findall(hlo_text)) - 1


def find_dead_or_dup_collectives(hlo_text):
    """[{kind: 'dead'|'duplicate', op, result, detail}] over the module.
    Dead: the collective's result is referenced nowhere beyond its
    definition (whole-text occurrence count, so consumers on lines the
    instruction parse skips still count) and is not ROOT. Duplicate:
    identical (op, operands, replica_groups, dimensions) pairs."""
    colls = []      # (result, op, key, is_root)
    for result, opcode, operands, rest, is_root in _instructions(
            hlo_text):
        base = opcode[:-6] if opcode.endswith("-start") else opcode
        if base in _COLLECTIVES:
            groups = ""
            mg = re.search(r"replica_groups=({[^}]*}|\S+)", rest)
            if mg:
                groups = mg.group(1)
            dims = ""
            md = re.search(r"dimensions={[^}]*}", rest)
            if md:
                dims = md.group(0)
            key = (base, tuple(sorted(operands)), groups, dims)
            colls.append((result, base, key, is_root))
    out = []
    seen = {}
    for result, op, key, is_root in colls:
        if not is_root and _references(hlo_text, result) == 0:
            out.append({"kind": "dead", "op": op, "result": result,
                        "detail": f"result %{result} feeds nothing"})
        first = seen.get(key)
        if first is not None:
            out.append({"kind": "duplicate", "op": op, "result": result,
                        "detail": f"identical to %{first} "
                                  f"(same operands/groups)"})
        else:
            seen[key] = result
    return out


# StableHLO entry arguments: %argN: tensor<2x3xf32> {attrs}
_ARG_RE = re.compile(
    r"%arg(\d+):\s*tensor<([0-9x]*?)x?(f64|f32|f16|bf16|i64|i32|i16|i8|"
    r"u64|u32|u16|u8|i1)>\s*(\{[^}]*\})?")
_DTYPE_BYTES = {"f64": 8, "i64": 8, "u64": 8, "f32": 4, "i32": 4,
                "u32": 4, "f16": 2, "bf16": 2, "i16": 2, "u16": 2,
                "i8": 1, "u8": 1, "i1": 1}


def find_unconstrained_args(stablehlo_text, min_bytes=1024):
    """Under a plan, the args above `min_bytes` with NO sharding
    annotation: [(argnum, bytes)]. "Under a plan" means at least one
    arg carries a real GSPMD tile assignment (``devices=[...]``) — a
    ``maximal`` (single-device commit) or absent annotation does not
    put the program under a plan, and an explicit ``replicated``
    annotation on an arg is a constrained choice, not a finding."""
    # only the PUBLIC entry signature: private helper funcs also bind
    # %arg0..., annotation-free, and must not count as entry inputs
    start = stablehlo_text.find("func.func public @main(")
    if start >= 0:
        open_i = stablehlo_text.index("(", start)
        depth, end_i = 0, len(stablehlo_text)
        for i in range(open_i, len(stablehlo_text)):
            ch = stablehlo_text[i]
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end_i = i
                    break
        stablehlo_text = stablehlo_text[open_i:end_i]
    args = []
    any_planned = False
    for m in _ARG_RE.finditer(stablehlo_text):
        argnum, dims, dtype, attrs = m.groups()
        n = 1
        for d in (dims.split("x") if dims else []):
            if d:
                n *= int(d)
        nbytes = n * _DTYPE_BYTES.get(dtype, 4)
        attrs = attrs or ""
        constrained = "mhlo.sharding" in attrs
        if constrained and "devices=[" in attrs:
            any_planned = True
        args.append((int(argnum), nbytes, constrained))
    if not any_planned:
        return []
    return [(a, b) for a, b, constrained in args
            if not constrained and b >= min_bytes]


def find_strong_scalar_consts(jaxpr):
    """Scalar (size-1) consts with a strong (non-weak) inexact/integer
    dtype in a ClosedJaxpr — the value is baked into the trace:
    [(index, dtype, shape)]."""
    out = []
    consts = getattr(jaxpr, "consts", ())
    cvars = getattr(getattr(jaxpr, "jaxpr", None), "constvars", ())
    for i, (c, v) in enumerate(zip(consts, cvars)):
        aval = getattr(v, "aval", None)
        shape = tuple(getattr(aval, "shape", getattr(c, "shape", ())))
        size = 1
        for d in shape:
            size *= d
        if size != 1:
            continue
        dtype = getattr(aval, "dtype", getattr(c, "dtype", None))
        if dtype is None or str(dtype) == "bool":
            continue
        if not getattr(aval, "weak_type", False):
            out.append((i, str(dtype), shape))
    return out


def find_donation_leaks(args_info, optimized_text):
    """(donated_leaves, aliased_count): how many input leaves were
    donated vs how many the compiled module aliases in place. A
    shortfall is the G01 finding."""
    import jax

    leaves = jax.tree_util.tree_leaves(
        args_info, is_leaf=lambda x: hasattr(x, "donated"))
    donated = sum(1 for a in leaves if getattr(a, "donated", False))
    aliased = optimized_text.count("may-alias") \
        + optimized_text.count("must-alias")
    return donated, aliased


# ------------------------------------------------------------- the linter
def lint_hlo_texts(executable, optimized_text, stablehlo_text=None,
                   jaxpr=None, args_info=None, copies_allow=0,
                   min_shard_bytes=1024):
    """Run every graph rule that its inputs allow; pure — no jax work
    beyond tree_leaves. Returns [GraphFinding]."""
    findings = []
    if args_info is not None:
        donated, aliased = find_donation_leaks(args_info, optimized_text)
        if aliased < donated:
            findings.append(GraphFinding(
                "MXTPU-G01", executable,
                f"aliased {aliased} of {donated} donated",
                f"{donated - aliased} donated input leaf/leaves not in "
                f"input_output_alias — XLA materialises the update out "
                f"of place"))
    copies = find_copies(optimized_text)
    total_copies = sum(n for _, n in copies)
    if total_copies > copies_allow:
        top = ", ".join(f"{src.rsplit('/', 1)[-1]}x{n}"
                        for src, n in copies[:4])
        findings.append(GraphFinding(
            "MXTPU-G02", executable,
            f"copies>{copies_allow}",
            f"{total_copies} copies (allowance {copies_allow}); top "
            f"sources: {top}"))
    for d in find_dead_or_dup_collectives(optimized_text):
        findings.append(GraphFinding(
            "MXTPU-G03", executable,
            f"{d['kind']}:{d['op']}",
            f"{d['kind']} {d['op']}: {d['detail']}"))
    if stablehlo_text is not None:
        for argnum, nbytes in find_unconstrained_args(
                stablehlo_text, min_bytes=min_shard_bytes):
            findings.append(GraphFinding(
                "MXTPU-G04", executable,
                f"arg{argnum}",
                f"input %arg{argnum} ({nbytes} B) has no sharding "
                f"annotation while the program runs under a plan — "
                f"GSPMD may replicate it"))
    if jaxpr is not None:
        for idx, dtype, shape in find_strong_scalar_consts(jaxpr):
            findings.append(GraphFinding(
                "MXTPU-G05", executable,
                f"const{idx}:{dtype}",
                f"closure-captured strong-typed scalar const #{idx} "
                f"({dtype}{list(shape)}) — a different value at this "
                f"site means a full retrace; ride it as a weak-typed "
                f"arg"))
    return findings


def lint_jit(jfn, *args, executable="executable", copies_allow=0,
             min_shard_bytes=1024, **kwargs):
    """AOT trace+lower+compile `jfn` (an InstrumentedJit or bare jitted
    callable) for the avals of `args`/`kwargs` and run every graph rule.
    Traced python does not re-run (the jaxpr cache), and the duplicate
    XLA compile is flagged as inspection so the compile-cache counters
    stay honest."""
    import jax

    from ..observability import compilex as _compilex

    jfn = getattr(jfn, "_jfn", jfn)
    aargs, akwargs = jax.tree_util.tree_map(_compilex._abstract,
                                            (args, kwargs))
    tl = _compilex._tl
    prev = getattr(tl, "inspecting", False)
    tl.inspecting = True
    try:
        import warnings
        with warnings.catch_warnings():
            # donated-but-unaliased inputs warn at lower(); that signal
            # IS finding G01 — don't also spam stderr while linting
            warnings.simplefilter("ignore")
            traced = jfn.trace(*aargs, **akwargs)
            lowered = traced.lower()
            compiled = lowered.compile()
    finally:
        tl.inspecting = prev
    return lint_hlo_texts(
        executable,
        compiled.as_text(),
        stablehlo_text=lowered.as_text(),
        jaxpr=traced.jaxpr,
        args_info=getattr(lowered, "args_info", None),
        copies_allow=copies_allow,
        min_shard_bytes=min_shard_bytes)


def lint_instrumented(ij, copies_allow=0, min_shard_bytes=1024):
    """Lint a live `compilex.InstrumentedJit` using the aval skeleton it
    recorded at its last compile (`last_abstract`); returns None when
    the wrapper never compiled in this process."""
    la = getattr(ij, "last_abstract", None)
    if la is None:
        return None
    args, kwargs = la
    return lint_jit(ij, *args, executable=ij.executable,
                    copies_allow=copies_allow,
                    min_shard_bytes=min_shard_bytes, **kwargs)


def apply_graph_baseline(findings, baseline_entries):
    """Same contract as astlint.apply_baseline, over the baseline's
    "graph" section ({rule, executable, key, why} entries)."""
    index = {(e["rule"], e["executable"], e.get("key", "")): e
             for e in baseline_entries}
    used = set()
    new, matched = [], []
    for f in findings:
        e = index.get(f.fingerprint)
        if e is not None:
            f.baselined = True
            used.add(id(e))
            matched.append(f)
        else:
            new.append(f)
    stale = [e for e in baseline_entries if id(e) not in used]
    return new, matched, stale
