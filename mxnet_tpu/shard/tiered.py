"""Tiered embedding storage: host-resident cold rows + a fixed-size
device-resident hot cache, prefetched by the dependency engine
(ISSUE 19; docs/PERFORMANCE.md "Tiered embeddings").

A 10**8-row table exceeds HBM even row-sharded (PR 15 halves bytes per
device; it cannot shrink the table). Production recommenders keep the
hot rows on-device and the cold tail in host DRAM — and this framework's
actual differentiator, the host-side dependency engine, is exactly the
machinery to hide the host<->HBM row movement behind compute, the same
way `DevicePrefetcher` stages batches (PR 5).

Layout per converted table (`ShardedEmbedding(tiered=True, hbm_rows=C)`
after `Trainer.shard`):

  host tier  (this module, pinned numpy)
    host_weight (vocab, D)        the FULL logical table
    host state  (vocab, D) per row-shaped optimizer-state leaf, created
                lazily-by-construction: a never-resident row has never
                been updated, so its state rows are exactly their init
                values (multi_tensor.classify_state_rows — zeros, or the
                weight cast for fp32 masters)

  device tier (the parameter's live data — the captured step trains it
               directly, the sparse fast path unchanged)
    hot cache   (S*C, D) row-sharded over the table's mesh axis (S
                shards x hbm_rows slots each); slot s lives on shard
                s // C
    id maps     slot_of (vocab,) id -> slot | -1;  id_at (S*C,) slot ->
                id | -1;  LRU stamps

The pipeline (strict depth-1, driven by `prefetch.RowPrefetcher`):

  1. PLAN (host, engine background task, overlapped with step k's
     device compute): dedup batch k+1's raw row ids; hits translate to
     slots for free. Misses pick victim slots — free first, then LRU
     among slots batch k+1 does not need — write the victims' CURRENT
     weight+state rows back device->host (every resident row is dirty:
     the scatter-add update touched it the step it was inserted), and
     stage the incoming cold rows as committed replicated device_put
     blocks (async H2D — `embed_h2d_bytes`). The batch's ids are
     REWRITTEN to slot ids: the captured program never learns the table
     was tiered.
  2. STEP k+1 (one dispatch, unchanged executable shape): the program
     first scatter-drops the incoming blocks into their slots
     (`embedding.scatter_rows`, zero collectives), then runs the normal
     sparse fast path against the cache as if it were a (S*C, D) table
     — dedup, 2 all-to-alls, hoisted-row backward, scatter-add update
     into the touched slots. An all-hit step stages NOTHING: the cached
     all-sentinel block is reused and sync H2D on the hot path is zero
     (tools/check_dispatch.py `_run_tiered_phase` pins this).

Correct by data flow, not by locks: the plan task gathers writeback
rows with `np.asarray` on the post-step-k arrays (blocks until step k's
compute lands), and step k+1 cannot dispatch until the prefetcher
returns the translated batch.

Checkpoints save the FLUSHED full logical table through the manifest
(`manifest["tiered"]`) — restore works onto any mesh size because the
host tier is the logical value (checkpoint.save_sharded/load_sharded
route through `swap_for_save` / `prepare_restore` / `finish_restore`).
"""
from __future__ import annotations

import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..observability import registry as _obs_registry

__all__ = ["TieredState", "on_plan", "register_hbm_rows", "hbm_rows_for",
           "state_for", "tiered_tables", "release", "swap_for_save",
           "prepare_restore", "finish_restore"]

_reg = _obs_registry()
_hits_c = _reg.counter("embed_cache_hits")
_miss_c = _reg.counter("embed_cache_misses")
_evict_c = _reg.counter("embed_cache_evictions")
_h2d_b = _reg.counter("embed_h2d_bytes")
_writeback_b = _reg.counter("embed_writeback_bytes")
_hit_rate_g = _reg.gauge("embed_cache_hit_rate")

# name-keyed registries (parameter names are the stable identity that
# survives save/restore and mesh resizes):
#   _HBM_ROWS — declared at ShardedEmbedding(tiered=True) construction,
#     BEFORE any plan resolves the name (ShardPlan._check_large_replicated
#     reads it to warn on HBM-resident bytes, not the host-tier shard)
#   _REGISTRY — live TieredState per converted table (checkpoint routing)
# Because checkpoint routing is name-keyed, a second LIVE table under an
# already-registered name is a hard error at conversion (`on_plan`) —
# a silent overwrite would route saves/restores into the wrong
# TieredState. Discarding a model frees its name via `release`.
_HBM_ROWS = {}
_REGISTRY = {}


def register_hbm_rows(name, hbm_rows):
    _HBM_ROWS[name] = int(hbm_rows)


def hbm_rows_for(name):
    """Declared hot-cache rows per shard for a tiered table name, or
    None for an untiered parameter."""
    return _HBM_ROWS.get(name)


def state_for(name):
    """The live `TieredState` for a converted table name, or None."""
    return _REGISTRY.get(name)


def tiered_tables():
    """{name: TieredState} for every converted table in this process."""
    return dict(_REGISTRY)


def release(name):
    """Drop a discarded table's registry entries (its live `TieredState`
    and the declared hbm_rows budget). Call this when the model/trainer
    that owned a tiered table is discarded and a NEW table will reuse
    its parameter name — e.g. rebuilding a same-prefix model for a
    checkpoint restore — because `on_plan` refuses a name collision
    rather than silently rerouting checkpoints. Returns True when a
    live state was registered under `name`."""
    _HBM_ROWS.pop(name, None)
    return _REGISTRY.pop(name, None) is not None


@jax.jit
def _take_rows(arrs, idx):
    # one shared jitted gather for writeback/flush: jax's jit cache keys
    # on the avals, and callers pad idx to a power-of-two length so the
    # retrace count stays logarithmic in the eviction batch size
    return tuple(jnp.take(a, idx, axis=0) for a in arrs)


def _resolve_axis(plan, name, shape):
    """The mesh axis a tiered table's rule row-shards it over. Resolution
    prefers the normalised spec; when the LOGICAL vocab does not divide
    the axis (irrelevant — only the cache lives on device) the raw
    matched rule decides. Purely-row-sharded (spec[1:] all None) is
    required: the hot cache must take the PR 15 sparse fast path."""
    from . import rules as _rules
    spec = tuple(plan.spec_for(name, shape))
    axis = spec[0] if spec and isinstance(spec[0], str) else None
    trailing = spec[1:]
    if axis is None:
        raw, _rep = _rules.match_partition_rules(
            plan.rules, {name: tuple(shape)})
        rspec = tuple(raw[name] or ())
        if rspec and isinstance(rspec[0], str):
            axis = rspec[0]
            trailing = rspec[1:]
    if axis is not None and any(e is not None for e in trailing):
        raise MXNetError(
            f"tiered embedding {name!r}: its rule shards more than the "
            f"row dim ({spec!r}) — a tiered table must be purely "
            f"row-sharded so its hot cache takes the sparse fast path")
    if axis is None or axis not in plan.mesh.shape:
        raise MXNetError(
            f"tiered embedding {name!r}: no partition rule row-shards "
            f"it over a mesh axis (resolved spec {spec!r}); add a rule "
            f"like ('{name}$', 'tp')")
    n = int(plan.mesh.shape[axis])
    if n < 2:
        raise MXNetError(
            f"tiered embedding {name!r}: mesh axis {axis!r} has size "
            f"{n}; tiering needs the table row-sharded over an axis of "
            f"size >= 2 (the sparse fast path's eligibility)")
    return axis, n


def _state_leaves(updater, index):
    st = updater.states.get(index)
    return st if isinstance(st, tuple) else \
        ((st,) if st is not None else ())


def _zeros_like_placed(arr):
    return jax.device_put(np.zeros(arr.shape, arr.dtype), arr.sharding)


class TieredState:
    """Host tier + hot-cache bookkeeping for ONE converted table (module
    docstring). Built by `on_plan` (never directly); thread-safe — the
    RowPrefetcher resolves on an engine worker while the training loop
    dispatches."""

    def __init__(self, param, hbm_rows):
        self.param = param
        self.name = param.name
        self.hbm_rows = int(hbm_rows)
        self.vocab = int(param._sharded_embedding["vocab"])
        self.dim = int(param._sharded_embedding["dim"])
        self._lock = threading.RLock()
        self._listeners = []
        self._pending = None
        self._staged_rows = None   # (ids, slots) of an outstanding plan
        self._zero_blocks = {}     # M -> cached all-sentinel arg tuple
        # filled by _attach:
        self.axis = self.n_shards = self.n_slots = None
        self.mesh = self._repl = None
        self.host_weight = None
        self.host_state = []       # np (vocab, D) per ROW-LIKE leaf
        self.kinds = ()            # per state leaf: zero|master|None
        self.row_like = ()
        self.state_nds = ()        # the leaf NDArrays cachedop rebinds
        self.slot_of = self.id_at = self.stamp = None
        self.clock = 0

    # ------------------------------------------------------- conversion
    def _attach(self, trainer, plan, index):
        """(Re)build the device tier on `plan`: fresh zero cache + fresh
        optimizer state placed on the plan's shardings, maps reset. The
        host tier must already hold the logical table."""
        p = self.param
        axis, n_shards = _resolve_axis(plan, self.name,
                                       (self.vocab, self.dim))
        n_slots = n_shards * self.hbm_rows
        cache_sh = plan.sharding(self.name, (n_slots, self.dim))
        if tuple(cache_sh.spec) and cache_sh.spec[0] != axis:
            raise MXNetError(
                f"tiered embedding {self.name!r}: the rule shards the "
                f"(S*hbm_rows, D) cache over {cache_sh.spec!r}, not the "
                f"table's row axis {axis!r}")
        dtype = self.host_weight.dtype
        cache = jax.device_put(np.zeros((n_slots, self.dim), dtype),
                               cache_sh)
        p._data._rebind(cache)
        if p._grad is not None:
            p._grad._rebind(jax.device_put(
                np.zeros((n_slots, self.dim), p._grad._data.dtype),
                cache_sh))

        opt = trainer._optimizer
        updater = trainer._updater
        old_leaves = _state_leaves(updater, index)
        updater.states.pop(index, None)
        st = opt.create_state_multi_precision(index, p.data())
        updater.states[index] = st
        leaves = _state_leaves(updater, index)
        self.row_like = tuple(
            s is not None and
            tuple(s._data.shape) == (n_slots, self.dim) for s in leaves)
        for j, (s, rl) in enumerate(zip(leaves, self.row_like)):
            if rl or s is None or j >= len(old_leaves):
                continue
            old = old_leaves[j]
            if old is not None and \
                    tuple(old._data.shape) == tuple(s._data.shape):
                # scalar leaves (step counters, ...) carry their value
                # across the rebuild — they are not tiered
                s._rebind(jnp.asarray(np.asarray(old._data)))
        self.state_nds = leaves
        self.axis, self.n_shards, self.n_slots = axis, n_shards, n_slots
        self.mesh = plan.mesh
        self._repl = NamedSharding(plan.mesh, P())
        self.slot_of = np.full((self.vocab,), -1, np.int64)
        self.id_at = np.full((n_slots,), -1, np.int64)
        self.stamp = np.zeros((n_slots,), np.int64)
        self.clock = 0
        self._pending = None
        self._staged_rows = None
        self._zero_blocks.clear()

    def _init_host_state(self, old_leaves=()):
        """Host stores for the row-like state leaves, from their lazy
        init rule (`classify_state_rows` kinds) — or captured from a
        pre-existing FULL-shape leaf (a trainer that already stepped
        before tiering)."""
        self.host_state = []
        ri = -1
        for j, (kind, rl) in enumerate(zip(self.kinds, self.row_like)):
            if not rl:
                continue
            ri += 1
            dt = np.dtype(self.state_nds[j]._data.dtype)
            old = old_leaves[j] if j < len(old_leaves) else None
            if old is not None and \
                    tuple(old._data.shape) == (self.vocab, self.dim):
                self.host_state.append(
                    np.array(np.asarray(old._data), dtype=dt))
            elif kind == "master":
                self.host_state.append(self.host_weight.astype(dt))
            else:
                self.host_state.append(
                    np.zeros((self.vocab, self.dim), dt))

    def retier(self, trainer, plan, index):
        """Elastic reshard (Trainer.resize_mesh): flush the live cache
        into the host tier on the OLD mesh, then rebuild the device tier
        directly on the new plan's shardings. The host tier — weight AND
        the row-like optimizer-state stores — is preserved across the
        rebuild: the stores are mesh-free (vocab, D) numpy arrays and
        after `flush` they ARE the logical state, so re-initialising
        them here would silently zero momentum/Adam rows and re-derive
        fp32 masters from the low-precision weight. Any RowPrefetcher
        feeding this table keeps working (listeners survive), but its
        staged plan — if one was in flight — is dropped with the
        cache."""
        with self._lock:
            self.flush()
            n_host = len(self.host_state)
            self._attach(trainer, plan, index)
            if sum(map(bool, self.row_like)) != n_host:
                raise MXNetError(
                    f"tiered embedding {self.name!r}: the rebuilt "
                    f"optimizer state has "
                    f"{sum(map(bool, self.row_like))} row-like leaves "
                    f"but the host tier holds {n_host} stores — the "
                    f"optimizer changed shape across resize_mesh")

    # ------------------------------------------------- the row pipeline
    def plan_step(self, idx):
        """Resolve one index batch AGAINST the hot cache (host side,
        engine-worker safe): evict + write back what must go, stage the
        incoming cold rows as committed replicated device blocks, and
        return `idx` rewritten to SLOT ids. Exactly one un-stepped plan
        may be outstanding (the strict depth-1 contract RowPrefetcher
        drives); the staged product is popped by the next captured-step
        dispatch."""
        idx = np.asarray(idx)
        if not np.issubdtype(idx.dtype, np.integer):
            raise MXNetError(
                f"tiered embedding {self.name!r}: index batch dtype "
                f"{idx.dtype} — integer indices are required")
        flat = idx.reshape(-1).astype(np.int64)
        M = int(flat.size)
        with self._lock:
            if self._pending is not None:
                raise MXNetError(
                    f"tiered embedding {self.name!r}: a staged row plan "
                    f"was never consumed — every planned batch must be "
                    f"STEPPED before the next resolves (drive the loop "
                    f"through prefetch.RowPrefetcher; do not fetch two "
                    f"batches per step)")
            if M and (flat.min() < 0 or flat.max() >= self.vocab):
                raise MXNetError(
                    f"tiered embedding {self.name!r}: index out of "
                    f"range for vocab {self.vocab}")
            uniq = np.unique(flat)
            cur = self.slot_of[uniq]
            hit = cur >= 0
            n_hits = int(hit.sum())
            n_miss = int(uniq.size) - n_hits
            _hits_c.inc(n_hits)
            _miss_c.inc(n_miss)
            _hit_rate_g.set(n_hits / uniq.size if uniq.size else 1.0)
            if uniq.size > self.n_slots:
                raise MXNetError(
                    f"tiered embedding {self.name!r}: cache thrash — "
                    f"this step needs {uniq.size} unique rows but the "
                    f"hot cache holds {self.n_slots} slots "
                    f"({self.n_shards} shards x hbm_rows="
                    f"{self.hbm_rows}). Raise hbm_rows to at least "
                    f"ceil(unique_rows_per_step / {self.n_shards}) or "
                    f"shrink the batch; a cache smaller than one step's "
                    f"working set cannot make progress")
            misses = uniq[~hit]
            new_slots = np.empty((0,), np.int64)
            if n_miss:
                free = np.flatnonzero(self.id_at < 0)
                take = free[:n_miss]
                n_evict = n_miss - int(take.size)
                if n_evict > 0:
                    needed = np.zeros((self.n_slots,), bool)
                    needed[cur[hit]] = True
                    cand = np.flatnonzero((self.id_at >= 0) & ~needed)
                    order = np.argsort(self.stamp[cand], kind="stable")
                    evict = cand[order[:n_evict]]
                    self._writeback(evict)
                    new_slots = np.concatenate([take, evict])
                else:
                    new_slots = take
                self.slot_of[misses] = new_slots
                self.id_at[new_slots] = misses
            # LRU touch for every slot this step references
            self.clock += 1
            self.stamp[self.slot_of[uniq]] = self.clock
            self._pending = self._incoming(misses, new_slots, M)
            # the staged rows' cache slots hold stale data until the
            # step's scatter-in lands: flush/lookup must keep reading
            # them host-side, and drop_pending can roll them back
            self._staged_rows = (misses, new_slots)
            slots_flat = self.slot_of[flat].astype(np.int32)
        return slots_flat.reshape(idx.shape)

    def _row_arrays(self):
        return (self.param._data._data,) + tuple(
            s._data for s, rl in zip(self.state_nds, self.row_like)
            if rl)

    def _gather_rows(self, slots):
        """Device->host gather of `slots` from the cache + row-like
        state leaves (padded to a power of two so the shared jit
        retraces O(log) times). Blocks until in-flight compute lands —
        the writeback correctness barrier."""
        n = int(slots.size)
        cap = 1 << max(0, (n - 1).bit_length())
        pad = np.zeros((max(cap, 1),), np.int32)
        pad[:n] = slots
        out = _take_rows(self._row_arrays(), pad)
        return [np.asarray(o)[:n] for o in out]

    def _writeback(self, evict):
        """Spill `evict` slots host-side: every resident row is dirty
        (the scatter-add update touched it the step it came in), so the
        weight AND state rows copy back unconditionally."""
        blocks = self._gather_rows(evict)
        ids = self.id_at[evict]
        self.host_weight[ids] = blocks[0].astype(self.host_weight.dtype,
                                                 copy=False)
        for store, rows in zip(self.host_state, blocks[1:]):
            store[ids] = rows.astype(store.dtype, copy=False)
        self.slot_of[ids] = -1
        self.id_at[evict] = -1
        self.stamp[evict] = 0
        _evict_c.inc(int(evict.size))
        _writeback_b.inc(sum(int(b.nbytes) for b in blocks))

    def _incoming(self, misses, slots, M):
        """The staged scatter-in product for one step: `(inc_slots,
        inc_rows, *inc_state_rows)`, committed replicated, STATIC length
        M (= the step's flat index count — the executable's shape never
        depends on the miss count) with the `n_slots` sentinel padding.
        All-hit steps reuse one cached all-sentinel tuple per M: zero
        H2D on the warm path."""
        n = int(misses.size)
        if n == 0:
            cached = self._zero_blocks.get(M)
            if cached is None:
                cached = self._zero_blocks[M] = self._stage(
                    np.full((M,), self.n_slots, np.int32),
                    [np.zeros((M, self.dim), self.host_weight.dtype)] +
                    [np.zeros((M, self.dim), s.dtype)
                     for s in self.host_state])
            return cached
        inc_slots = np.full((M,), self.n_slots, np.int32)
        inc_slots[:n] = slots
        rows = np.zeros((M, self.dim), self.host_weight.dtype)
        rows[:n] = self.host_weight[misses]
        blocks = [rows]
        for store in self.host_state:
            b = np.zeros((M, self.dim), store.dtype)
            b[:n] = store[misses]
            blocks.append(b)
        return self._stage(inc_slots, blocks)

    def _stage(self, inc_slots, blocks):
        nbytes = int(inc_slots.nbytes) + sum(int(b.nbytes)
                                             for b in blocks)
        _h2d_b.inc(nbytes)
        # committed replicated async device_put — overlaps step k's
        # compute; the dispatch passes these straight into the jit
        return tuple(jax.device_put([inc_slots] + blocks,
                                    [self._repl] * (1 + len(blocks))))

    def take_pending(self):
        with self._lock:
            out, self._pending = self._pending, None
            if out is not None:
                # the consuming dispatch scatters the staged rows in;
                # from here their cache slots are the live copies
                self._staged_rows = None
            return out

    def drop_pending(self):
        """Discard a staged-but-never-stepped row plan
        (`RowPrefetcher.close` after a fetched batch was abandoned):
        without this the table is wedged — the next `plan_step` raises
        forever on the unconsumed plan. The staged incoming rows never
        reached the cache, so their residency rolls back (the host rows
        are still authoritative — `_incoming` copied, never moved) and
        the next plan starts clean. Returns True when a plan was
        dropped."""
        with self._lock:
            if self._pending is None:
                return False
            staged, self._staged_rows = self._staged_rows, None
            self._pending = None
            if staged is not None:
                ids, slots = staged
                if ids.size:
                    self.slot_of[ids] = -1
                    self.id_at[slots] = -1
                    self.stamp[slots] = 0
            return True

    def _live_slots(self):
        """Resident slots whose CACHE rows are current — excludes slots
        claimed by an outstanding plan (their scatter-in has not run;
        the host tier still holds their rows)."""
        live = np.flatnonzero(self.id_at >= 0)
        staged = self._staged_rows
        if staged is not None and staged[1].size:
            live = np.setdiff1d(live, staged[1], assume_unique=True)
        return live

    # step listeners: cachedop fires notify_step() after a dispatch's
    # rebinds — RowPrefetcher hangs the NEXT batch's resolve off it
    def add_step_listener(self, cb):
        with self._lock:
            if cb not in self._listeners:
                self._listeners.append(cb)

    def remove_step_listener(self, cb):
        with self._lock:
            if cb in self._listeners:
                self._listeners.remove(cb)

    def notify_step(self):
        with self._lock:
            listeners = tuple(self._listeners)
        for cb in listeners:
            cb()

    # --------------------------------------------------- host-tier I/O
    def flush(self):
        """Mirror every RESIDENT row back into the host tier (rows stay
        cached — maps unchanged). After this, host_weight/host_state ARE
        the logical table+state."""
        with self._lock:
            live = self._live_slots()
            if not live.size:
                return
            blocks = self._gather_rows(live)
            ids = self.id_at[live]
            self.host_weight[ids] = blocks[0].astype(
                self.host_weight.dtype, copy=False)
            for store, rows in zip(self.host_state, blocks[1:]):
                store[ids] = rows.astype(store.dtype, copy=False)

    def export_table(self):
        """The full logical (vocab, D) table, flushed, as numpy — what
        checkpoints save."""
        with self._lock:
            self.flush()
            return self.host_weight.copy()

    def export_state(self):
        """Flushed full logical row-like state stores, in state-leaf
        order (row-like leaves only)."""
        with self._lock:
            self.flush()
            return [s.copy() for s in self.host_state]

    def import_table(self, full):
        """Replace the logical table (checkpoint restore): host_weight
        := full, state stores re-derive from their init rule, the device
        cache goes COLD (zeroed in place, shardings kept) and any staged
        plan is dropped. Resize-proof by construction — the host tier
        never depends on the mesh."""
        full = np.asarray(full)
        if tuple(full.shape) != (self.vocab, self.dim):
            raise MXNetError(
                f"tiered embedding {self.name!r}: imported table shape "
                f"{tuple(full.shape)} != ({self.vocab}, {self.dim})")
        with self._lock:
            self.host_weight = full.astype(self.host_weight.dtype,
                                           copy=True)
            self._init_host_state()
            self.param._data._rebind(
                _zeros_like_placed(self.param._data._data))
            if self.param._grad is not None:
                self.param._grad._rebind(
                    _zeros_like_placed(self.param._grad._data))
            for s, rl in zip(self.state_nds, self.row_like):
                if rl:
                    s._rebind(_zeros_like_placed(s._data))
            self.slot_of[:] = -1
            self.id_at[:] = -1
            self.stamp[:] = 0
            self.clock = 0
            self._pending = None
            self._staged_rows = None
            self._zero_blocks.clear()

    # ----------------------------------------------------- eager reads
    def lookup_np(self, idx):
        """Eager/eval lookup through the host tier: the logical table is
        host_weight overlaid with the LIVE cache rows (flush without the
        store mutation). Correct anywhere; slow by design — the training
        hot path never comes here."""
        idx = np.asarray(idx)
        with self._lock:
            table = self.host_weight
            live = self._live_slots()
            if live.size:
                rows = self._gather_rows(live)[0]
                table = table.copy()
                table[self.id_at[live]] = rows.astype(table.dtype,
                                                      copy=False)
            return table[idx]


# ---------------------------------------------------------- conversion
def on_plan(trainer, plan):
    """Trainer.shard / Trainer.resize_mesh hook, called BEFORE
    `_place_on_plan`: convert every `tiered=True`-marked table to the
    two-tier layout (first shard), or re-tier already-converted state
    onto the new plan. Freshly-built device arrays land directly on the
    plan's shardings, so the subsequent redistribution pass no-ops over
    them."""
    from ..ndarray.ndarray import NDArray
    from ..optimizer import multi_tensor as _mt
    for index, p in enumerate(trainer._params):
        ts = getattr(p, "_tiered_state", None)
        if ts is not None:
            ts.retier(trainer, plan, index)
            continue
        marker = getattr(p, "_tiered", None)
        if not marker or p._data is None:
            continue
        opt = trainer._optimizer
        if not type(opt).elementwise:
            raise MXNetError(
                f"tiered embedding {p.name!r}: optimizer "
                f"{type(opt).__name__} is not elementwise — the tiered "
                f"cache requires the sparse fast path's scatter-add "
                f"update")
        prev = _REGISTRY.get(p.name)
        if prev is not None and prev.param is not p:
            raise MXNetError(
                f"tiered embedding {p.name!r}: a different live table "
                f"is already registered under this parameter name — "
                f"checkpoint routing is name-keyed, so a silent "
                f"overwrite would route saves/restores into the wrong "
                f"table. Give the blocks distinct prefixes, or call "
                f"shard.tiered.release({p.name!r}) after discarding "
                f"the old model")
        ts = TieredState(p, marker["hbm_rows"])
        if tuple(p._data.shape) != (ts.vocab, ts.dim):
            raise MXNetError(
                f"tiered embedding {p.name!r}: live shape "
                f"{tuple(p._data.shape)} != declared "
                f"({ts.vocab}, {ts.dim})")
        # snapshot the full logical table host-side BEFORE the device
        # rebind (np.asarray gathers a sharded array transparently)
        ts.host_weight = np.array(np.asarray(p._data._data))
        old_leaves = _state_leaves(trainer._updater, index)
        ts._attach(trainer, plan, index)
        # probe the optimizer's state-init rule on a SYNTHETIC,
        # guaranteed-nonzero row slice — probing real table rows
        # (zero-initialised embeddings and padding rows are common)
        # makes an fp32-master leaf (== the weight cast) look all-zero
        # and misclassify as "zero", silently zeroing restored masters
        probe_np = np.linspace(0.25, 1.0, 2 * ts.dim,
                               dtype=np.float64).reshape(2, ts.dim)
        probe = NDArray(jnp.asarray(
            probe_np.astype(ts.host_weight.dtype)))
        ts.kinds = _mt.classify_state_rows(opt, index, probe)
        if len(ts.kinds) != len(ts.row_like) or any(
                (k is not None) != rl
                for k, rl in zip(ts.kinds, ts.row_like)):
            raise MXNetError(
                f"tiered embedding {p.name!r}: optimizer state layout "
                f"probed on a row slice disagrees with the cache-shaped "
                f"state — cannot tier this optimizer's state")
        ts._init_host_state(old_leaves)
        p._tiered_state = ts
        _REGISTRY[p.name] = ts
        register_hbm_rows(p.name, ts.hbm_rows)


# ------------------------------------------------- checkpoint routing
def _is_nd(x):
    from ..ndarray.ndarray import NDArray
    return isinstance(x, NDArray)


def swap_for_save(params):
    """Checkpoint pre-pass (checkpoint.save_sharded): replace every leaf
    that IS a live tiered hot cache (identity match on the device array,
    or param-name + cache-shape match) with the FLUSHED full logical
    table. Returns `(params_with_full_tables, tiered_manifest_or_None)`
    — the manifest entry records vocab/dim/hbm_rows/dtype per name so a
    restore knows to route the full table back through the tier."""
    if not _REGISTRY:
        return params, None
    from ..checkpoint import _leaf_name
    leaves, treedef = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=_is_nd)
    by_id = {}
    for ts in _REGISTRY.values():
        if ts.param._data is not None:
            by_id[id(ts.param._data._data)] = ts
    meta, new = {}, []
    for path, leaf in leaves:
        data = getattr(leaf, "_data", leaf)
        ts = by_id.get(id(data))
        if ts is None:
            cand = _REGISTRY.get(_leaf_name(path))
            if cand is not None and cand.n_slots is not None and \
                    tuple(getattr(data, "shape", ())) == \
                    (cand.n_slots, cand.dim):
                ts = cand
        if ts is None:
            new.append(leaf)
            continue
        full = ts.export_table()
        meta[ts.name] = {"vocab": ts.vocab, "dim": ts.dim,
                         "hbm_rows": ts.hbm_rows,
                         "dtype": str(full.dtype)}
        new.append(full)
    if not meta:
        return params, None
    return jax.tree_util.tree_unflatten(treedef, new), meta


def prepare_restore(template, tiered_meta):
    """Checkpoint restore pre-pass (checkpoint.load_sharded): for every
    template leaf whose name the manifest's `tiered` entry covers,
    substitute a full-table (vocab, D) zeros template — the checkpoint
    holds the logical table, not a cache. Returns `(template, routes)`;
    routes is None when nothing matched."""
    from ..checkpoint import _leaf_name
    leaves, treedef = jax.tree_util.tree_flatten_with_path(
        template, is_leaf=_is_nd)
    routes, new = [], []
    for j, (path, leaf) in enumerate(leaves):
        m = (tiered_meta or {}).get(_leaf_name(path))
        if m is None:
            new.append(leaf)
            continue
        dt = np.dtype(m.get("dtype") or "float32")
        new.append(np.zeros((int(m["vocab"]), int(m["dim"])), dt))
        routes.append((j, _leaf_name(path)))
    if not routes:
        return template, None
    return jax.tree_util.tree_unflatten(treedef, new), routes


def finish_restore(restored, routes):
    """Checkpoint restore post-pass: route each restored full table back
    into its live TieredState (`import_table` — host tier replaced,
    cache cold) and hand back the cache leaf in its place; a name with
    no live tiered table keeps the full table (an untiered consumer
    restoring a tiered save)."""
    leaves, treedef = jax.tree_util.tree_flatten(restored,
                                                 is_leaf=_is_nd)
    for j, name in routes:
        full = np.asarray(getattr(leaves[j], "_data", leaves[j]))
        ts = _REGISTRY.get(name)
        if ts is None or ts.n_slots is None:
            leaves[j] = full
            continue
        ts.import_table(full)
        leaves[j] = ts.param._data._data
    return jax.tree_util.tree_unflatten(treedef, leaves)
