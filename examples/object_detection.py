"""The detection trio — SSD, Faster-RCNN, YOLOv3 — on one synthetic scene
(reference workflows: gluoncv demo_ssd / demo_faster_rcnn / demo_yolo).

Each model runs its full TPU-native predict path: one jitted program per
model covering backbone -> heads -> static-shape decode -> NMS (per-class,
fixed max_out). YOLOv3 additionally does one training step through its
host-side target assigner + dynamic-ignore loss, the reference training
contract.

Usage: python examples/object_detection.py [--smoke]
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd


def scene(size, batch=1):
    """A light background with two dark rectangles to detect."""
    img = onp.full((batch, size, size, 3), 0.8, onp.float32)
    s = size // 4
    img[:, s:2 * s, s:2 * s] = 0.2
    img[:, 2 * s:3 * s, 2 * s:3 * s + s // 2] = 0.1
    return nd.array(img)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    size = 64 if args.smoke else 128
    yolo_size = 64 if args.smoke else 416

    # ------------------------------------------------------------- SSD
    from mxnet_tpu.models.ssd import SSD, ssd_decode
    ssd = SSD(num_classes=3, backbone_layers=18, input_size=size)
    ssd.initialize(mx.init.Xavier())
    ssd.hybridize()
    t0 = time.time()
    cls_p, loc_p = ssd(scene(size))
    det = ssd_decode(cls_p, loc_p, ssd.anchors, max_det=10)
    print(f"SSD: {det.shape} detections tensor in {time.time() - t0:.1f}s")

    # ----------------------------------------------------- Faster-RCNN
    from mxnet_tpu.models.faster_rcnn import FasterRCNN
    frcnn = FasterRCNN(num_classes=3, backbone_layers=18, input_size=size,
                       post_nms=20)
    frcnn.initialize(mx.init.Xavier())
    frcnn.hybridize()
    t0 = time.time()
    obj, deltas, feat = frcnn(scene(size))
    props, scores = frcnn.rpn_proposals(obj, deltas, pre_nms=100)
    cls, box = frcnn.roi_head(feat, props)
    print(f"Faster-RCNN: {props.shape[1]} proposals, roi head {cls.shape} "
          f"in {time.time() - t0:.1f}s")

    # ---------------------------------------------------------- YOLOv3
    from mxnet_tpu.models.yolo import (yolo3_darknet53,
                                       YOLOV3TargetGenerator, YOLOV3Loss)
    yolo = yolo3_darknet53(num_classes=3, input_size=yolo_size)
    yolo.initialize(mx.init.Normal(0.02))
    x = scene(yolo_size)
    t0 = time.time()
    ids, det_scores, boxes = yolo.predict(x, conf_thresh=0.01)
    print(f"YOLOv3: predict {boxes.shape} in {time.time() - t0:.1f}s")

    # one reference-style train step: host-side targets, jitted loss
    s = yolo_size // 4
    gt = nd.array([[[s, s, 2 * s, 2 * s],
                    [2 * s, 2 * s, 3 * s + s // 2, 3 * s]]],
                  dtype="float32")
    gid = nd.array([[0.0, 1.0]])
    targets = YOLOV3TargetGenerator(3, yolo_size)(gt, gid)
    lossfn = YOLOV3Loss(input_size=yolo_size)
    trainer = mx.gluon.Trainer(yolo.collect_params(), "adam",
                               {"learning_rate": 1e-3})
    with mx.autograd.record():
        outs = yolo(x)
        loss = lossfn(outs, *targets, gt_boxes=gt)
    loss.backward()
    trainer.step(1)
    print(f"YOLOv3 train step: loss={float(loss.asnumpy()):.2f}")
    print("detection trio done")


if __name__ == "__main__":
    main()
