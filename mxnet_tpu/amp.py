"""Automatic mixed precision (reference: python/mxnet/contrib/amp).

TPU-native: bf16 is the native MXU dtype (no loss scaling needed, unlike
fp16 on GPUs). `init()` turns on op-level autocast — the matmul/conv entry
points in `ops.nn_ops` consult `amp.autocast_dtype()` and run fp32 inputs
through the MXU in the target dtype (the reference patches its op namespace
with cast wrappers at amp.init(); here the cast lives in the op, applied at
trace time, so one jit recompile picks it up). Normalisation layers listed
in `_KEEP_FP32` are kept/re-cast to fp32 by `convert_block`.

For fp16 parity the reference's dynamic loss scaling is wired into
`gluon.Trainer.step`: when `init(target_dtype="float16")` installed a
`DynamicLossScaler`, step() unscales gradients, skips the update on
overflow, and halves the scale (§5 failure-detection: `skip_nonfinite`).
"""
from __future__ import annotations

import jax
import numpy as np

import jax.numpy as jnp

__all__ = ["init", "reset", "convert_block", "scale_loss", "unscale",
           "unscale_arrays", "DynamicLossScaler", "bfloat16",
           "autocast_dtype", "is_active", "grads_nonfinite", "scaler"]

bfloat16 = jnp.bfloat16

_CAST_LAYERS = ("Dense", "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
                "Embedding", "ShardedEmbedding")
_KEEP_FP32 = ("BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm")

_state = {"scaler": None, "initialized": False, "target_dtype": None}


def init(target_dtype="bfloat16"):
    """Enable AMP (reference: amp.init()). Turns on op-level autocast for
    matmul/conv ops and, for float16, installs a DynamicLossScaler that
    gluon.Trainer.step consults."""
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype
    if target_dtype == "float16":
        _state["scaler"] = DynamicLossScaler()


def reset():
    """Disable AMP again (test helper / parity with amp re-init)."""
    _state["initialized"] = False
    _state["target_dtype"] = None
    _state["scaler"] = None


def is_active():
    return _state["initialized"]


def scaler():
    """The installed DynamicLossScaler, or None. Non-None only for fp16
    AMP (bf16 needs no loss scaling) — the one accessor the Trainer and
    the captured step (cachedop.py) consult, so the overflow-skip
    protocol has a single source of truth."""
    return _state.get("scaler") if _state["initialized"] else None


def autocast_dtype():
    """The dtype fp32 matmul/conv inputs are cast to under AMP, or None.
    Consulted by ops.nn_ops.fully_connected / convolution at trace time."""
    if not _state["initialized"]:
        return None
    t = _state.get("target_dtype") or "bfloat16"
    return jnp.float16 if str(t) in ("float16", "fp16") else jnp.bfloat16


def convert_block(block, target_dtype="bfloat16"):
    """Cast matmul/conv layers to the target dtype and force the
    normalisation layers in `_KEEP_FP32` back to fp32 — so it is safe to
    call after a blanket `net.cast("bfloat16")`
    (reference: amp.convert_hybrid_block)."""
    def walk(b):
        name = type(b).__name__
        if name in _KEEP_FP32:
            b.cast("float32")
            return
        if name in _CAST_LAYERS:
            b.cast(target_dtype)
        for c in b._children.values():
            walk(c)
    walk(block)
    return block


class DynamicLossScaler:
    """Reference: AMP dynamic loss scaling (fp16 only; bf16 doesn't need it)."""

    def __init__(self, init_scale=2. ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        return grads_nonfinite(params)

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self.scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.scale_window:
                self.loss_scale *= self.scale_factor
                self._unskipped = 0


@jax.jit
def _any_nonfinite(grads):
    # jit's own tracing cache keys on the input avals, so this compiles
    # once per gradient-signature — no hand-rolled cache needed
    bad = [jnp.sum(~jnp.isfinite(g.astype(jnp.float32)), dtype=jnp.int32)
           for g in grads]
    return sum(bad) > 0


def grads_nonfinite(params):
    """True if any parameter gradient contains inf/nan. ONE jitted program
    over all gradients producing a single scalar — one dispatch + one host
    sync per step, not one tiny `isfinite().all()` launch per parameter."""
    grads = [p._grad._data for p in params
             if getattr(p, "_grad", None) is not None]
    if not grads:
        return False
    return bool(_any_nonfinite(grads))


def scale_loss(loss, trainer_or_scaler=None):
    scaler = _state.get("scaler")
    if scaler is None:
        return loss
    return loss * scaler.loss_scale


_unscale_fn = None


def unscale_arrays(grads, inv_scale):
    """Multiply every gradient array by `inv_scale` as ONE jitted
    multi-tensor launch (cached by jax.jit on the gradient pytree
    signature — scale moves hit the cache, the scalar is an argument).
    Counts as a single `amp_unscale` dispatch. The scalar is cast to
    each grad's dtype before the multiply, matching the per-array
    `g * python_float` weak-promotion semantics this replaces."""
    global _unscale_fn
    from . import profiler
    if not grads:
        return []
    if _unscale_fn is None:
        _unscale_fn = jax.jit(
            lambda gs, inv: [g * inv.astype(g.dtype) for g in gs])
    profiler.record_dispatch("amp_unscale")
    return _unscale_fn(list(grads), jnp.float32(inv_scale))


def unscale(grads_or_trainer):
    scaler = _state.get("scaler")
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    params = grads_or_trainer._params if hasattr(grads_or_trainer, "_params") \
        else grads_or_trainer
    live = [p for p in params if getattr(p, "_grad", None) is not None]
    outs = unscale_arrays([p._grad._data for p in live], inv)
    for p, g in zip(live, outs):
        p._grad._rebind(g)
