"""NDArray serialisation (reference: mx.nd.save / mx.nd.load, C API
NDArraySave/NDArrayLoad). Format: numpy .npz — portable, no custom binary."""
from __future__ import annotations

import numpy as np

from .ndarray import NDArray, array

__all__ = ["save", "load"]


def save(fname, data):
    """Save a list or str-keyed dict of NDArrays."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        arrays = {f"arr:{i}": d.asnumpy() for i, d in enumerate(data)}
    elif isinstance(data, dict):
        arrays = {f"key:{k}": v.asnumpy() for k, v in data.items()}
    else:
        raise TypeError(f"unsupported data type {type(data)}")
    np.savez(fname if fname.endswith(".npz") else fname, **arrays)


def load(fname):
    """Load NDArrays saved by `save` — returns list or dict matching input."""
    with np.load(fname, allow_pickle=False) as f:
        keys = list(f.keys())
        if all(k.startswith("arr:") for k in keys):
            items = sorted(keys, key=lambda k: int(k.split(":", 1)[1]))
            return [array(f[k]) for k in items]
        return {k.split(":", 1)[1]: array(f[k]) for k in keys}
