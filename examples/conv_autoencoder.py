"""Convolutional autoencoder: Conv2D encoder + Conv2DTranspose decoder,
trained on synthetic images, then exported and re-served via
SymbolBlock.imports (reference flow: gluon conv nets + HybridBlock.export).

Usage: python examples/conv_autoencoder.py [--steps N] [--smoke]

TPU notes: hybridize compiles the whole forward into one XLA program;
export re-traces it symbolically so the deployed artifact is the same
graph the Executor jits at serve time.
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import SymbolBlock, Trainer, nn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=0.005)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.steps = 120

    mx.random.seed(0)
    rs = np.random.RandomState(0)
    x = nd.array(rs.rand(64, 1, 16, 16).astype(np.float32))

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, strides=2, padding=1, activation="relu"),
            nn.Conv2DTranspose(1, 4, strides=2, padding=1))
    net.initialize()
    net.hybridize()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})

    first = None
    for step in range(args.steps):
        with autograd.record():
            loss = ((net(x) - x) ** 2).mean()
        loss.backward()
        trainer.step(1)
        if first is None:
            first = float(loss.asscalar())
    final = float(loss.asscalar())
    print(f"mse: {first:.4f} -> {final:.4f}")
    assert final < 0.3 * first, "autoencoder failed to train"

    expect = net(x[:4]).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ae")
        net.export(path)
        served = SymbolBlock.imports(path + "-symbol.json", ["data"],
                                     path + "-0000.params.npz")
        got = served(x[:4]).asnumpy()
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
    print("export/imports round trip matches; conv_autoencoder done")


if __name__ == "__main__":
    main()
