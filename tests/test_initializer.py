"""Initializer tests (SURVEY.md §2 #26)."""
import numpy as np
import jax
import pytest

import mxnet_tpu as mx
from mxnet_tpu import init
from mxnet_tpu import initializer


KEY = jax.random.PRNGKey(0)


def _draw(ini, name="weight", shape=(64, 64)):
    return np.asarray(ini(name, shape, np.float32, KEY))


def test_zero_one_constant():
    assert (_draw(init.Zero()) == 0).all()
    assert (_draw(init.One()) == 1).all()
    assert (_draw(init.Constant(2.5)) == 2.5).all()


def test_uniform_normal_stats():
    u = _draw(init.Uniform(0.5), shape=(256, 256))
    assert u.min() >= -0.5 and u.max() <= 0.5
    n = _draw(init.Normal(0.1), shape=(256, 256))
    assert abs(n.std() - 0.1) < 0.01 and abs(n.mean()) < 0.01


def test_orthogonal():
    w = _draw(init.Orthogonal(scale=1.0), shape=(32, 32))
    np.testing.assert_allclose(w @ w.T, np.eye(32), atol=1e-4)


def test_xavier_scale():
    w = _draw(init.Xavier(factor_type="avg", magnitude=3), shape=(100, 100))
    bound = np.sqrt(3.0 / 100)
    assert abs(w.std() - bound / np.sqrt(3)) < 0.02
    assert w.min() >= -bound - 1e-6 and w.max() <= bound + 1e-6


def test_msra_prelu():
    w = _draw(init.MSRAPrelu(), shape=(128, 128))
    assert w.std() > 0


def test_bilinear_upsampling_kernel():
    w = _draw(init.Bilinear(), shape=(1, 1, 4, 4))
    # symmetric, peak at center
    k = w[0, 0]
    np.testing.assert_allclose(k, k[::-1, ::-1], atol=1e-6)


def test_lstmbias_forget_gate():
    b = _draw(init.LSTMBias(forget_bias=1.0), name="lstm_bias",
              shape=(4 * 8,))
    # the forget-gate quarter is 1, everything else 0
    quarters = b.reshape(4, 8)
    sums = quarters.sum(1)
    assert (sums > 0).sum() == 1


def test_name_dispatch_bias_gamma():
    ini = init.Normal(1.0)
    assert (_draw(ini, name="fc_bias", shape=(8,)) == 0).all()
    assert (_draw(ini, name="bn_gamma", shape=(8,)) == 1).all()
    assert (_draw(ini, name="bn_running_var", shape=(8,)) == 1).all()


def test_mixed():
    ini = init.Mixed([".*special.*", ".*"],
                     [init.One(), init.Zero()])
    assert (_draw(ini, name="special_weight", shape=(4,)) == 1).all()
    assert (_draw(ini, name="plain_weight", shape=(4,)) == 0).all()


def test_create_by_name():
    ini = initializer.create("xavier", magnitude=2)
    assert isinstance(ini, init.Xavier)


def test_block_initialize_uses_initializer():
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4, in_units=4)
    net.initialize(init.One())
    assert (net.weight.data().asnumpy() == 1).all()
    assert (net.bias.data().asnumpy() == 0).all()


def test_load_initializer():
    """initializer.Load: saved values win, default_init covers the rest."""
    from mxnet_tpu.gluon import nn
    saved = {"arg:weight": mx.nd.ones((3, 4)) * 7}
    init = mx.init.Load({"arg:weight": saved["arg:weight"]},
                        default_init=mx.init.Zero())
    w = init.init_array("weight", (3, 4), "float32", None)
    np.testing.assert_allclose(np.asarray(w), np.full((3, 4), 7.0))
    b = init.init_array("bias", (3,), "float32", None)
    np.testing.assert_allclose(np.asarray(b), np.zeros(3))
    with pytest.raises(ValueError, match="shape mismatch"):
        init.init_array("weight", (2, 2), "float32", None)
