"""Model-parallel sparse embedding lookup + sparse-gradient fast path
(ISSUE 15; docs/PERFORMANCE.md "Sharded embeddings").

SURVEY §8 maps sparse embeddings to a dense ``take`` over a REPLICATED
table — fine for BERT vocabularies, fatal for recommendation-scale
tables (10⁸ rows x wide meshes), where memory capacity, not FLOPs, is
the binding constraint. This module row-shards a table over one named
mesh axis (the PR 8 partition-rule machinery assigns the layout) and
moves only the LOOKED-UP rows over the interconnect — the
portable-collective philosophy of arXiv:2112.01075:

  forward  (``gather_rows``, inside the captured step's program):
    1. dedup — ``jnp.unique(size=n)`` over the step's flat index batch,
       so each distinct row crosses the wire once per step regardless of
       how many batch positions reference it;
    2. bucket the deduped ids by owner shard (``plan_buckets``: sort by
       ``id // rows_per_shard``, slot into a static ``(shards, U)``
       layout, out-of-range sentinel pads);
    3. ONE ``all_to_all`` exchanges the index buckets, each owner
       gathers its local rows, ONE more ``all_to_all`` returns the
       vectors — exactly 2 all-to-alls per table per step, the count
       tools/check_fusion.py pins.

  backward (the sparse-gradient fast path, mxnet_tpu/cachedop.py): the
    table is HOISTED OUT of the step's ``jax.vjp`` — the gathered
    ``(U, D)`` row block is the differentiable input instead, so the
    cotangent the backward materialises is ``(unique_rows, D)`` plus an
    index vector, NEVER an O(vocab) dense gradient. XLA's scatter-add
    over the dedup inverse IS the segment-sum of per-position
    cotangents into the touched-row block.

  update (``sparse_row_update``): the multi-tensor optimizer's
    scatter-add arm (optimizer/multi_tensor.py ``sparse_update_rows``)
    runs on the OWNING shard only — touched weight rows and their
    row-shaped optimizer-state rows (momentum, Adam m/v, fp32 masters)
    are gathered, staged through the exact ``apply_param_update``
    numerics, and scattered back in place into the donated, mesh-
    resident buffers. Untouched rows never move and never update
    (MXNet's documented lazy/sparse-update semantics: weight decay and
    momentum-style state decay apply to TOUCHED rows only; plain SGD
    with wd=0 matches the dense path exactly).

Capacity note: bucket capacity is U (the deduped count) per destination
— correctness never depends on the index distribution. Per-step wire
bytes are O(shards * U * D) for the vector return; the memory headline
is ``embed_param_bytes_frac`` ~= 1/axis_size per device.
"""
from __future__ import annotations

import re
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..jax_compat import shard_map
from .exchange import (exchange, local_offsets,  # noqa: F401  (re-export)
                       plan_buckets)

__all__ = ["plan_buckets", "gather_rows", "sparse_row_update",
           "scatter_rows", "SparseLookupContext", "lookup",
           "sparse_eligibility", "embed_param_bytes_frac"]


# how many all-to-alls one sharded lookup lowers to — the forward index
# exchange plus the vector return. tools/check_fusion.py cross-checks
# its pinned count for the (2,2) embedding step against
# `A2A_PER_TABLE * n_tables` so the budget and the exchange math cannot
# drift apart silently. The bucket layout + a2a primitive live in
# shard/exchange.py (shared with the MoE token-routing head).
A2A_PER_TABLE = 2


def gather_rows(table, uniq, mesh, axis):
    """Fetch the deduped rows ``table[uniq]`` from a table row-sharded
    over ``mesh`` axis ``axis``: bucket ids by owner shard, all-to-all
    the index buckets, gather locally on the owner, all-to-all the
    vectors back (2 collectives total). ``uniq`` must be replicated
    (the step deduplicates the GLOBAL index batch); out-of-range ids
    (the unique-pass sentinel) come back as clamped garbage rows that
    no inverse-index slot ever references. Returns ``(U, D)``
    replicated. Axis size 1 degenerates to a local gather."""
    n_shards = int(mesh.shape[axis])
    if n_shards <= 1:
        return jnp.take(table, jnp.clip(uniq, 0, table.shape[0] - 1),
                        axis=0)
    vocab = table.shape[0]
    rows_per = vocab // n_shards

    def local(tab, ids):
        t = jax.lax.axis_index(axis)
        buckets, s_owner, rank, order = plan_buckets(
            ids, n_shards, rows_per, vocab)
        recv_ids = exchange(buckets, axis)
        loc = jnp.clip(recv_ids - t * rows_per, 0, tab.shape[0] - 1)
        send_rows = tab[loc]                       # (n_shards, U, D)
        rows_back = exchange(send_rows, axis)
        got_sorted = rows_back[s_owner, rank]      # (U, D)
        inv_order = jnp.argsort(order, stable=True)
        return got_sorted[inv_order]

    table_spec = P(*([axis] + [None] * (table.ndim - 1)))
    return shard_map(local, mesh=mesh,
                     in_specs=(table_spec, P()),
                     out_specs=P(), check_vma=False)(table, uniq)


def sparse_row_update(table, state_vals, uniq, g_rows, mesh, axis,
                      stage_fn):
    """The scatter-add arm's sharded half: on the OWNING shard only,
    gather the touched weight rows + row-shaped optimizer-state rows,
    run ``stage_fn(w_rows, g_rows, sv_rows) -> (new_rows, new_sv)``
    (the multi-tensor ``apply_param_update`` staging over the row
    block), and scatter the results back in place. Scalar state leaves
    (e.g. Adam's step counter) pass through whole and update
    replicated. Non-owned and sentinel slots scatter with
    ``mode='drop'`` — a shard never writes rows it does not own, and
    untouched rows never change."""
    n_shards = int(mesh.shape[axis])
    row_like = tuple(s.shape == table.shape for s in state_vals)
    if n_shards <= 1:
        cl = jnp.clip(uniq, 0, table.shape[0] - 1)
        valid = uniq < table.shape[0]
        w_rows = table[cl]
        sv_rows = tuple(s[cl] if rl else s
                        for s, rl in zip(state_vals, row_like))
        new_rows, new_sv = stage_fn(w_rows, g_rows, sv_rows)
        safe = jnp.where(valid, cl, table.shape[0])
        new_tab = table.at[safe].set(new_rows, mode="drop")
        out_sv = tuple(
            s.at[safe].set(ns, mode="drop") if rl else ns
            for s, ns, rl in zip(state_vals, new_sv, row_like))
        return new_tab, out_sv

    rows_per = table.shape[0] // n_shards

    def local(tab, sv, ids, g):
        t = jax.lax.axis_index(axis)
        safe, _own = local_offsets(ids, t, rows_per)
        cl = jnp.clip(safe, 0, rows_per - 1)
        w_rows = tab[cl]
        sv_rows = tuple(s[cl] if rl else s
                        for s, rl in zip(sv, row_like))
        new_rows, new_sv = stage_fn(w_rows, g, sv_rows)
        # non-owned and sentinel ids carry safe == rows_per -> drop
        new_tab = tab.at[safe].set(new_rows, mode="drop")
        out_sv = tuple(
            s.at[safe].set(ns, mode="drop") if rl else ns
            for s, ns, rl in zip(sv, new_sv, row_like))
        return new_tab, out_sv

    def spec_of(a, rl):
        if not rl:
            return P()
        return P(*([axis] + [None] * (a.ndim - 1)))

    table_spec = P(*([axis] + [None] * (table.ndim - 1)))
    sv_specs = tuple(spec_of(s, rl)
                     for s, rl in zip(state_vals, row_like))
    return shard_map(
        local, mesh=mesh,
        in_specs=(table_spec, sv_specs, P(), P()),
        out_specs=(table_spec, sv_specs),
        check_vma=False)(table, tuple(state_vals), uniq, g_rows)


def scatter_rows(table, slots, rows, mesh, axis):
    """Write ``rows[i]`` into ``table[slots[i]]`` in place on the owning
    shard — ZERO collectives (every shard receives the replicated
    ``(M,)``/``(M, D)`` blocks and keeps only the slots it owns; the
    sentinel ``table.shape[0]`` and non-owned slots drop). The tiered
    hot cache's in-program scatter-in (shard/tiered.py): the
    RowPrefetcher stages incoming cold rows replicated, and the captured
    step lands them into freed cache slots before the lookup gathers.
    Axis size 1 degenerates to a local drop-scatter."""
    n_shards = int(mesh.shape[axis])
    if n_shards <= 1:
        safe = jnp.where(slots < table.shape[0], slots, table.shape[0])
        return table.at[safe].set(rows.astype(table.dtype), mode="drop")
    rows_per = table.shape[0] // n_shards

    def local(tab, s, r):
        t = jax.lax.axis_index(axis)
        safe, _own = local_offsets(s, t, rows_per)
        return tab.at[safe].set(r.astype(tab.dtype), mode="drop")

    table_spec = P(*([axis] + [None] * (table.ndim - 1)))
    return shard_map(local, mesh=mesh,
                     in_specs=(table_spec, P(), P()),
                     out_specs=table_spec, check_vma=False)(
                         table, slots, rows)


# ------------------------------------------------ capture integration
class SparseLookupContext:
    """Trace-time side channel between the captured step's program build
    (mxnet_tpu/cachedop.py) and `ShardedEmbedding.hybrid_forward`.

    ``record`` mode: the program's discovery pass runs the model trace
    once with this context installed; every sharded-lookup site
    registers its (param, index tracer) pair and returns a correctly-
    shaped ZEROS block WITHOUT touching the table value (the pass's
    outputs are unused, so XLA dead-code-eliminates everything but the
    recorded index extraction — and because lookups never reference the
    table, any remaining reference in the discovery jaxpr is a
    NON-lookup use, which cachedop demotes to the dense path rather
    than silently dropping its gradient). ``consume`` mode:
    inside the vjp'd forward, each site pops its pre-gathered row
    segment instead of touching the table — the table never enters the
    differentiated function, which is what makes the backward
    O(unique_rows) instead of O(vocab). Sites replay in trace order
    (same python, same order)."""

    _tl = threading.local()

    def __init__(self, mode, param_ids):
        self.mode = mode
        self.param_ids = frozenset(param_ids)
        self.sites = {}        # id(param) -> [idx tracer, ...]
        self.consume_plan = {}  # id(param) -> (rows, inv, segments, pos)

    @staticmethod
    def active():
        return getattr(SparseLookupContext._tl, "value", None)

    def __enter__(self):
        self._old = SparseLookupContext.active()
        SparseLookupContext._tl.value = self
        return self

    def __exit__(self, *exc):
        SparseLookupContext._tl.value = self._old

    def handles(self, param):
        return id(param) in self.param_ids

    # record mode -----------------------------------------------------
    def record(self, param, idx):
        self.sites.setdefault(id(param), []).append(idx)
        return None

    # consume mode ----------------------------------------------------
    def set_rows(self, param, rows, inv, segments):
        self.consume_plan[id(param)] = [rows, inv, segments, 0]

    def consume(self, param, idx):
        plan = self.consume_plan[id(param)]
        rows, inv, segments, pos = plan
        if pos >= len(segments):
            raise MXNetError(
                "sharded embedding: more lookup sites than the discovery "
                "pass recorded (non-deterministic model trace?)")
        off, shape = segments[pos]
        plan[3] = pos + 1
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        seg = jax.lax.dynamic_slice_in_dim(inv, off, n)
        return jnp.take(rows, seg, axis=0).reshape(
            tuple(shape) + rows.shape[1:])


def check_index_dtype(dtype):
    """Integer index dtypes pass through untouched; a float index batch
    raises (float32 loses integer exactness above 2**24 — at recommender
    scale that is a silent wrong-row lookup)."""
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        raise MXNetError(
            f"ShardedEmbedding: index batch has dtype {jnp.dtype(dtype)}; "
            f"integer indices are required (float32 cannot represent "
            f"row ids above 2**24 exactly — cast the input pipeline to "
            f"int32/int64 instead)")


def lookup(param, idx, weight):
    """One sharded-embedding lookup over raw jax values, honouring the
    active `SparseLookupContext` (capture path) and degrading to a
    dense integer take everywhere else (eager, imperative fallback,
    eval). `weight` is the table VALUE in the caller's scope (the
    traced override under capture, the live data otherwise)."""
    check_index_dtype(idx.dtype)
    ctx = SparseLookupContext.active()
    if ctx is not None and ctx.handles(param):
        if ctx.mode == "record":
            ctx.record(param, idx)
            # shape/dtype only — the table VALUE stays untouched, so
            # the discovery jaxpr's use-analysis sees lookup-only
            # tables as unreferenced (cachedop's demotion guard)
            return jnp.zeros(tuple(idx.shape) + tuple(weight.shape[1:]),
                             weight.dtype)
        return ctx.consume(param, idx)
    return jnp.take(weight, idx, axis=0)


# ------------------------------------------------------- eligibility
def sparse_eligibility(plan, diff, optimizer):
    """{position-in-diff: {"axis", "vocab", "dim"}} for every trainable
    parameter the sparse fast path can take: marked by
    `ShardedEmbedding` (``p._sharded_embedding``), 2-D, row-sharded by
    its rule over exactly ONE mesh axis that divides the vocab, under
    an elementwise optimizer (the row-block staging IS the dense rule
    restricted to touched rows only for elementwise updates). Anything
    else trains through the dense GSPMD path unchanged."""
    out = {}
    if plan is None or not type(optimizer).elementwise:
        return out
    for k, (i, p) in enumerate(diff):
        if not getattr(p, "_sharded_embedding", None):
            continue
        w = p.data()._data
        if w.ndim != 2:
            continue
        spec = tuple(plan.spec_for(p.name, w.shape))
        if not spec or spec[0] is None or not isinstance(spec[0], str):
            continue
        if any(e is not None for e in spec[1:]):
            continue
        n_ax = int(plan.mesh.shape[spec[0]])
        if n_ax < 1 or w.shape[0] % max(n_ax, 1):
            continue
        out[k] = {"axis": spec[0], "vocab": int(w.shape[0]),
                  "dim": int(w.shape[1])}
    return out


def embed_param_bytes_frac(plan, named_arrays):
    """Per-device / total byte fraction of the EMBEDDING-table subset of
    ``{name: array}`` under ``plan`` — the headline memory metric of the
    recommender workload (~= 1/axis_size when the embed rule row-shards
    every table). Tables are selected by the SAME name pattern the
    DEFAULT_RULES embedding rule shards (`rules.EMBED_WEIGHT_PATTERN` —
    "embedding0", DLRM-style "emb_cat3", ...). None when the set holds
    no embedding tables."""
    from .rules import EMBED_WEIGHT_PATTERN
    pat = re.compile(EMBED_WEIGHT_PATTERN)
    embed = {n: a for n, a in named_arrays.items() if pat.search(n)}
    if not embed:
        return None
    per_dev, total = plan.param_bytes_per_device(embed)
    return per_dev / total if total else None
