"""gluon.contrib layers: SyncBatchNorm (cross-replica stats on the virtual
mesh), pixel shuffle, ConvLSTM/LSTMP/VariationalDropout cells."""
import numpy as np
import jax
import jax.numpy as jnp
from mxnet_tpu.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import contrib
from mxnet_tpu.gluon.contrib.nn import sync_batch_norm
from mxnet_tpu.parallel.mesh import make_mesh


def test_sync_batch_norm_cross_replica_stats():
    """Inside a dp shard_map, SyncBatchNorm stats are GLOBAL-batch: the
    sharded output must match plain BN run on the full batch — and differ
    from per-shard BN when shard means differ."""
    rs = np.random.RandomState(0)
    # per-shard distributions differ wildly so local != global stats
    x = np.concatenate([rs.randn(2, 4, 3, 3) * (i + 1) + 2 * i
                        for i in range(8)]).astype(np.float32)
    g = np.abs(rs.randn(4).astype(np.float32)) + 0.5
    b = rs.randn(4).astype(np.float32)
    mm = np.zeros(4, np.float32)
    mv = np.ones(4, np.float32)

    y_full, nm_full, nv_full = sync_batch_norm(
        jnp.asarray(x), jnp.asarray(g), jnp.asarray(b), jnp.asarray(mm),
        jnp.asarray(mv), training=True, axis_name=None)

    mesh = make_mesh({"dp": 8})
    y_sh, nm_sh, nv_sh = shard_map(
        lambda xs, gs, bs, mms, mvs: sync_batch_norm(
            xs, gs, bs, mms, mvs, training=True, axis_name="dp"),
        mesh=mesh,
        in_specs=(P("dp"), P(None), P(None), P(None), P(None)),
        out_specs=(P("dp"), P(None), P(None)))(
        jnp.asarray(x), jnp.asarray(g), jnp.asarray(b),
        jnp.asarray(mm), jnp.asarray(mv))
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_full),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(nv_sh), np.asarray(nv_full),
                               rtol=2e-4, atol=2e-4)
    # and per-shard (unsynced) stats give a DIFFERENT result
    y_local = shard_map(
        lambda xs, gs, bs, mms, mvs: sync_batch_norm(
            xs, gs, bs, mms, mvs, training=True, axis_name=None)[0],
        mesh=mesh,
        in_specs=(P("dp"), P(None), P(None), P(None), P(None)),
        out_specs=P("dp"))(
        jnp.asarray(x), jnp.asarray(g), jnp.asarray(b),
        jnp.asarray(mm), jnp.asarray(mv))
    assert np.abs(np.asarray(y_local) - np.asarray(y_full)).max() > 0.1


def test_sync_batch_norm_layer_eager_matches_batchnorm():
    """Outside any mesh the layer degrades to plain BatchNorm."""
    from mxnet_tpu.gluon.nn import BatchNorm
    rs = np.random.RandomState(1)
    x = nd.array(rs.randn(8, 4, 5, 5).astype(np.float32))
    sbn = contrib.nn.SyncBatchNorm(in_channels=4)
    bn = BatchNorm(in_channels=4)
    sbn.initialize()
    bn.initialize()
    with autograd.record():
        y1 = sbn(x)
    with autograd.record():
        y2 = bn(x)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=1e-4,
                               atol=1e-4)
    # running stats updated identically
    np.testing.assert_allclose(sbn.running_var.data().asnumpy(),
                               bn.running_var.data().asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_pixel_shuffle_2d():
    ps = contrib.nn.PixelShuffle2D(2)
    x = nd.array(np.arange(1 * 8 * 2 * 2, dtype=np.float32)
                 .reshape(1, 8, 2, 2))
    y = ps(x)
    assert y.shape == (1, 2, 4, 4)
    # matches the torch.pixel_shuffle layout contract
    import torch
    expect = torch.pixel_shuffle(torch.from_numpy(x.asnumpy()), 2).numpy()
    np.testing.assert_allclose(y.asnumpy(), expect)


def test_conv2d_lstm_cell():
    cell = contrib.rnn.Conv2DLSTMCell(input_shape=(3, 8, 8),
                                      hidden_channels=6, i2h_kernel=3,
                                      h2h_kernel=3)
    cell.initialize()
    x = nd.random.uniform(shape=(2, 3, 8, 8))
    states = cell.begin_state(batch_size=2)
    assert states[0].shape == (2, 6, 8, 8)
    out, new_states = cell(x, states)
    assert out.shape == (2, 6, 8, 8)
    assert len(new_states) == 2
    # unroll over a short sequence
    seq = nd.random.uniform(shape=(2, 4, 3, 8, 8))  # NTC...
    outs, final = cell.unroll(4, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 4, 6, 8, 8)


def test_conv_lstm_gradients_flow():
    cell = contrib.rnn.Conv1DLSTMCell(input_shape=(2, 10),
                                      hidden_channels=4)
    cell.initialize()
    x = nd.random.uniform(shape=(3, 2, 10))
    states = cell.begin_state(batch_size=3)
    with autograd.record():
        out, _ = cell(x, states)
        loss = (out ** 2).sum()
    loss.backward()
    g = cell.i2h_weight.grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_lstmp_cell():
    cell = contrib.rnn.LSTMPCell(hidden_size=16, projection_size=5)
    cell.initialize()
    x = nd.random.uniform(shape=(4, 7))
    states = cell.begin_state(batch_size=4)
    assert states[0].shape == (4, 5) and states[1].shape == (4, 16)
    out, new_states = cell(x, states)
    assert out.shape == (4, 5)
    with autograd.record():
        out, _ = cell(x, cell.begin_state(batch_size=4))
        out.sum().backward()
    assert np.abs(cell.h2r_weight.grad().asnumpy()).sum() > 0


def test_variational_dropout_cell_mask_reuse():
    from mxnet_tpu.gluon.rnn import LSTMCell
    base = contrib.rnn.VariationalDropoutCell(LSTMCell(8), drop_inputs=0.5)
    base.initialize()
    x = nd.ones((2, 8))
    states = base.base_cell.begin_state(batch_size=2)
    with autograd.record():
        y1, _ = base(x, states)
        y2, _ = base(x, states)  # same mask -> identical outputs
    np.testing.assert_array_equal(y1.asnumpy(), y2.asnumpy())
    k1 = np.asarray(base._base_key)
    base.reset()
    assert not np.array_equal(k1, np.asarray(base._base_key))
    # inference: no dropout
    y, _ = base(x, states)
    assert y.shape == (2, 8)


def test_variational_dropout_cell_trace_then_eager():
    """Masks must not leak tracers: a traced call followed by an eager call
    without reset() must work (round-2 review finding)."""
    import jax
    from mxnet_tpu.gluon.rnn import LSTMCell
    cell = contrib.rnn.VariationalDropoutCell(LSTMCell(4), drop_inputs=0.5)
    cell.initialize()
    x = nd.ones((2, 4))
    states = cell.base_cell.begin_state(batch_size=2)
    cell(x, states)  # materialise deferred params eagerly before tracing
    with autograd.record():
        @jax.jit
        def traced(xv):
            out, _ = cell(nd.NDArray(xv), states)
            return out._data
        traced(x._data)
        out, _ = cell(x, states)  # eager reuse: same key, fresh mask
    assert out.shape == (2, 4)


def test_sparse_embedding_divergence():
    import pytest
    with pytest.raises(mx.base.MXNetError):
        contrib.nn.SparseEmbedding(10, 4)
