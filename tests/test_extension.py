"""Custom-op extension points (SURVEY gap: autograd.Function +
mx.operator.CustomOp; reference: python/mxnet/autograd.py class Function,
python/mxnet/operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


class _WrongGrad(autograd.Function):
    """Custom backward that deliberately disagrees with the natural
    gradient — proves the tape calls OUR backward, not autodiff."""

    def forward(self, x):
        return x * x

    def backward(self, dy):
        return dy * 100.0


def test_function_custom_backward_overrides_autodiff():
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    x.attach_grad()
    f = _WrongGrad()
    with autograd.record():
        y = f(x)
        z = (y * 2).sum()
    z.backward()
    # natural grad would be 2*2x = [4, 8, 12]; custom gives 2*100
    np.testing.assert_allclose(x.grad.asnumpy(), [200.0, 200.0, 200.0])


def test_function_multi_input_output():
    class Swap(autograd.Function):
        def forward(self, a, b):
            return b * 2, a * 3

        def backward(self, da, db):
            return db * 3, da * 2

    a = nd.array(np.array([1.0], np.float32))
    b = nd.array(np.array([5.0], np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        o1, o2 = Swap()(a, b)
        loss = o1.sum() + 10 * o2.sum()
    loss.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [30.0])  # 10 * 3
    np.testing.assert_allclose(b.grad.asnumpy(), [2.0])


def test_function_saved_state():
    class Scale(autograd.Function):
        def forward(self, x):
            self._x = x
            return x * x

        def backward(self, dy):
            return dy * 2 * self._x  # the true gradient, via saved state

    x = nd.array(np.array([3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = Scale()(x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_function_bad_grad_count_raises():
    class Bad(autograd.Function):
        def forward(self, a, b):
            return a + b

        def backward(self, dy):
            return dy  # one grad for two inputs

    a = nd.ones((2,))
    b = nd.ones((2,))
    a.attach_grad()
    b.attach_grad()
    with pytest.raises(Exception):
        with autograd.record():
            y = Bad()(a, b)
        y.backward()


class _SigmoidOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], 1 / (1 + (-in_data[0]).exp()))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))


@mx.operator.register("test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _SigmoidOp()


def test_custom_op_forward_backward():
    x = nd.array(np.array([0.0, 1.0, -1.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="test_sigmoid")
        z = y.sum()
    z.backward()
    sig = 1 / (1 + np.exp(-np.array([0.0, 1.0, -1.0])))
    np.testing.assert_allclose(y.asnumpy(), sig, rtol=1e-5)
    np.testing.assert_allclose(x.grad.asnumpy(), sig * (1 - sig), rtol=1e-5)


def test_custom_op_unregistered_raises():
    with pytest.raises(Exception):
        nd.Custom(nd.ones((2,)), op_type="never_registered")


def test_custom_op_wrong_arity_raises():
    with pytest.raises(Exception):
        nd.Custom(nd.ones((2,)), nd.ones((2,)), op_type="test_sigmoid")
