"""Capture a device profile of the ResNet-50 bench train step and print a
per-op time breakdown.

Usage:  python tools/profile_bench.py [--batch N] [--steps N]

Writes the raw trace under /tmp/mxtpu_prof and prints the top-K HLO ops by
total device time (aggregated over the steps inside the trace), which is the
evidence base for bench tuning (VERDICT r1 next-step #1).
"""
from __future__ import annotations

import argparse
import glob
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_step(batch):
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import extract_pure_fn
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    net = resnet50_v1(layout="NHWC", stem_s2d=True)
    net.initialize()
    net.cast("bfloat16")
    x = mx.nd.random.uniform(shape=(batch, 224, 224, 3), dtype="bfloat16")
    net(x)
    fwd, params = extract_pure_fn(net, x, training=True)
    aux_idx = list(fwd.aux_indices)

    key = jax.random.PRNGKey(0)
    labels = jax.random.randint(key, (batch,), 0, 1000)

    def loss_fn(p, xb, yb):
        logits, aux = fwd(p, xb)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1)), aux

    lr, mu = 0.1, 0.9

    def train_step(p, mom, xb, yb):
        (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(p, xb, yb)
        new_mom = [mu * m + gg.astype(m.dtype) for m, gg in zip(mom, g)]
        new_p = [pp - lr * m for pp, m in zip(p, new_mom)]
        for i, v in zip(aux_idx, aux):
            new_p[i] = v
        return new_p, new_mom, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    mom = [jnp.zeros_like(p) for p in params]
    return step, params, mom, x._data, labels


def parse_xspace(logdir, min_pct=0.3):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise SystemExit(f"no xplane.pb under {logdir}")
    space = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        space.ParseFromString(f.read())
    for plane in space.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
        agg = defaultdict(float)
        total = 0.0
        for line in plane.lines:
            # XLA Ops line has the per-HLO breakdown; "Steps"/"XLA Modules"
            # lines would double-count the same wall time.
            if line.name not in ("XLA Ops",):
                continue
            for ev in line.events:
                dur = ev.duration_ps / 1e12
                agg[ev_meta.get(ev.metadata_id, "?")] += dur
                total += dur
        if not agg:
            continue
        print(f"\n== plane: {plane.name}  total XLA-op time {total*1e3:.1f} ms")
        shown = 0.0
        for name, t in sorted(agg.items(), key=lambda kv: -kv[1]):
            pct = 100 * t / total
            if pct < min_pct:
                break
            shown += pct
            print(f"{t*1e3:9.3f} ms {pct:5.1f}%  {name[:110]}")
        print(f"(shown {shown:.0f}% of device op time)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--logdir", default="/tmp/mxtpu_prof")
    args = ap.parse_args()

    import jax
    step, params, mom, images, labels = build_step(args.batch)
    params, mom, loss = step(params, mom, images, labels)
    params, mom, loss = step(params, mom, images, labels)
    float(loss)  # sync

    jax.profiler.start_trace(args.logdir)
    for _ in range(args.steps):
        params, mom, loss = step(params, mom, images, labels)
    float(loss)
    jax.profiler.stop_trace()
    parse_xspace(args.logdir)


if __name__ == "__main__":
    main()
