"""Gluon basic layers (reference: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ... import autograd
from ...base import MXNetError
from ...ndarray.ndarray import NDArray, _apply
from ...ops import nn_ops as K
from ..block import (Block, HybridBlock, _layer_rng, _report_aux_update,
                     is_symbolic)

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Flatten",
           "Lambda", "HybridLambda", "Embedding", "ShardedEmbedding",
           "ShardedMoE", "BatchNorm", "LayerNorm",
           "InstanceNorm", "GroupNorm", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "Swish", "GELU", "SiLU", "Concurrent", "Identity", "BatchNormReLU"]


class _SequentialContainer:
    """Shared container behaviour for Sequential / HybridSequential."""

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            for b in items[key]:
                net.register_child(b)
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())


class Sequential(_SequentialContainer, Block):
    """Stack of Blocks executed in order."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)


class HybridSequential(_SequentialContainer, HybridBlock):
    """Stack of HybridBlocks — hybridizes into one XLA executable."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        for block in self._children.values():
            x = block(x)
        return x


class Dense(HybridBlock):
    """Fully-connected layer y = act(x W^T + b) (reference: nn.Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype=np.float32, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def _infer_shapes(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight._finish_deferred_init((self._units, in_units))
        if self.bias is not None:
            self.bias._finish_deferred_init((self._units,))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self._activation is not None:
            out = F.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{self._units}, "
                f"{'linear' if not self._activation else self._activation})")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if not autograd.is_training() or self._rate <= 0:
            return x
        key = _layer_rng()

        def fn(a, _key=key, _p=self._rate, _axes=self._axes):
            import jax
            shape = list(a.shape)
            for ax in _axes:
                shape[ax] = 1
            keep = 1.0 - _p
            mask = jax.random.bernoulli(_key, keep, tuple(shape))
            return jnp.where(mask, a / keep, 0).astype(a.dtype)
        return _apply(fn, [x])

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return x.flatten()

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class Lambda(Block):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import ndarray as F
            function_ = getattr(F, function)
            self._func = lambda *a: function_(*a)
        else:
            self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            name = function
            self._func = lambda F, *a: getattr(F, name)(*a)
        else:
            self._func = function

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype=np.float32,
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def cast(self, dtype):
        # the block's dtype governs the TABLE only; integer index batches
        # must never be cast through a float dtype (exactness dies at
        # 2**24 — ISSUE 15 satellite). HybridBlock.cast already touches
        # parameters only; this override just documents + pins that.
        return super().cast(dtype)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class ShardedEmbedding(Embedding):
    """Model-parallel embedding table for recommender-scale vocabularies
    (ISSUE 15; docs/PERFORMANCE.md "Sharded embeddings").

    Same forward contract as `Embedding`, but the table is meant to be
    ROW-SHARDED over a mesh axis by a shard-plan rule
    (`shard.DEFAULT_RULES` row-shards ``*embed*_weight`` over ``tp``),
    and under a captured step (`Trainer.capture` with `Trainer.shard`)
    the lookup lowers to the sparse fast path of
    mxnet_tpu/shard/embedding.py: dedup -> owner-bucketed all-to-all
    index exchange -> local gather -> all-to-all vector return, with a
    `(unique_rows, D)` sparse backward and a scatter-add optimizer
    update on the owning shard only — no O(vocab) gradient, no
    host-side gather, table + state mesh-resident between steps.

    Integer index batches are REQUIRED (int32/int64 pass untouched); a
    float index batch raises instead of silently looking up the wrong
    row above 2**24. Outside a captured+sharded step the block behaves
    exactly like `Embedding` on integer inputs.
    """

    def __init__(self, input_dim, output_dim, dtype=np.float32,
                 weight_initializer=None, tiered=False, hbm_rows=None,
                 **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer, **kwargs)
        # the capture-path marker mxnet_tpu/cachedop.py keys sparse
        # eligibility on (shard/embedding.py sparse_eligibility)
        self.weight._sharded_embedding = {"vocab": int(input_dim),
                                          "dim": int(output_dim)}
        if tiered:
            from ...base import MXNetError
            from ...shard import tiered as _tiered
            if hbm_rows is None or int(hbm_rows) < 1:
                raise MXNetError(
                    "ShardedEmbedding(tiered=True) needs hbm_rows >= 1 "
                    "(hot-cache rows per shard)")
            # conversion happens at Trainer.shard (shard/tiered.py
            # on_plan); registering the budget by NAME here lets
            # ShardPlan._check_large_replicated account HBM-resident
            # bytes before the table is ever converted
            self.weight._tiered = {"hbm_rows": int(hbm_rows)}
            _tiered.register_hbm_rows(self.weight.name, int(hbm_rows))

    def hybrid_forward(self, F, x, weight):
        from ...shard import embedding as _semb
        if is_symbolic(x):
            # a Symbol's dtype is only a HINT (usually None until bind);
            # enforce the integer contract when the hint is there — the
            # eager/captured paths below always enforce it at execution
            hint = getattr(x, "_dtype_hint", None)
            if hint is not None:
                _semb.check_index_dtype(hint)
            return F.Embedding(x, weight, input_dim=self._input_dim,
                               output_dim=self._output_dim)
        _semb.check_index_dtype(x.dtype)
        ctx = _semb.SparseLookupContext.active()
        if ctx is not None and ctx.handles(self.weight):
            # captured-step trace: recording is off, tracers flow raw
            return type(x)(_semb.lookup(self.weight, x._data,
                                        weight._data))
        ts = getattr(self.weight, "_tiered_state", None)
        if ts is not None:
            if getattr(self.weight, "_trace_override", None) is not None:
                # inside the capture machinery's ABSTRACT passes
                # (eval_shape pre-pass / jaxpr record, cachedop.py):
                # only shapes matter — the live record/consume passes
                # take the SparseLookupContext branch above — so the
                # plain gather below is shape-correct and never
                # materialises values
                return F.Embedding(x, weight, input_dim=self._input_dim,
                                   output_dim=self._output_dim)
            # eager/eval on a converted table: the live parameter is the
            # HOT CACHE, not the logical table — look up through the
            # host tier instead (slow path by design)
            import jax
            import jax.numpy as jnp
            try:
                # eager-only by construction (capture passes return
                # shapes above, foreign traces raise below); the host
                # sync IS the point of the read-through path
                # mxtpu: disable=E02
                idx = np.asarray(x._data)
            except (jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError):
                from ...base import MXNetError
                raise MXNetError(
                    f"tiered embedding {self.weight.name!r} cannot be "
                    f"looked up inside a foreign trace — use the "
                    f"captured step (Trainer.capture) or call it "
                    f"eagerly") from None
            return type(x)(jnp.asarray(ts.lookup_np(idx)))
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return (f"ShardedEmbedding({self._input_dim} -> "
                f"{self._output_dim})")


class ShardedMoE(HybridBlock):
    """Expert-parallel Mixture-of-Experts FFN (ISSUE 16;
    docs/PERFORMANCE.md "Expert parallelism").

    Replaces one dense FFN with ``num_experts`` expert FFNs and a
    learned top-``k`` softmax router. The stacked expert banks
    (``expert_ffn*_weight`` / ``_bias``, dim 0 = expert index) are
    routed to the 'tp' mesh axis by `shard.DEFAULT_RULES`' axis
    override, so each device holds ``E / tp`` experts; under a captured
    step with a shard plan the dispatch/combine lowers to the
    shard/moe.py 2-all-to-all exchange (tokens sharded over (dp, tp)
    jointly — the GShard layout). Without a plan, on an axis of size 1,
    or with non-divisible expert/token counts, the layer degenerates to
    pure local dispatch with zero collectives.

    Capacity-factor token dropping is LOUD, never silent: the
    ``dropped`` aux parameter accumulates the psum'd drop count,
    ``overflow_frac`` holds the last step's dropped fraction of
    (token, choice) pairs, and `publish_metrics()` forwards both to the
    observability registry (`moe_tokens_dropped` counter,
    `moe_overflow_frac` / `moe_aux_loss` gauges). A dropped token's MoE
    output is exactly zero, so with ``residual=True`` (default) it
    passes through the skip connection unchanged — gradients included.

    The Switch-style load-balancing auxiliary loss
    ``E * sum_e f_e * P_e`` (scaled by ``aux_loss_coef``) is threaded
    into the captured/imperative training loss automatically by
    `Trainer.capture`; in a hand-written eager loop read it from
    ``self.last_aux_loss`` after the forward and add it yourself.
    """

    def __init__(self, units, hidden_units, num_experts, k=2,
                 capacity_factor=1.25, aux_loss_coef=0.01,
                 activation="relu", residual=True, normalize_gates=True,
                 dtype=np.float32, weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        if not 1 <= int(k) <= int(num_experts):
            raise MXNetError(f"ShardedMoE: k={k} must be in "
                             f"[1, num_experts={num_experts}]")
        if capacity_factor <= 0:
            raise MXNetError("ShardedMoE: capacity_factor must be > 0")
        from ...shard import moe as _smoe
        if activation not in _smoe._ACTS:
            raise MXNetError(f"ShardedMoE: unknown activation "
                             f"{activation!r} (have "
                             f"{sorted(_smoe._ACTS)})")
        self._units = int(units)
        self._hidden = int(hidden_units)
        self._num_experts = int(num_experts)
        self._k = int(k)
        self._capacity_factor = float(capacity_factor)
        self._aux_loss_coef = float(aux_loss_coef)
        self._activation = activation
        self._residual = bool(residual)
        self._normalize_gates = bool(normalize_gates)
        self.last_aux_loss = None
        self._published_dropped = 0.0
        E, d, h = self._num_experts, self._units, self._hidden
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(E, d), dtype=dtype,
                init=weight_initializer)
            self.expert_ffn1_weight = self.params.get(
                "expert_ffn1_weight", shape=(E, d, h), dtype=dtype,
                init=weight_initializer)
            self.expert_ffn1_bias = self.params.get(
                "expert_ffn1_bias", shape=(E, h), dtype=dtype,
                init="zeros")
            self.expert_ffn2_weight = self.params.get(
                "expert_ffn2_weight", shape=(E, h, d), dtype=dtype,
                init=weight_initializer)
            self.expert_ffn2_bias = self.params.get(
                "expert_ffn2_bias", shape=(E, d), dtype=dtype,
                init="zeros")
            # loud-accounting aux state (BN running-stat pattern):
            # last-step aux loss + overflow fraction, cumulative drops
            self.aux_loss = self.params.get(
                "aux_loss", shape=(1,), init="zeros", grad_req="null")
            self.overflow_frac = self.params.get(
                "overflow_frac", shape=(1,), init="zeros",
                grad_req="null")
            self.dropped = self.params.get(
                "dropped", shape=(1,), init="zeros", grad_req="null")

    def _routing(self, n_tokens):
        """(mesh, axis, layout) for this layer under the enclosing
        captured step's plan — honouring per-param axis overrides via
        `plan.spec_for` on the expert bank (None/size-1/non-divisible
        all land on the local path)."""
        from ...shard import moe as _smoe
        plan = _smoe.current_plan()
        mesh = axis = data_axis = None
        if plan is not None:
            E, d, h = self._num_experts, self._units, self._hidden
            spec = tuple(plan.spec_for(self.expert_ffn1_weight.name,
                                       (E, d, h)))
            if spec and isinstance(spec[0], str):
                mesh, axis = plan.mesh, spec[0]
                data_axis = plan.data_axis
        lay = _smoe.routing_layout(
            n_tokens, self._num_experts, self._k, self._capacity_factor,
            mesh=mesh, axis=axis, data_axis=data_axis)
        return mesh, axis, data_axis, lay

    def hybrid_forward(self, F, x, gate_weight, expert_ffn1_weight,
                       expert_ffn1_bias, expert_ffn2_weight,
                       expert_ffn2_bias, aux_loss=None,
                       overflow_frac=None, dropped=None):
        from ...shard import moe as _smoe
        from ..block import _TraceContext
        if is_symbolic(x):
            raise MXNetError(
                "ShardedMoE has no symbolic/export path — data-dependent "
                "token routing does not lower to a static symbol graph; "
                "hybridize/capture the imperative block instead")
        if x.shape[-1] != self._units:
            raise MXNetError(
                f"ShardedMoE: input feature dim {x.shape[-1]} != "
                f"units {self._units}")
        n_tokens = 1
        for s in x.shape[:-1]:
            n_tokens *= int(s)
        mesh, axis, data_axis, lay = self._routing(n_tokens)
        itemsize = np.dtype(x.dtype).itemsize
        _smoe.report_site({
            "name": self.name, "sharded": lay["sharded"],
            "reason": lay["reason"], "capacity": lay["capacity"],
            "n_exp_shards": lay["n_exp_shards"],
            "a2a_per_pass": _smoe.A2A_PER_LAYER if lay["sharded"] else 0,
            "bytes": _smoe.a2a_bytes_per_step(
                lay, self._num_experts, self._units, itemsize)})

        def fn(xv, gw, w1, b1, w2, b2, _E=self._num_experts,
               _k=self._k, _cf=self._capacity_factor,
               _act=self._activation, _nrm=self._normalize_gates,
               _mesh=mesh, _axis=axis, _dax=data_axis):
            shp = xv.shape
            y2, aux, frac, drops = _smoe.moe_forward(
                xv.reshape((-1, shp[-1])), gw, w1, b1, w2, b2,
                n_experts=_E, k=_k, capacity_factor=_cf,
                activation=_act, normalize_gates=_nrm,
                mesh=_mesh, axis=_axis, data_axis=_dax)
            return y2.reshape(shp), aux, frac, drops

        y, aux, frac, drops = _apply(
            fn, [x, gate_weight, expert_ffn1_weight, expert_ffn1_bias,
                 expert_ffn2_weight, expert_ffn2_bias], n_out=4)
        out = (y + x) if self._residual else y

        if autograd.is_training():
            _report_aux_update(self.aux_loss, aux.reshape((1,)))
            _report_aux_update(self.overflow_frac, frac.reshape((1,)))
            _report_aux_update(self.dropped,
                               dropped + drops.reshape((1,)))
        scaled = aux * self._aux_loss_coef
        if not _smoe.report_aux_loss(scaled) \
                and _TraceContext.active() is None:
            # hand-written eager loop: the caller owns the aux loss
            # (never stash a tracer on the block under a trace)
            self.last_aux_loss = scaled
        return out

    def publish_metrics(self):
        """Flush the layer's drop/aux accounting to the observability
        registry: the cumulative `moe_tokens_dropped{layer=}` counter
        delta since the last publish plus the `moe_overflow_frac` /
        `moe_aux_loss` gauges. Host-syncs the three scalars — call it
        between steps (eval boundaries, bench teardown), never inside
        a captured loss. Returns {"dropped", "overflow_frac",
        "aux_loss"} as floats."""
        from ...observability import registry
        dropped = float(self.dropped.data().asnumpy()[0])
        frac = float(self.overflow_frac.data().asnumpy()[0])
        aux = float(self.aux_loss.data().asnumpy()[0])
        reg = registry()
        delta = dropped - self._published_dropped
        if delta > 0:
            reg.counter("moe_tokens_dropped", layer=self.name).inc(delta)
            self._published_dropped = dropped
        reg.gauge("moe_overflow_frac", layer=self.name).set(frac)
        reg.gauge("moe_aux_loss", layer=self.name).set(aux)
        return {"dropped": dropped, "overflow_frac": frac,
                "aux_loss": aux}

    def __repr__(self):
        return (f"ShardedMoE({self._units} -> {self._hidden} -> "
                f"{self._units}, experts={self._num_experts}, "
                f"k={self._k}, cf={self._capacity_factor})")


class BatchNorm(HybridBlock):
    """Batch normalisation with functional running-stat updates."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, in_channels=0,
                 beta_initializer="zeros", gamma_initializer="ones", **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,), init="zeros",
                allow_deferred_init=True, grad_req="null")
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,), init="ones",
                allow_deferred_init=True, grad_req="null")

    def _infer_shapes(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._finish_deferred_init((c,))

    def cast(self, dtype):
        if np.dtype(dtype) == np.float16:
            dtype = np.float32
        return super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        if is_symbolic(x):
            # symbolic trace (export path): aux-state updates are handled
            # by the Executor's train registry, not the gluon tape
            return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                               eps=self._epsilon, momentum=self._momentum,
                               axis=self._axis, fix_gamma=not self._scale,
                               use_global_stats=self._use_global_stats)
        training = autograd.is_training() and not self._use_global_stats
        outs = _apply(
            lambda a, g, b, mm, mv, _e=self._epsilon, _m=self._momentum,
            _t=training, _ax=self._axis:
            K.batch_norm(a, g, b, mm, mv, _e, _m, _t, _ax),
            [x, gamma, beta, running_mean, running_var], n_out=3)
        out, new_mean, new_var = outs
        if training:
            _report_aux_update(self.running_mean, new_mean)
            _report_aux_update(self.running_var, new_var)
        return out

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, eps={self._epsilon}, "
                f"momentum={self._momentum}, in_channels={self.in_channels})")



class BatchNormReLU(BatchNorm):
    """BatchNorm with a fused ReLU epilogue (reference: nn.BatchNormReLU
    — upstream fuses via cuDNN; XLA fuses the relu into the BN kernel
    here, so subclass + relu is already the fused program)."""

    def hybrid_forward(self, F, x, *args, **kwargs):
        out = super().hybrid_forward(F, x, *args, **kwargs)
        return F.relu(out)   # F-dispatch keeps the symbolic path alive


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def _infer_shapes(self, x):
        c = x.shape[self._axis]
        self.gamma._finish_deferred_init((c,))
        self.beta._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        if not is_symbolic(x) and self._axis in (-1, x.ndim - 1):
            # fused fast path (Pallas on TPU)
            from ...ops.pallas_kernels import fused_layer_norm

            def fn(a, g, b, _e=self._epsilon):
                return fused_layer_norm(a, g, b, eps=_e)
            return _apply(fn, [x, gamma, beta])
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)

    def __repr__(self):
        return f"LayerNorm(axis={self._axis}, eps={self._epsilon})"


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def _infer_shapes(self, x):
        c = x.shape[1]
        self.gamma._finish_deferred_init((c,))
        self.beta._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def _infer_shapes(self, x):
        c = x.shape[1]
        self.gamma._finish_deferred_init((c,))
        self.beta._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def _alias(self):
        return getattr(self, "_act_type", "activation")

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(in_channels,),
                init=alpha_initializer or init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.PReLU(x, alpha)


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        if self._beta == 1.0:
            return F.silu(x)
        return x * F.sigmoid(x * self._beta)


SiLU = Swish


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation != "erf"

    def hybrid_forward(self, F, x):
        import jax
        return _apply(lambda a, _t=self._approx: jax.nn.gelu(a, approximate=_t),
                      [x])


class Concurrent(Sequential):
    """Parallel branches concatenated along an axis (reference: contrib)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        from ..block import is_symbolic
        if is_symbolic(outs[0]):
            from ...symbol import ops as S
            return S.concat(*outs, dim=self.axis)
        from ...ops.tensor_ops import concat
        return concat(*outs, dim=self.axis)
