"""Transformer NMT (Sockeye / gluonnlp transformer_en_de parity —
encoder-decoder with multi-head attention, label smoothing, beam search;
rebuilt TPU-first from the behavior of gluonnlp's model.transformer).

TPU-first choices:
  * sinusoidal position encodings precomputed as a static table;
  * fused QKV for self-attention, fused KV for cross-attention (MXU-sized
    matmuls);
  * causal self-attention in the decoder via ops.pallas_kernels
    flash_attention rides the Pallas kernels, with padding expressed as
    per-row kv valid lengths (scalar-prefetch masked flash path);
  * beam search is ONE jitted program: `lax.scan` over decode steps with
    static (batch, beam, max_len) shapes — no dynamic shapes, no host sync
    inside the loop.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply
from ..gluon import nn
from ..gluon.block import HybridBlock, extract_pure_fn
from ..ops.pallas_kernels import flash_attention

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerNMT",
           "transformer_base", "beam_search", "sinusoid_table"]


def sinusoid_table(max_len, units):
    pos = np.arange(max_len)[:, None]
    dim = np.arange(units)[None, :]
    angle = pos / np.power(10000, (2 * (dim // 2)) / units)
    table = np.zeros((max_len, units), np.float32)
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def _split_heads(x, n_heads):
    b, s, d = x.shape
    return x.reshape(b, s, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, s, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * dh)


class SelfAttention(HybridBlock):
    """Fused-QKV self-attention; causal flag for decoder use."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads != 0:
            raise MXNetError("units must be divisible by num_heads")
        self._h = num_heads
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, in_units=units,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, in_units=units,
                                 prefix="proj_")
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, valid_length=None):
        h, causal = self._h, self._causal

        def attn(qkv_raw, *maybe_vl):
            q, k, v = jnp.split(qkv_raw, 3, axis=-1)
            q, k, v = (_split_heads(t, h) for t in (q, k, v))
            kv_len = maybe_vl[0].astype(jnp.int32) if maybe_vl else None
            out = flash_attention(q, k, v, causal=causal, kv_lengths=kv_len)
            return _merge_heads(out)

        inputs = [self.qkv(x)] +             ([valid_length] if valid_length is not None else [])
        return self.dropout(self.proj(_apply(attn, inputs)))


class CrossAttention(HybridBlock):
    """Decoder->encoder attention with fused KV projection."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._h = num_heads
        with self.name_scope():
            self.q = nn.Dense(units, flatten=False, in_units=units,
                              prefix="q_")
            self.kv = nn.Dense(2 * units, flatten=False, in_units=units,
                               prefix="kv_")
            self.proj = nn.Dense(units, flatten=False, in_units=units,
                                 prefix="proj_")
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, memory, mem_valid_length=None):
        h = self._h

        def attn(q_raw, kv_raw, *maybe_vl):
            k, v = jnp.split(kv_raw, 2, axis=-1)
            q = _split_heads(q_raw, h)
            k = _split_heads(k, h)
            v = _split_heads(v, h)
            kv_len = maybe_vl[0].astype(jnp.int32) if maybe_vl else None
            out = flash_attention(q, k, v, kv_lengths=kv_len)
            return _merge_heads(out)

        inputs = [self.q(x), self.kv(memory)]
        if mem_valid_length is not None:
            inputs.append(mem_valid_length)
        return self.dropout(self.proj(_apply(attn, inputs)))


class _FFN(HybridBlock):
    def __init__(self, units, hidden, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn1 = nn.Dense(hidden, flatten=False, in_units=units,
                                 activation="relu", prefix="ffn1_")
            self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden,
                                 prefix="ffn2_")
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x):
        return self.dropout(self.ffn2(self.ffn1(x)))


class EncoderLayer(HybridBlock):
    def __init__(self, units, hidden, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attn = SelfAttention(units, num_heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden, dropout)
            self.ln2 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, valid_length=None):
        x = self.ln1(x + self.attn(x, valid_length))
        return self.ln2(x + self.ffn(x))


class DecoderLayer(HybridBlock):
    def __init__(self, units, hidden, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attn = SelfAttention(units, num_heads, dropout,
                                           causal=True)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.cross_attn = CrossAttention(units, num_heads, dropout)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden, dropout)
            self.ln3 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory, self_valid_length=None,
                       mem_valid_length=None):
        x = self.ln1(x + self.self_attn(x, self_valid_length))
        x = self.ln2(x + self.cross_attn(x, memory, mem_valid_length))
        return self.ln3(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden, num_heads, max_length=512,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._pos = sinusoid_table(max_length, units)
        self._scale = math.sqrt(units)
        with self.name_scope():
            self.dropout = nn.Dropout(dropout)
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(EncoderLayer(units, hidden, num_heads,
                                                 dropout))

    def hybrid_forward(self, F, x, valid_length=None):
        s = x.shape[1]
        pos, scale = self._pos, self._scale

        def add_pos(a):
            return a * scale + jnp.asarray(pos[:s])[None]

        x = self.dropout(_apply(add_pos, [x]))
        for layer in self.layers:
            x = layer(x, valid_length)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden, num_heads, max_length=512,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._pos = sinusoid_table(max_length, units)
        self._scale = math.sqrt(units)
        with self.name_scope():
            self.dropout = nn.Dropout(dropout)
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(DecoderLayer(units, hidden, num_heads,
                                                 dropout))

    def hybrid_forward(self, F, x, memory, self_valid_length=None,
                       mem_valid_length=None, position_offset=0):
        s = x.shape[1]
        pos, scale = self._pos, self._scale
        off = position_offset

        def add_pos(a):
            return a * scale + jnp.asarray(pos[off:off + s])[None]

        x = self.dropout(_apply(add_pos, [x]))
        for layer in self.layers:
            x = layer(x, memory, self_valid_length, mem_valid_length)
        return x


class TransformerNMT(HybridBlock):
    """Seq2seq NMT model. forward(src, tgt, src_valid_length=None) -> logits
    over the target vocabulary (teacher forcing). Source/target embeddings and
    the output projection share one weight matrix (Sockeye's
    weight-tying=src_trg_softmax)."""

    def __init__(self, vocab_size, units=512, hidden=2048, num_layers=6,
                 num_heads=8, max_length=512, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.vocab_size = vocab_size
        self._units = units
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.encoder = TransformerEncoder(num_layers, units, hidden,
                                              num_heads, max_length, dropout)
            self.decoder = TransformerDecoder(num_layers, units, hidden,
                                              num_heads, max_length, dropout)

    def encode(self, src, src_valid_length=None):
        return (self.encoder(self.embed(src), src_valid_length),
                src_valid_length)

    def project(self, x):
        """Tied output projection: logits = x @ embed.T."""
        w = self.embed.weight.data()
        return _apply(lambda a, ww: jnp.einsum("bsd,vd->bsv", a, ww), [x, w])

    def hybrid_forward(self, F, src, tgt, src_valid_length=None):
        memory, mem_vl = self.encode(src, src_valid_length)
        out = self.decoder(self.embed(tgt), memory, None, mem_vl)
        return self.project(out)


def transformer_base(vocab_size=36548, **kwargs):
    """WMT16 En-De base config (Sockeye transformer parity)."""
    return TransformerNMT(vocab_size, units=512, hidden=2048, num_layers=6,
                          num_heads=8, **kwargs)


# ---------------------------------------------------------------------------
# beam search — one jitted XLA program, static shapes
# ---------------------------------------------------------------------------
def beam_search(model: TransformerNMT, src, src_valid_length=None,
                beam_size=4, max_length=32, bos_id=2, eos_id=3, alpha=0.6):
    """Batched beam search decode.

    Returns (tokens (B, K, max_length) int32, scores (B, K) float32), beams
    sorted best-first. The whole search is one `lax.scan` over decode steps:
    at step t the decoder re-runs over the static (max_length)-padded prefix
    with a causal mask — static shapes, so XLA compiles exactly one program
    regardless of output length (KV-cache incremental decode is a further
    optimisation; reference decoders re-run the graph per step too).
    """
    fwd, params = extract_pure_fn(
        model, src, NDArray(jnp.zeros(
            (src.shape[0], max_length), jnp.int32)),
        *( [src_valid_length] if src_valid_length is not None else []))

    B = src.shape[0]
    K = beam_size
    V = model.vocab_size
    src_r = jnp.repeat(src._data, K, axis=0)              # (B*K, S)
    args = [src_r]
    if src_valid_length is not None:
        args.append(jnp.repeat(src_valid_length._data, K, axis=0))

    neg_inf = -1e9

    def step(carry, t):
        tokens, scores, done = carry                      # (B*K, L), (B*K,)
        logits = fwd(params, args[0], tokens, *args[1:])  # (B*K, L, V)
        logp = jax.nn.log_softmax(
            lax.dynamic_index_in_dim(logits, t, axis=1, keepdims=False)
            .astype(jnp.float32))                         # (B*K, V)
        # finished beams only extend with EOS at zero cost
        eos_only = jnp.full((V,), neg_inf).at[eos_id].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None], logp)
        cand = scores[:, None] + logp                     # (B*K, V)
        cand = cand.reshape(B, K * V)
        top_scores, top_idx = lax.top_k(cand, K)          # (B, K)
        beam_idx = top_idx // V                           # source beam
        tok_idx = (top_idx % V).astype(jnp.int32)
        flat_beam = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
        tokens = tokens[flat_beam]
        done = done[flat_beam]
        tokens = tokens.at[:, t + 1].set(
            jnp.where(done, tokens[:, t + 1], tok_idx.reshape(-1)))
        done = jnp.logical_or(done, tok_idx.reshape(-1) == eos_id)
        return (tokens, top_scores.reshape(-1), done), None

    tokens0 = jnp.zeros((B * K, max_length), jnp.int32).at[:, 0].set(bos_id)
    # only beam 0 of each batch is live at t=0 (all beams identical)
    scores0 = jnp.where(jnp.arange(B * K) % K == 0, 0.0, neg_inf)
    done0 = jnp.zeros((B * K,), bool)

    def run():
        (tokens, scores, done), _ = lax.scan(
            step, (tokens0, scores0, done0), jnp.arange(max_length - 1))
        lengths = jnp.argmax(tokens == eos_id, axis=1)
        lengths = jnp.where(lengths == 0, max_length, lengths + 1)
        lp = ((5.0 + lengths) / 6.0) ** alpha             # GNMT length norm
        norm = scores / lp
        norm = norm.reshape(B, K)
        order = jnp.argsort(-norm, axis=1)
        tokens = tokens.reshape(B, K, max_length)
        tokens = jnp.take_along_axis(tokens, order[:, :, None], axis=1)
        norm = jnp.take_along_axis(norm, order, axis=1)
        return tokens, norm

    tokens, norm = jax.jit(run)()
    return NDArray(tokens), NDArray(norm)
