"""Misc utilities (reference: python/mxnet/util.py).

The np-mode switches delegate to mx.npx — one process-global flag
(reference parity: the C++ side keeps one global, not per-thread state),
whether flipped via mx.util or mx.npx.
"""
from __future__ import annotations

__all__ = ["waitall", "is_np_array", "is_np_shape", "set_np", "reset_np",
           "use_np", "set_module"]


def waitall():
    from .ndarray.ndarray import waitall as _w
    _w()


def is_np_array():
    from . import numpy_extension as npx
    return npx.is_np_array()


def is_np_shape():
    from . import numpy_extension as npx
    return npx.is_np_shape()


def set_np(shape=True, array=True):
    from . import numpy_extension as npx
    npx.set_np(shape=shape, array=array)


def reset_np():
    from . import numpy_extension as npx
    npx.reset_np()


def use_np(func):
    from . import numpy_extension as npx
    return npx.use_np(func)


def set_module(module):
    """Decorator overriding `__module__` for nicer reprs/docs (reference:
    python/mxnet/util.py set_module)."""
    def decorator(obj):
        if module is not None:
            obj.__module__ = module
        return obj
    return decorator
