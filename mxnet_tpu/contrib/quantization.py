"""INT8 quantization (reference: python/mxnet/contrib/quantization.py +
src/operator/quantization/*).

TPU-native: the MXU multiplies int8 x int8 into int32 natively, so int8
inference is a first-class fast path — not a GPU-only feature. The design
maps the reference's calibrated symmetric per-tensor scheme onto XLA:

  * `quantize` / `dequantize` — symmetric linear mapping
    q = clip(round(x / scale), -127, 127), x ≈ q * scale
    (reference: quantize_v2 with min/max calib -> int8).
  * `QuantizedDense` / `QuantizedConv2D` — weights stored int8 + fp scale;
    activations quantized dynamically per call (or with a calibrated
    static scale); the dot runs int8 x int8 -> int32
    (`preferred_element_type=jnp.int32`) and one fp multiply rescales.
  * `quantize_model` / `quantize_net` — walk a Gluon block tree and swap
    Dense/Conv2D layers for their quantized twins, optionally running
    calibration batches to fix activation scales ('naive' max-abs
    calibration, reference's calib_mode='naive').

Excluded layers (first/last, by name) mirror the reference's
`excluded_sym_names`.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply

__all__ = ["quantize", "dequantize", "QuantizedDense", "QuantizedConv2D",
           "quantize_net", "quantize_model"]


def _scale_of(amax):
    return jnp.maximum(amax, 1e-12) / 127.0


_ACTS = {
    None: lambda y: y,
    "relu": lambda y: jnp.maximum(y, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
}


def _act_fn(name, layer_name):
    if name not in _ACTS:
        raise MXNetError(
            f"quantized layer {layer_name!r}: unsupported activation "
            f"{name!r} (supported: {sorted(k for k in _ACTS if k)})")
    return _ACTS[name]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """Symmetric int8 quantization. Returns (quantized, min_range,
    max_range) like the reference's quantize op. min/max default to the
    observed +-absmax."""
    if out_type != "int8":
        raise MXNetError("TPU quantization is int8 (MXU-native)")
    def _to_float(r):
        if r is None:
            return 0.0
        return float(r.asnumpy()) if hasattr(r, "asnumpy") else float(r)

    calib = None
    if min_range is not None or max_range is not None:
        calib = max(abs(_to_float(min_range)), abs(_to_float(max_range)))

    def f(x):
        amax = jnp.float32(calib) if calib is not None             else jnp.max(jnp.abs(x))
        scale = _scale_of(amax)
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return q, -amax, amax

    if isinstance(data, NDArray):
        return _apply(f, [data], n_out=3)
    return f(data)


def dequantize(data, min_range, max_range):
    """int8 -> float32 (reference: dequantize op). Ranges may be NDArrays,
    jax arrays, or plain floats."""
    def f(q, mn, mx):
        scale = _scale_of(jnp.maximum(jnp.abs(mn), jnp.abs(mx)))
        return q.astype(jnp.float32) * scale

    if isinstance(data, NDArray):
        def lift(r):
            return r if isinstance(r, NDArray) else NDArray(jnp.asarray(r))
        return _apply(f, [data, lift(min_range), lift(max_range)])
    return f(data, jnp.asarray(min_range), jnp.asarray(max_range))


def _quantize_weight(w):
    """fp weight -> (int8 weight, fp32 scale), symmetric per-tensor."""
    amax = float(jnp.max(jnp.abs(w)))
    scale = max(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, np.float32(scale)


def _dyn_act_scale(x):
    return _scale_of(jnp.max(jnp.abs(x)))


class _QuantizedBase:
    """Common int8 layer mechanics; not a Block — forward is pure and goes
    through _apply so it records on the tape and traces under jit."""

    def __init__(self, name):
        self.name = name
        self._act_scale = None      # set by calibration; else dynamic

    def observe(self, x):
        """Calibration: track max-abs of activations (naive calib)."""
        amax = float(jnp.max(jnp.abs(x._data if isinstance(x, NDArray)
                                     else x)))
        prev = self._act_scale_amax = max(
            getattr(self, "_act_scale_amax", 0.0), amax)
        self._act_scale = np.float32(max(prev, 1e-12) / 127.0)


class QuantizedDense(_QuantizedBase):
    """int8 y = (x_q @ W_q^T) * (s_x * s_w) + b (reference:
    quantized_fully_connected). Weight held int8; activation quantized
    dynamically unless calibrated."""

    def __init__(self, dense):
        super().__init__(getattr(dense, "name", "dense"))
        w = dense.weight.data()._data.astype(jnp.float32)
        self.wq, self.w_scale = _quantize_weight(w)
        self.bias = (dense.bias.data()._data.astype(jnp.float32)
                     if getattr(dense, "bias", None) is not None else None)
        self._flatten = getattr(dense, "_flatten", True)
        self._act = _act_fn(getattr(dense, "_activation", None), self.name)

    def __call__(self, x):
        wq, w_scale = self.wq, self.w_scale
        bias, act = self.bias, self._act
        static_scale = self._act_scale
        flatten = self._flatten

        def f(xv):
            if flatten and xv.ndim > 2:
                xv = xv.reshape(xv.shape[0], -1)
            xf = xv.astype(jnp.float32)
            s_x = static_scale if static_scale is not None \
                else _dyn_act_scale(xf)
            xq = jnp.clip(jnp.round(xf / s_x), -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, wq, (((xq.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * (s_x * w_scale)
            if bias is not None:
                y = y + bias
            return act(y)

        return _apply(f, [x] if isinstance(x, NDArray) else [NDArray(x)])


class QuantizedConv2D(_QuantizedBase):
    """int8 NHWC/NCHW conv -> int32 accum -> fp rescale (reference:
    quantized_conv)."""

    def __init__(self, conv):
        super().__init__(getattr(conv, "name", "conv"))
        w = conv.weight.data()._data.astype(jnp.float32)
        self.wq, self.w_scale = _quantize_weight(w)
        self.bias = (conv.bias.data()._data.astype(jnp.float32)
                     if getattr(conv, "bias", None) is not None else None)
        self._stride = getattr(conv, "_strides", 1)
        self._pad = getattr(conv, "_padding", 0)
        self._dilation = getattr(conv, "_dilation", 1)
        self._groups = getattr(conv, "_groups", 1)
        self._layout = getattr(conv, "_layout", None) or "NCHW"
        self._act = _act_fn(getattr(conv, "_activation", None), self.name)

    def __call__(self, x):
        wq, w_scale = self.wq, self.w_scale
        bias, act = self.bias, self._act
        stride, pad, layout = self._stride, self._pad, self._layout
        dilation, groups = self._dilation, self._groups
        static_scale = self._act_scale

        def f(xv):
            from jax import lax
            xf = xv.astype(jnp.float32)
            s_x = static_scale if static_scale is not None \
                else _dyn_act_scale(xf)
            xq = jnp.clip(jnp.round(xf / s_x), -127, 127).astype(jnp.int8)
            ndim = xv.ndim - 2
            st = (stride,) * ndim if isinstance(stride, int) \
                else tuple(stride)
            pd = (pad,) * ndim if isinstance(pad, int) else tuple(pad)
            dl = (dilation,) * ndim if isinstance(dilation, int) \
                else tuple(dilation)
            spatial = layout.replace("N", "").replace("C", "")
            rhs = ("OI" + spatial) if layout.index("C") == 1 \
                else ("O" + spatial + "I")
            dn = lax.conv_dimension_numbers(xq.shape, wq.shape,
                                            (layout, rhs, layout))
            acc = lax.conv_general_dilated(
                xq, wq, window_strides=st,
                padding=tuple((p, p) for p in pd),
                rhs_dilation=dl, feature_group_count=groups,
                dimension_numbers=dn, preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * (s_x * w_scale)
            if bias is not None:
                c_axis = layout.index("C")
                shape = [1] * y.ndim
                shape[c_axis] = -1
                y = y + bias.reshape(shape)
            return act(y)

        return _apply(f, [x] if isinstance(x, NDArray) else [NDArray(x)])


_SEQ_TYPES = ("HybridSequential", "Sequential")


class QuantizedNet:
    """Result of quantize_net: same call signature as the source block,
    with listed layers running int8. Supports (nested) Sequential trees —
    quantize_net raises up front for structures it cannot rewire, so a
    returned QuantizedNet never silently runs fp32."""

    def __init__(self, block, replacements):
        self._block = block
        self._replacements = replacements  # id(child) -> quantized twin

    def __call__(self, x):
        return self._forward(self._block, x, observe=False)

    def _forward(self, block, x, observe):
        """Run `block` with quantized twins substituted; with observe=True
        runs the ORIGINAL layers but feeds each twin's calibrator."""
        for c in block._children.values():
            q = self._replacements.get(id(c))
            if q is not None:
                if observe:
                    q.observe(x)
                    x = c(x)
                else:
                    x = q(x)
            elif type(c).__name__ in _SEQ_TYPES:
                x = self._forward(c, x, observe)
            else:
                x = c(x)
        return x

    @property
    def quantized_layers(self):
        return list(self._replacements.values())


def quantize_net(network, quantized_dtype="int8", exclude_layers=None,
                 calib_data=None, num_calib_batches=None, **kwargs):
    """Quantize a Gluon net's Dense/Conv2D layers to int8 (reference:
    contrib.quantization.quantize_net). Returns a callable QuantizedNet.

    calib_data: optional iterable of input batches used to fix activation
    scales (naive max-abs); without it activations quantize dynamically."""
    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError("TPU quantization supports int8")
    exclude = set(exclude_layers or [])
    if type(network).__name__ not in _SEQ_TYPES:
        raise MXNetError(
            "quantize_net rewires (nested) HybridSequential/Sequential "
            "trees; for custom Blocks wrap the quantizable submodules in a "
            "Sequential or use QuantizedDense/QuantizedConv2D directly")
    replacements = {}

    def walk(b, path=""):
        for name, child in b._children.items():
            cls = type(child).__name__
            cpath = f"{path}.{name}" if path else name
            if cpath in exclude or cls in exclude:
                continue
            if cls == "Dense":
                replacements[id(child)] = QuantizedDense(child)
            elif cls == "Conv2D":
                replacements[id(child)] = QuantizedConv2D(child)
            elif cls in _SEQ_TYPES:
                walk(child, cpath)
            elif any(type(g).__name__ in ("Dense", "Conv2D")
                     for g in _descendants(child)):
                # a quantizable layer hiding under a custom block would be
                # silently skipped at call time — refuse instead
                raise MXNetError(
                    f"cannot quantize inside custom block {cpath!r} "
                    f"({cls}); exclude it via exclude_layers or quantize "
                    f"its layers directly")

    walk(network)
    if not replacements:
        raise MXNetError("no quantizable (Dense/Conv2D) layers found")
    qnet = QuantizedNet(network, replacements)

    if calib_data is not None:
        n = 0
        for batch in calib_data:
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            # run the ORIGINAL fp net, observing inputs to each twin —
            # same traversal as inference, nested containers included
            qnet._forward(network, x, observe=True)
            n += 1
            if num_calib_batches is not None and n >= num_calib_batches:
                break
    return qnet


def _descendants(block):
    for c in getattr(block, "_children", {}).values():
        yield c
        yield from _descendants(c)


def quantize_model(sym_or_net, *args, **kwargs):
    """Reference-named entry: quantize a Gluon block (the Symbol/Module
    path quantizes the bound net the same way)."""
    return quantize_net(sym_or_net, *args, **kwargs)
