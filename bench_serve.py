"""Serving benchmark (ISSUE 6): request latency percentiles + aggregate
tokens/s under Poisson arrivals, continuous vs static batching.

ISSUE 12 extension — the `--fastpath` arm (also folded into bench.py's
supervisor fields) measures the serving fast path on a shared-system-
prompt Poisson mix: the SAME prompted trace runs warm (content-hashed
radix prefix cache on — later requests adopt cached prompt pages and
skip that prefill) vs cold (cache disabled), and once more with
speculative_k=3 (n-gram drafts verified by one widened dispatch per
turn). Headlines: `prefix_speedup` (wall tokens/s, warm over cold) with
`{warm,cold}_decode_turns` as the deterministic witness, and
`spec_turns_per_token` vs `control_turns_per_token` for speculation.

ISSUE 7 extension — the `--background-train` arm replays the same trace
while a sustained background engine flood (prefetch/checkpoint stand-in
tasks) contends for the engine workers, once with QoS priorities on and
once with `engine.set_qos(False)` (pure FIFO): the contended p99 pair is
what the priority classes + aging actually buy a serving tenant sharing
chips with training. `p99_contended_ms` rides the supervisor JSON as
`serve_p99_contended_ms`.

The workload is a mixed-length open-loop arrival process: exponential
inter-arrival times (Poisson process, seeded), source lengths and token
budgets drawn from a spread so a static batch always carries stragglers.
The same request trace is replayed twice through the SAME model:

  * continuous — `serve.Server` default: admissions fill freed slots
    every step, so short requests never wait for the batch's longest;
  * static    — `static_batching=True`: admission only into an empty
    batch (the classic serve-batch-drain loop) — the baseline continuous
    batching must beat on any mixed-length workload.

Reports p50/p95/p99 end-to-end latency, p50 TTFT and tokens/s for both
policies plus the speedup. Prints exactly ONE JSON line on stdout
(standalone); `measure()` returns the dict for bench.py's supervisor
contract (`serve_tokens_per_s` / `serve_p99_ms` ride the headline
metric). Off the driver line by default only in --smoke runs; disable
with BENCH_SERVE=0.
"""
from __future__ import annotations

import json
import os
import sys
import time

# service-bound load: arrivals fast enough that slots stay contended —
# an arrival-bound trace would let both policies idle between requests
# and hide the straggler cost static batching pays
N_REQUESTS = 48
RATE_HZ = 400.0         # mean arrival rate of the Poisson process
SLOTS = 4


def _build_server(static):
    import mxnet_tpu as mx
    from mxnet_tpu.models.transformer import TransformerNMT

    mx.random.seed(7)
    model = TransformerNMT(64, units=32, hidden=64, num_layers=2,
                           num_heads=4, max_length=64, dropout=0.0)
    model.initialize()
    return mx.serve.Server(model, slots=SLOTS, page_size=8,
                           max_src_len=16, max_new_tokens=32,
                           max_queue=N_REQUESTS,
                           static_batching=static, engine_driven=True)


def _workload(seed=0, n=N_REQUESTS):
    import numpy as np
    rng = np.random.RandomState(seed)
    reqs = []
    for _ in range(n):
        src = rng.randint(4, 64, (int(rng.randint(4, 16)),))
        # mixed token budgets: the straggler spread static batching eats
        max_new = int(rng.choice([4, 8, 16, 32]))
        gap = float(rng.exponential(1.0 / RATE_HZ))
        reqs.append((src.astype(np.int32), max_new, gap))
    return reqs


def _run(policy_static, reqs):
    import numpy as np

    from mxnet_tpu import profiler

    srv = _build_server(policy_static)
    handles = []
    try:
        # warm outside the timed window: the first request compiles the
        # prefill + decode executables (seconds of XLA work that would
        # otherwise masquerade as queueing latency)
        srv.submit(np.arange(4, 12, dtype=np.int32),
                   max_new_tokens=4).result(timeout=300)
        turns0 = profiler.dispatch_count("serve_decode")
        t0 = time.perf_counter()
        for src, max_new, gap in reqs:
            time.sleep(gap)
            handles.append(srv.submit(src, max_new_tokens=max_new))
        for h in handles:
            h.result(timeout=300)
    finally:
        srv.close()
    wall = time.perf_counter() - t0
    lats = sorted(h.latency for h in handles)
    ttfts = sorted(h.ttft for h in handles)
    toks = sum(len(h.tokens) for h in handles)

    def pct(sorted_vals, q):
        i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
        return sorted_vals[i]

    return {
        "tokens": toks,
        "tokens_per_s": toks / wall,
        "wall_s": wall,
        "decode_turns": profiler.dispatch_count("serve_decode") - turns0,
        "p50_ms": pct(lats, 0.50) * 1e3,
        "p95_ms": pct(lats, 0.95) * 1e3,
        "p99_ms": pct(lats, 0.99) * 1e3,
        "ttft_p50_ms": pct(ttfts, 0.50) * 1e3,
    }


def measure_contended(reqs, qos=True):
    """One continuous-batching pass under the background-train flood
    (`bench_util.BackgroundEngineLoad`, the same generator the
    check_qos gate floods with), with or without priority scheduling
    (engine.set_qos)."""
    from mxnet_tpu import engine
    from bench_util import BackgroundEngineLoad

    prev = engine.set_qos(qos)
    try:
        with BackgroundEngineLoad(engine.num_workers() * 32, task_s=0.01):
            time.sleep(0.2)             # let the backlog build
            return _run(policy_static=False, reqs=reqs)
    finally:
        engine.set_qos(prev)
        engine.wait_for_all()


def _contended_fields(reqs):
    """The QoS-vs-FIFO contended arm, one pass each (the deterministic
    decode-turn witness makes repeats unnecessary): decode p99 while a
    background-train flood contends for the engine, with and without
    priority scheduling. One source for both the supervisor-contract
    fields in measure() and the standalone --background-train line."""
    qos = measure_contended(reqs, qos=True)
    fifo = measure_contended(reqs, qos=False)
    return {
        "p99_contended_ms": round(qos["p99_ms"], 2),
        "p99_contended_fifo_ms": round(fifo["p99_ms"], 2),
        "contended_p99_ratio_fifo_over_qos": round(
            fifo["p99_ms"] / max(qos["p99_ms"], 1e-9), 3),
        "tokens_per_s_contended": round(qos["tokens_per_s"], 2),
    }


def _build_fast_server(speculative_k=0, prefix_cache=True, **kw):
    """The fast-path server (ISSUE 12): prompt budget for the shared
    system prompts, optional speculative width. Same model/seed as the
    headline arms so the executables compare like for like. Extra
    keywords (kv_dtype / weight_dtype, ISSUE 14) pass through to
    `Server`."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.transformer import TransformerNMT

    mx.random.seed(7)
    model = TransformerNMT(64, units=32, hidden=64, num_layers=2,
                           num_heads=4, max_length=64, dropout=0.0)
    model.initialize()
    return mx.serve.Server(model, slots=SLOTS, page_size=8,
                           max_src_len=16, max_new_tokens=24,
                           max_prompt_len=32,
                           speculative_k=speculative_k,
                           prefix_cache=prefix_cache,
                           max_queue=N_REQUESTS, engine_driven=True,
                           **kw)


def _prefix_workload(seed=1, n=N_REQUESTS, templates=3):
    """Shared-system-prompt Poisson mix: every request draws one of
    `templates` (source, 24-token system prompt) pairs — the radix-
    shareable material — plus a short unique prompt suffix on some
    requests (partial-prefix hits) and a mixed generation budget."""
    import numpy as np
    rng = np.random.RandomState(seed)
    temps = [(rng.randint(4, 64, (int(rng.randint(6, 16)),)
                          ).astype(np.int32),
              rng.randint(4, 64, (24,)).astype(np.int32))
             for _ in range(templates)]
    reqs = []
    for _ in range(n):
        src, sys_prompt = temps[int(rng.randint(templates))]
        prompt = sys_prompt
        if rng.rand() < 0.4:
            prompt = np.concatenate(
                [sys_prompt,
                 rng.randint(4, 64, (int(rng.randint(1, 5)),))]
            ).astype(np.int32)
        max_new = int(rng.choice([4, 8, 16, 24]))
        gap = float(rng.exponential(1.0 / RATE_HZ))
        reqs.append((src, prompt, max_new, gap))
    return reqs


def _run_fast(reqs, speculative_k=0, prefix_cache=True, **kw):
    """One pass of the prompted trace; returns wall tokens/s plus the
    deterministic witnesses: decode turns, committed tokens, prefix hit
    rate, draft acceptance and the per-request token outputs (the
    accuracy-contract comparison material)."""
    srv = _build_fast_server(speculative_k=speculative_k,
                             prefix_cache=prefix_cache, **kw)
    handles = []
    try:
        # warm-up compiles prefill + (widened) decode outside the clock
        srv.submit(list(range(4, 12)), max_new_tokens=4,
                   prompt_tokens=list(range(4, 10))).result(timeout=300)
        sched = srv.scheduler
        turns0, toks0 = sched.decode_turns, sched.tokens_generated
        t0 = time.perf_counter()
        for src, prompt, max_new, gap in reqs:
            time.sleep(gap)
            handles.append(srv.submit(src, max_new_tokens=max_new,
                                      prompt_tokens=prompt))
        for h in handles:
            h.result(timeout=300)
        wall = time.perf_counter() - t0
        turns = sched.decode_turns - turns0
        toks = sched.tokens_generated - toks0
        cache = srv.prefix_cache
        hit_rate = (cache.hits / max(cache.hits + cache.misses, 1)
                    if cache is not None else 0.0)
        saved = cache.tokens_saved if cache is not None else 0
        accept = (sched.spec_accepted / max(sched.spec_drafted, 1)
                  if speculative_k else 0.0)
        outputs = [list(h.tokens) for h in handles]
    finally:
        srv.close()
    return {
        "tokens": toks,
        "tokens_per_s": toks / wall,
        "wall_s": wall,
        "decode_turns": turns,
        "turns_per_token": turns / max(toks, 1),
        "prefix_hit_rate": hit_rate,
        "prefix_tokens_saved": saved,
        "spec_accept_rate": accept,
        "outputs": outputs,
    }


def measure_fastpath(seed=1, repeats=2):
    """The ISSUE 12 arms. Prefix-heavy: the same shared-system-prompt
    trace warm (radix cache on) vs cold (cache disabled) — the headline
    is wall tokens/s speedup, with prefill-turns-saved as the
    deterministic witness. Speculative: the same trace with k=3 n-gram
    drafts per turn vs the 1-wide control — the witness is decode turns
    per committed token."""
    reqs = _prefix_workload(seed)
    warm = min((_run_fast(reqs, prefix_cache=True)
                for _ in range(repeats)), key=lambda r: r["wall_s"])
    cold = min((_run_fast(reqs, prefix_cache=False)
                for _ in range(repeats)), key=lambda r: r["wall_s"])
    spec = _run_fast(reqs, speculative_k=3, prefix_cache=True)
    return {
        "metric": "serve_fastpath",
        "unit": "tokens/sec",
        "value": round(warm["tokens_per_s"], 2),
        "requests": len(reqs),
        "prefix_hit_rate": round(warm["prefix_hit_rate"], 4),
        "prefix_tokens_saved": warm["prefix_tokens_saved"],
        "prefix_speedup": round(
            warm["tokens_per_s"] / max(cold["tokens_per_s"], 1e-9), 3),
        "cold_tokens_per_s": round(cold["tokens_per_s"], 2),
        "warm_decode_turns": warm["decode_turns"],
        "cold_decode_turns": cold["decode_turns"],
        "spec_accept_rate": round(spec["spec_accept_rate"], 4),
        "spec_turns_per_token": round(spec["turns_per_token"], 4),
        "control_turns_per_token": round(cold["turns_per_token"], 4),
        "spec_tokens_per_s": round(spec["tokens_per_s"], 2),
    }


def _token_match(ref_outputs, outputs):
    """Position-wise greedy token-match rate vs the fp32 reference
    (length mismatches count as mismatches) — the accuracy number every
    low-precision speed claim ships with (ISSUE 14)."""
    matched = total = 0
    for a, b in zip(ref_outputs, outputs):
        total += max(len(a), len(b))
        matched += sum(1 for x, y in zip(a, b) if x == y)
    return matched / max(total, 1)


def _logit_mse(kv_dtype=None, weight_dtype=None, steps=8, seed=5):
    """Teacher-forced decode-logit MSE vs the fp32 runtime: both
    runtimes prefill the same source and decode the same forced token
    sequence, so the per-position logits compare like for like."""
    import numpy as np

    def drive(srv):
        rng = np.random.RandomState(seed)
        src = rng.randint(4, 64, (8,)).astype(np.int32)
        toks = rng.randint(4, 64, (steps,)).astype(np.int32)
        rt = srv.runtime
        pool = srv.pool
        pages = pool.alloc(pool.pages_for(steps))
        tables = np.full((rt.slots, rt.max_pages_per_slot), 0, np.int32)
        tables[0, :len(pages)] = pages
        rt.prefill(0, src)
        active = np.zeros((rt.slots,), np.int32)
        active[0] = 1
        cur = np.zeros((rt.slots,), np.int32)
        lens = np.zeros((rt.slots,), np.int32)
        logits = []
        for t in range(steps):
            cur[0] = toks[t]
            lens[0] = t
            _, lg = rt.decode(tables, lens, cur, active)
            logits.append(np.asarray(lg[0], np.float64))
        pool.free(pages)
        srv.close()
        return np.stack(logits)

    ref = drive(_build_fast_server())
    got = drive(_build_fast_server(kv_dtype=kv_dtype,
                                   weight_dtype=weight_dtype))
    return float(np.mean((ref - got) ** 2))


def measure_int8kv(seed=2):
    """The ISSUE 14 arm: the same shared-system-prompt trace through an
    int8-KV server vs the fp32 twin. Headlines: wall tokens/s ratio
    (honest — on the CPU mesh the quantise/requantise work is not free,
    so the ratio can sit below 1; the bandwidth win needs a chip) and
    the CAPACITY witnesses (tokens + concurrent full-size requests a
    fixed HBM byte budget holds — deterministic, hardware-independent,
    ~3.5x vs fp32 pages). Every speed number ships with its accuracy
    contract: greedy token-match rate + teacher-forced logit MSE vs
    fp32."""
    from mxnet_tpu.serve.quant import kv_page_bytes, token_capacity

    reqs = _prefix_workload(seed)
    fp = _run_fast(reqs, prefix_cache=True)
    q = _run_fast(reqs, prefix_cache=True, kv_dtype="int8")
    match = _token_match(fp["outputs"], q["outputs"])
    mse = _logit_mse(kv_dtype="int8")
    # capacity at a fixed byte budget (the bench model's KV geometry:
    # 2 layers x 4 heads x 8 head-dim, page_size 8)
    geo = dict(n_layers=2, page_size=8, num_heads=4, head_dim=8)
    budget = 256 * kv_page_bytes(kv_dtype="float32", **geo)
    cap_fp = token_capacity(budget, kv_dtype="float32", **geo)
    cap_q = token_capacity(budget, kv_dtype="int8", **geo)
    return {
        "metric": "serve_int8_kv",
        "unit": "tokens/sec",
        "value": round(q["tokens_per_s"], 2),
        "fp_tokens_per_s": round(fp["tokens_per_s"], 2),
        "speedup_vs_fp": round(
            q["tokens_per_s"] / max(fp["tokens_per_s"], 1e-9), 3),
        "token_match": round(match, 4),
        "logit_mse": mse,
        "capacity_tokens_ratio": round(cap_q / cap_fp, 3),
        "tokens_at_budget_int8": cap_q,
        "tokens_at_budget_fp32": cap_fp,
        "concurrent_slots_int8": cap_q // (32 + 24),
        "concurrent_slots_fp32": cap_fp // (32 + 24),
        "decode_turns": q["decode_turns"],
        "fp_decode_turns": fp["decode_turns"],
    }


def measure(seed=0, repeats=2, background_train=True):
    """Best-of-`repeats` per policy: shared-box wall clocks are noisy at
    this scale, so each arm keeps its best run — and the DETERMINISTIC
    witness rides along: `decode_turns` (one shared dispatch per serving
    turn) is what continuous batching actually saves, independent of the
    scheduler's timing luck."""
    reqs = _workload(seed)
    cont = min((_run(policy_static=False, reqs=reqs)
                for _ in range(repeats)), key=lambda r: r["wall_s"])
    stat = min((_run(policy_static=True, reqs=reqs)
                for _ in range(repeats)), key=lambda r: r["wall_s"])
    contended = {}
    if background_train:
        try:
            contended = _contended_fields(reqs)
        except Exception as exc:
            # The contended arm runs AFTER cont/stat: a failure here must
            # not discard the uncontended serve fields already measured
            # (bench.py's per-field guard can then still see them).
            print(f"[bench_serve] contended arm failed: {exc!r}",
                  file=sys.stderr)
    return {
        "metric": "serve_throughput",
        "unit": "tokens/sec",
        "value": round(cont["tokens_per_s"], 2),
        "requests": len(reqs),
        "slots": SLOTS,
        "p50_ms": round(cont["p50_ms"], 2),
        "p95_ms": round(cont["p95_ms"], 2),
        "p99_ms": round(cont["p99_ms"], 2),
        "ttft_p50_ms": round(cont["ttft_p50_ms"], 2),
        "decode_turns": cont["decode_turns"],
        "static_tokens_per_s": round(stat["tokens_per_s"], 2),
        "static_p99_ms": round(stat["p99_ms"], 2),
        "static_decode_turns": stat["decode_turns"],
        "speedup_vs_static": round(
            cont["tokens_per_s"] / max(stat["tokens_per_s"], 1e-9), 3),
        "turns_ratio_vs_static": round(
            stat["decode_turns"] / max(cont["decode_turns"], 1), 3),
        **contended,
    }


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    if "--fastpath" in argv:
        # ISSUE 12 arms only: prefix-heavy warm-vs-cold + speculative
        print(json.dumps(measure_fastpath()), flush=True)
        return 0
    if "--int8-kv" in argv:
        # ISSUE 14 arm only: int8-KV tokens/s + capacity-at-fixed-budget
        # vs fp32, with the accuracy contract riding along
        print(json.dumps(measure_int8kv()), flush=True)
        return 0
    if "--background-train" in argv:
        # contended arm only: decode p99 under background-train load,
        # QoS vs FIFO
        fields = _contended_fields(_workload())
        print(json.dumps({
            "metric": "serve_p99_contended",
            "unit": "ms",
            "value": fields.pop("p99_contended_ms"),
            **fields,
        }), flush=True)
        return 0
    print(json.dumps(measure()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
