"""mx.sym.contrib — symbolic control flow (reference:
python/mxnet/symbol/contrib.py + src/operator/control_flow.cc).

The reference builds nnvm subgraph ops (_foreach/_while_loop/_cond) whose
bodies are cut-out symbol graphs with captured closure variables lifted to
extra op inputs. Same structure here: the body function is called once on
placeholder Variables to build the subgraph; free Variables (weights used
inside the body) are auto-captured as node inputs; evaluation lowers to ONE
`lax.scan` / masked scan / `lax.cond` inside the executor's XLA program —
the TPU-native form (static shapes, no Python unrolling).

Subgraph attrs serialize through `tojson` (nested graph JSON), so
control-flow graphs round-trip like any other symbol.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, _as_list
from .symbol import (Group, Symbol, Variable, _make, register_op,
                     register_shape_rule)

__all__ = ["foreach", "while_loop", "cond"]


def _sym_list(x, what):
    xs = _as_list(x) if x is not None else []
    for v in xs:
        if not isinstance(v, Symbol):
            raise MXNetError(f"{what} must be Symbol(s), got {type(v)}")
    return list(xs)


def _free_vars(heads, bound_names):
    """Variables used by `heads` that are not placeholders: the body's
    closure captures, lifted to op inputs (reference: _cut_subgraph)."""
    seen, out = set(), []
    for h in heads:
        for n in h._topo():
            if n._op is None and n.name not in bound_names \
                    and id(n) not in seen:
                seen.add(id(n))
                out.append(n)
    return out


def _pack(template, values):
    values = list(values)
    if not isinstance(template, (list, tuple)):
        return values[0] if len(values) == 1 else values
    return values


def _eval_heads(heads, values):
    return tuple(h._eval_with_values(values) for h in heads)


# ---------------------------------------------------------------------------
# foreach
# ---------------------------------------------------------------------------
def _foreach_eval(*arrays, sub_outs=None, in_names=None, n_data=0,
                  n_states=0, n_out=0):
    data = arrays[:n_data]
    states = arrays[n_data:n_data + n_states]
    caps = arrays[n_data + n_states:]
    cap_vals = dict(zip(in_names[n_data + n_states:], caps))

    def step(carry, xs):
        vals = dict(zip(in_names[:n_data], xs))
        vals.update(zip(in_names[n_data:n_data + n_states], carry))
        vals.update(cap_vals)
        outs = _eval_heads(sub_outs, vals)
        return tuple(outs[n_out:]), tuple(outs[:n_out])

    carry, ys = lax.scan(step, tuple(states), tuple(data))
    return tuple(ys) + tuple(carry)


register_op("_foreach", _foreach_eval)


def _subgraph_capture_shapes(ins, names, heads, bound_shapes):
    """Fill unknown capture shapes by running the SUBGRAPH's own shape
    inference from the known outer shapes (the reference runs nnvm
    InferShape on the subgraph the same way)."""
    g = Group(heads) if len(heads) > 1 else heads[0]
    arg_shapes, _, _ = g.infer_shape(**bound_shapes)
    if arg_shapes is None:
        return ins
    shape_of = dict(zip(g.list_arguments(), arg_shapes))
    return [s if s is not None else shape_of.get(names[k])
            for k, s in enumerate(ins)]


def _foreach_shapes(ins, attrs):
    names = attrs["in_names"]
    n_d, n_s = attrs["n_data"], attrs["n_states"]
    bind = {}
    for i, s in enumerate(ins):
        if s is not None:
            bind[names[i]] = tuple(s[1:]) if i < n_d else tuple(s)
    return _subgraph_capture_shapes(ins, names, attrs["sub_outs"], bind)


register_shape_rule("_foreach", _foreach_shapes)


def foreach(body, data, init_states, name="foreach"):
    """Symbolic scan: body(data_slice, states) -> (outputs, new_states),
    iterated over dim 0 of `data`, compiled to one `lax.scan`.

    Returns (outputs, final_states) — outputs stacked on a new dim 0.
    """
    data_list = _sym_list(data, "foreach data")
    state_list = _sym_list(init_states, "foreach init_states")
    if not data_list:
        raise MXNetError("foreach needs at least one data symbol")

    slice_vars = [Variable(f"__{name}_data{i}__")
                  for i in range(len(data_list))]
    state_vars = [Variable(f"__{name}_state{j}__")
                  for j in range(len(state_list))]
    outs, new_states = body(_pack(data, slice_vars),
                            _pack(init_states, state_vars))
    out_list = _sym_list(outs, "foreach outputs") if outs is not None else []
    new_state_list = _sym_list(new_states, "foreach states")
    if len(new_state_list) != len(state_list):
        raise MXNetError("foreach body must return as many states as given")

    placeholders = [v.name for v in slice_vars + state_vars]
    captures = _free_vars(out_list + new_state_list, set(placeholders))
    in_names = placeholders + [c.name for c in captures]
    n_out, n_states = len(out_list), len(state_list)

    node = _make("_foreach", data_list + state_list + list(captures),
                 {"sub_outs": out_list + new_state_list,
                  "in_names": in_names, "n_data": len(data_list),
                  "n_states": n_states, "n_out": n_out},
                 name=name, n_out=n_out + n_states)
    outs_syms = [node[i] for i in range(n_out)]
    state_syms = [node[n_out + j] for j in range(n_states)]
    outs_packed = [] if not out_list else (
        outs_syms[0] if not isinstance(outs, (list, tuple)) else outs_syms)
    return outs_packed, _pack(init_states, state_syms)


# ---------------------------------------------------------------------------
# while_loop
# ---------------------------------------------------------------------------
def _while_eval(*arrays, sub_cond=None, sub_outs=None, in_names=None,
                n_vars=0, n_out=0, max_iterations=0):
    vs = arrays[:n_vars]
    caps = dict(zip(in_names[n_vars:], arrays[n_vars:]))

    def probe(values):
        return _eval_heads(sub_outs, values)

    # output buffers sized from an abstract probe of one step
    vals0 = dict(zip(in_names[:n_vars], vs))
    vals0.update(caps)
    shapes = jax.eval_shape(lambda v: probe(v), vals0)

    bufs0 = tuple(jnp.zeros((max_iterations,) + s.shape, s.dtype)
                  for s in shapes[:n_out])

    def step(carry, i):
        cur, bufs, active = carry
        vals = dict(zip(in_names[:n_vars], cur))
        vals.update(caps)
        keep = jnp.logical_and(
            active,
            jnp.squeeze(_eval_heads([sub_cond], vals)[0]).astype(bool))

        def take(args):
            cur, bufs = args
            outs = probe(vals)
            new_bufs = tuple(
                lax.dynamic_update_index_in_dim(b, o, i, 0)
                for b, o in zip(bufs, outs[:n_out]))
            return tuple(outs[n_out:]), new_bufs

        new_cur, new_bufs = lax.cond(keep, take, lambda a: a, (cur, bufs))
        return (new_cur, new_bufs, keep), None

    (vs_f, bufs, _), _ = lax.scan(
        step, (tuple(vs), bufs0, jnp.bool_(True)),
        jnp.arange(max_iterations))
    return tuple(bufs) + tuple(vs_f)


register_op("_while_loop", _while_eval)


def _while_shapes(ins, attrs):
    names = attrs["in_names"]
    bind = {names[i]: tuple(s) for i, s in enumerate(ins) if s is not None}
    return _subgraph_capture_shapes(
        ins, names, [attrs["sub_cond"]] + attrs["sub_outs"], bind)


register_shape_rule("_while_loop", _while_shapes)


def while_loop(cond, func, loop_vars, max_iterations=None, name="while"):
    """Symbolic while: cond(*loop_vars) -> scalar Symbol;
    func(*loop_vars) -> (step_outputs, new_loop_vars). Outputs are padded
    to `max_iterations` rows (XLA static shapes, same contract as the
    reference symbolic while_loop)."""
    var_list = _sym_list(loop_vars, "while_loop loop_vars")
    if not var_list:
        raise MXNetError("while_loop needs at least one loop var")
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")

    vars_ph = [Variable(f"__{name}_var{i}__") for i in range(len(var_list))]
    cond_sym = cond(*vars_ph)
    outs, new_vars = func(*vars_ph)
    out_list = _sym_list(outs, "while outputs") if outs is not None else []
    new_var_list = _sym_list(new_vars, "while loop vars")
    if len(new_var_list) != len(var_list):
        raise MXNetError("while_loop func must return as many loop_vars")

    placeholders = [v.name for v in vars_ph]
    captures = _free_vars([cond_sym] + out_list + new_var_list,
                          set(placeholders))
    in_names = placeholders + [c.name for c in captures]
    n_out, n_vars = len(out_list), len(var_list)

    node = _make("_while_loop", var_list + list(captures),
                 {"sub_cond": cond_sym,
                  "sub_outs": out_list + new_var_list,
                  "in_names": in_names, "n_vars": n_vars, "n_out": n_out,
                  "max_iterations": int(max_iterations)},
                 name=name, n_out=n_out + n_vars)
    outs_syms = [node[i] for i in range(n_out)]
    var_syms = [node[n_out + j] for j in range(n_vars)]
    return (outs_syms[0] if n_out == 1 else outs_syms), \
        _pack(loop_vars, var_syms)


# ---------------------------------------------------------------------------
# cond
# ---------------------------------------------------------------------------
def _cond_eval(*arrays, sub_pred=None, sub_then=None, sub_else=None,
               in_names=None):
    vals = dict(zip(in_names, arrays))
    pred = jnp.squeeze(_eval_heads([sub_pred], vals)[0]).astype(bool)
    out = lax.cond(pred,
                   lambda v: _eval_heads(sub_then, v),
                   lambda v: _eval_heads(sub_else, v), vals)
    return tuple(out)


register_op("_cond", _cond_eval)


def _cond_shapes(ins, attrs):
    names = attrs["in_names"]
    bind = {names[i]: tuple(s) for i, s in enumerate(ins) if s is not None}
    return _subgraph_capture_shapes(
        ins, names, [attrs["sub_pred"]] + attrs["sub_then"]
        + attrs["sub_else"], bind)


register_shape_rule("_cond", _cond_shapes)


def cond(pred, then_func, else_func, name="cond"):
    """Symbolic branch: pred is a scalar Symbol; then/else are thunks
    returning Symbol(s) of matching shapes, lowered to `lax.cond` (both
    branches compiled, one executed on device)."""
    if not isinstance(pred, Symbol):
        raise MXNetError("cond pred must be a Symbol")
    then_list = _sym_list(then_func(), "cond then outputs")
    else_list = _sym_list(else_func(), "cond else outputs")
    if len(then_list) != len(else_list):
        raise MXNetError("cond branches must return the same arity")

    captures = _free_vars([pred] + then_list + else_list, set())
    in_names = [c.name for c in captures]
    n_out = len(then_list)
    node = _make("_cond", list(captures),
                 {"sub_pred": pred, "sub_then": then_list,
                  "sub_else": else_list, "in_names": in_names},
                 name=name, n_out=n_out)
    outs = [node[i] for i in range(n_out)]
    return outs[0] if n_out == 1 else outs


# ---------------------------------------------------------------------------
# transformer/NLP helper ops — symbol counterparts of ndarray.contrib's
# (reference: sym.contrib.interleaved_matmul_selfatt_* etc.), so
# hybrid_forward code calling F.contrib.<op> survives hybridize()/export.
# Kernels shared via the raw fns in ndarray/contrib.py's _apply closures
# would not serialise; these re-state the math as registered pure kernels.
# ---------------------------------------------------------------------------
import jax.numpy as _jnp


def _ileave_split(qkv, heads):
    s, b, hd3 = qkv.shape
    dh = hd3 // (3 * heads)

    def pick(i):
        x = qkv.reshape(s, b, heads, 3, dh)[:, :, :, i, :]
        return x.transpose(1, 2, 0, 3).reshape(b * heads, s, dh)
    return pick(0), pick(1), pick(2), dh


def _ileave_qk(qkv, heads=1):
    q, k, _v, dh = _ileave_split(qkv, heads)
    return _jnp.einsum("nqd,nkd->nqk", q, k) / _jnp.sqrt(
        _jnp.asarray(dh, qkv.dtype))


def _ileave_valatt(qkv, att, heads=1):
    s, b, _ = qkv.shape
    _q, _k, v, dh = _ileave_split(qkv, heads)
    out = _jnp.einsum("nqk,nkd->nqd", att, v)
    return out.reshape(b, heads, s, dh).transpose(2, 0, 1, 3) \
              .reshape(s, b, heads * dh)


register_op("_contrib_interleaved_matmul_selfatt_qk", _ileave_qk)
register_op("_contrib_interleaved_matmul_selfatt_valatt", _ileave_valatt)
register_op("_contrib_div_sqrt_dim",
            lambda x: x / _jnp.sqrt(_jnp.asarray(x.shape[-1], x.dtype)))


def _arange_like_k(x, start=0.0, step=1.0, repeat=1, axis=None):
    def ramp(n):
        count = -(-n // repeat)
        vals = start + step * _jnp.arange(count, dtype=x.dtype)
        return _jnp.repeat(vals, repeat)[:n]
    if axis is None:
        return ramp(x.size).reshape(x.shape)
    return ramp(x.shape[axis])


register_op("_contrib_arange_like", _arange_like_k)


def interleaved_matmul_selfatt_qk(queries_keys_values, heads, name=None):
    return _make("_contrib_interleaved_matmul_selfatt_qk",
                 [queries_keys_values], {"heads": heads}, name=name)


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads, name=None):
    return _make("_contrib_interleaved_matmul_selfatt_valatt",
                 [queries_keys_values, attention], {"heads": heads},
                 name=name)


def div_sqrt_dim(data, name=None):
    return _make("_contrib_div_sqrt_dim", [data], {}, name=name)


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, name=None):
    return _make("_contrib_arange_like", [data],
                 {"start": start, "step": step, "repeat": repeat,
                  "axis": axis}, name=name)


__all__ += ["interleaved_matmul_selfatt_qk",
            "interleaved_matmul_selfatt_valatt", "div_sqrt_dim",
            "arange_like"]


# ---------------------------------------------------------------------------
# detection / vision contrib ops (upstream: src/operator/contrib/ — see
# ops/contrib_ops.py for the TPU kernel designs). Registered as pure
# kernels so graphs using them serialise/round-trip like any other op.
# ---------------------------------------------------------------------------
from ..ops import detection_ops as _det
from ..ops import contrib_ops as _cops


del _det  # kernels live in ops/contrib_ops.py; nothing here uses _det


def _roi_align_k(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
                 sample_ratio=2):
    return _cops.roi_align_batched(
        data, rois, pooled_size=tuple(pooled_size),
        spatial_scale=spatial_scale, sample_ratio=max(int(sample_ratio), 1))


def _box_nms_k(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
               coord_start=2, score_index=1, id_index=-1, background_id=-1,
               force_suppress=False):
    return _cops.box_nms(
        data, overlap_thresh=overlap_thresh, valid_thresh=valid_thresh,
        topk=int(topk), coord_start=int(coord_start),
        score_index=int(score_index), id_index=int(id_index),
        background_id=int(background_id),
        force_suppress=bool(force_suppress))


def _box_iou_k(lhs, rhs, format="corner"):
    return _cops.box_iou_generic(lhs, rhs, format=format)


def _multibox_prior_k(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                      offsets=(0.5, 0.5), steps=(-1.0, -1.0)):
    return _cops.multibox_prior_k(data, sizes=tuple(sizes),
                                  ratios=tuple(ratios), clip=bool(clip),
                                  offsets=tuple(offsets),
                                  steps=tuple(steps))


def _multibox_target_k(anchor, label, cls_pred, overlap_threshold=0.5,
                       variances=(0.1, 0.1, 0.2, 0.2)):
    return _cops.multibox_target_k(anchor, label, cls_pred,
                                   overlap_threshold=overlap_threshold,
                                   variances=tuple(variances))


def _multibox_detection_k(cls_prob, loc_pred, anchor, threshold=0.01,
                          nms_threshold=0.45, nms_topk=400, max_det=100,
                          variances=(0.1, 0.1, 0.2, 0.2)):
    return _cops.multibox_detection_k(
        cls_prob, loc_pred, anchor, threshold=threshold,
        nms_threshold=nms_threshold, nms_topk=int(nms_topk),
        max_det=int(max_det), variances=tuple(variances))


def _multi_proposal_k(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                      rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                      scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                      feature_stride=16):
    rois, _scores = _cops.multi_proposal(
        cls_prob, bbox_pred, im_info,
        rpn_pre_nms_top_n=int(rpn_pre_nms_top_n),
        rpn_post_nms_top_n=int(rpn_post_nms_top_n), threshold=threshold,
        rpn_min_size=rpn_min_size, scales=tuple(scales),
        ratios=tuple(ratios), feature_stride=int(feature_stride))
    return rois


def _deformable_conv_k(*arrs, kernel=(3, 3), stride=(1, 1), dilate=(1, 1),
                       pad=(0, 0), num_group=1, num_deformable_group=1):
    data, offset, weight = arrs[:3]
    bias = arrs[3] if len(arrs) > 3 else None
    return _cops.deformable_convolution(
        data, offset, weight, bias=bias, kernel=tuple(kernel),
        stride=tuple(stride), dilate=tuple(dilate), pad=tuple(pad),
        num_group=int(num_group),
        num_deformable_group=int(num_deformable_group))


def _count_sketch_k(data, h, s, out_dim=0):
    return _cops.count_sketch(data, h, s, int(out_dim))


register_op("_contrib_ROIAlign", _roi_align_k)
register_op("_contrib_box_nms", _box_nms_k)
register_op("_contrib_box_iou", _box_iou_k)
register_op("_contrib_MultiBoxPrior", _multibox_prior_k)
register_op("_contrib_MultiBoxTarget", _multibox_target_k)
register_op("_contrib_MultiBoxDetection", _multibox_detection_k)
register_op("_contrib_MultiProposal", _multi_proposal_k)
register_op("_contrib_DeformableConvolution", _deformable_conv_k)
register_op("_contrib_fft", lambda x, compute_size=128: _cops.fft(x))
register_op("_contrib_ifft", lambda x, compute_size=128: _cops.ifft(x))
register_op("_contrib_count_sketch", _count_sketch_k)


def ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
             sample_ratio=2, name=None, **kw):
    return _make("_contrib_ROIAlign", [data, rois],
                 {"pooled_size": list(pooled_size),
                  "spatial_scale": spatial_scale,
                  "sample_ratio": sample_ratio}, name=name)


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, name=None, **kw):
    return _make("_contrib_box_nms", [data],
                 {"overlap_thresh": overlap_thresh,
                  "valid_thresh": valid_thresh, "topk": topk,
                  "coord_start": coord_start, "score_index": score_index,
                  "id_index": id_index, "background_id": background_id,
                  "force_suppress": force_suppress}, name=name)


box_non_maximum_suppression = box_nms


def box_iou(lhs, rhs, format="corner", name=None, **kw):
    return _make("_contrib_box_iou", [lhs, rhs], {"format": format},
                 name=name)


def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5), name=None, **kw):
    return _make("_contrib_MultiBoxPrior", [data],
                 {"sizes": list(sizes), "ratios": list(ratios),
                  "clip": clip, "offsets": list(offsets),
                  "steps": list(steps)}, name=name)


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   variances=(0.1, 0.1, 0.2, 0.2), name=None, **kw):
    return _make("_contrib_MultiBoxTarget", [anchor, label, cls_pred],
                 {"overlap_threshold": overlap_threshold,
                  "variances": list(variances)}, name=name, n_out=3)


def MultiBoxDetection(cls_prob, loc_pred, anchor, threshold=0.01,
                      nms_threshold=0.45, nms_topk=400, max_det=100,
                      variances=(0.1, 0.1, 0.2, 0.2), name=None, **kw):
    return _make("_contrib_MultiBoxDetection", [cls_prob, loc_pred, anchor],
                 {"threshold": threshold, "nms_threshold": nms_threshold,
                  "nms_topk": nms_topk, "max_det": max_det,
                  "variances": list(variances)}, name=name)


def MultiProposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                  rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                  scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                  feature_stride=16, name=None, **kw):
    return _make("_contrib_MultiProposal", [cls_prob, bbox_pred, im_info],
                 {"rpn_pre_nms_top_n": rpn_pre_nms_top_n,
                  "rpn_post_nms_top_n": rpn_post_nms_top_n,
                  "threshold": threshold, "rpn_min_size": rpn_min_size,
                  "scales": list(scales), "ratios": list(ratios),
                  "feature_stride": feature_stride}, name=name)


Proposal = MultiProposal


def DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3),
                          stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                          num_filter=0, num_group=1, num_deformable_group=1,
                          no_bias=False, name=None, **kw):
    ins = [data, offset, weight]
    if bias is not None and not no_bias:
        ins.append(bias)
    return _make("_contrib_DeformableConvolution", ins,
                 {"kernel": list(kernel), "stride": list(stride),
                  "dilate": list(dilate), "pad": list(pad),
                  "num_group": num_group,
                  "num_deformable_group": num_deformable_group}, name=name)


def fft(data, compute_size=128, name=None, **kw):
    return _make("_contrib_fft", [data], {"compute_size": compute_size},
                 name=name)


def ifft(data, compute_size=128, name=None, **kw):
    return _make("_contrib_ifft", [data], {"compute_size": compute_size},
                 name=name)


def count_sketch(data, h, s, out_dim, name=None, **kw):
    return _make("_contrib_count_sketch", [data, h, s],
                 {"out_dim": out_dim}, name=name)


from ..ops import extra_ops as _extra

register_op("_contrib_AdaptiveAvgPooling2D",
            lambda x, output_size=1:
            _extra.adaptive_avg_pool2d_k(x, output_size))
register_op("_contrib_BilinearResize2D",
            lambda x, height=0, width=0, scale_height=0.0, scale_width=0.0:
            _extra.bilinear_resize_k(
                x, *_extra._resize_target(x.shape, height, width,
                                          scale_height, scale_width)))


def AdaptiveAvgPooling2D(data, output_size=1, name=None, **kw):
    """reference: contrib.AdaptiveAvgPooling2D (adaptive_avg_pooling.cc)."""
    out = (list(output_size) if isinstance(output_size, (tuple, list))
           else int(output_size))
    return _make("_contrib_AdaptiveAvgPooling2D", [data],
                 {"output_size": out}, name=name)


def BilinearResize2D(data, height=None, width=None, scale_height=None,
                     scale_width=None, name=None, **kw):
    """reference: contrib.BilinearResize2D (bilinear_resize.cc);
    explicit height/width, or the scale_height/scale_width mode."""
    if not (height and width) and not (scale_height and scale_width):
        raise MXNetError("BilinearResize2D: need height+width or "
                         "scale_height+scale_width")
    return _make("_contrib_BilinearResize2D", [data],
                 {"height": int(height or 0), "width": int(width or 0),
                  "scale_height": float(scale_height or 0.0),
                  "scale_width": float(scale_width or 0.0)}, name=name)


# -- op-level quantization (reference: src/operator/quantization/*.cc) ------
register_op("_contrib_quantize",
            lambda x, a, b, out_type="uint8":
            _cops.quantize(x, a, b, out_type), )
register_op("_contrib_quantize_v2",
            lambda x, out_type="int8", min_calib_range=None,
            max_calib_range=None:
            _cops.quantize_v2(x, out_type, min_calib_range,
                              max_calib_range))
register_op("_contrib_dequantize",
            lambda q, a, b, out_type="float32":
            _cops.dequantize(q, a, b, out_type))
register_op("_contrib_requantize",
            lambda q, a, b, min_calib_range=None, max_calib_range=None:
            _cops.requantize(q, a, b, min_calib_range, max_calib_range))


def quantize(data, min_range, max_range, out_type="uint8", name=None,
             **kw):
    """reference: quantize.cc — (q, out_min, out_max), range as inputs."""
    return _make("_contrib_quantize", [data, min_range, max_range],
                 {"out_type": out_type}, name=name, n_out=3)


def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None, name=None, **kw):
    """reference: quantize_v2.cc — calibration ranges as attrs."""
    return _make("_contrib_quantize_v2", [data],
                 {"out_type": out_type,
                  "min_calib_range": min_calib_range,
                  "max_calib_range": max_calib_range}, name=name, n_out=3)


def dequantize(data, min_range, max_range, out_type="float32", name=None,
               **kw):
    """reference: dequantize.cc."""
    return _make("_contrib_dequantize", [data, min_range, max_range],
                 {"out_type": out_type}, name=name)


def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None, name=None, **kw):
    """reference: requantize.cc — int32 -> int8 under a new range."""
    return _make("_contrib_requantize", [data, min_range, max_range],
                 {"min_calib_range": min_calib_range,
                  "max_calib_range": max_calib_range}, name=name, n_out=3)


def _qfc_eval(xq, wq, *rest, num_hidden=None, no_bias=False):
    b, ranges = _cops.split_quantized_bias(rest)
    return _cops.quantized_fully_connected(xq, wq, b, *ranges,
                                           num_hidden=num_hidden)


def _qconv_eval(xq, wq, *rest, stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                layout="NCHW", no_bias=False, **akw):
    b, ranges = _cops.split_quantized_bias(rest)
    return _cops.quantized_conv(xq, wq, b, *ranges, stride=stride,
                                pad=pad, dilate=dilate, layout=layout)


register_op("_contrib_quantized_fully_connected", _qfc_eval)
register_op("_contrib_quantized_conv", _qconv_eval)
register_op("_contrib_quantized_pooling",
            lambda q, a, b, kernel=(2, 2), pool_type="max", stride=None,
            pad=(0, 0), layout="NCHW":
            _cops.quantized_pooling(
                q, a, b, kernel=tuple(kernel), pool_type=pool_type,
                stride=None if stride is None else tuple(stride),
                pad=tuple(pad), layout=layout))
register_op("_contrib_quantized_flatten", _cops.quantized_flatten)


def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, num_hidden=None,
                              no_bias=False, name=None, **kw):
    """reference: quantized_fully_connected.cc."""
    ins = [data, weight] + ([] if no_bias or bias is None else [bias]) \
        + [min_data, max_data, min_weight, max_weight]
    return _make("_contrib_quantized_fully_connected", ins,
                 {"num_hidden": num_hidden, "no_bias": no_bias},
                 name=name, n_out=3)


def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, kernel=None, stride=(1, 1), pad=(0, 0),
                   dilate=(1, 1), num_filter=None, layout="NCHW",
                   no_bias=False, name=None, **kw):
    """reference: quantized_conv.cc."""
    ins = [data, weight] + ([] if no_bias or bias is None else [bias]) \
        + [min_data, max_data, min_weight, max_weight]
    def _l2(v):
        return [v, v] if isinstance(v, int) else list(v)
    return _make("_contrib_quantized_conv", ins,
                 {"stride": _l2(stride), "pad": _l2(pad),
                  "dilate": _l2(dilate), "layout": layout,
                  "no_bias": no_bias}, name=name, n_out=3)


def quantized_pooling(data, min_range, max_range, kernel=(2, 2),
                      pool_type="max", stride=None, pad=(0, 0),
                      layout="NCHW", name=None, **kw):
    """reference: quantized_pooling.cc."""
    def _l2(v):
        return [v, v] if isinstance(v, int) else list(v)
    return _make("_contrib_quantized_pooling",
                 [data, min_range, max_range],
                 {"kernel": _l2(kernel), "pool_type": pool_type,
                  "stride": None if stride is None else _l2(stride),
                  "pad": _l2(pad), "layout": layout}, name=name, n_out=3)


def quantized_flatten(data, min_range, max_range, name=None, **kw):
    """reference: quantized_flatten.cc."""
    return _make("_contrib_quantized_flatten",
                 [data, min_range, max_range], {}, name=name, n_out=3)


__all__ += ["ROIAlign", "box_nms", "box_non_maximum_suppression", "box_iou",
            "MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection",
            "Proposal", "MultiProposal", "DeformableConvolution",
            "fft", "ifft", "count_sketch", "AdaptiveAvgPooling2D",
            "BilinearResize2D", "quantize", "quantize_v2", "dequantize",
            "requantize", "quantized_fully_connected", "quantized_conv",
            "quantized_pooling", "quantized_flatten"]
