"""All-to-all (Ulysses-style) sequence parallelism over the 'sp' axis.

The second long-context strategy alongside `ring_attention` (the build
brief asks for ring OR all-to-all context parallelism; this framework
ships both — they trade differently):

  * ring: K/V rotate around the ICI ring, O(S/P) memory, P ppermute steps,
    best when S is huge and heads are few;
  * all-to-all (Ulysses, DeepSpeed-style): one stacked `lax.all_to_all`
    swaps the sharded dimension — sequence-sharded q/k/v
    (B, S/P, H, Dh) become head-sharded full-sequence blocks
    (B, S, H/P, Dh) in a single collective over the stacked triple —
    every device runs ordinary full attention (the Pallas flash kernel)
    for its head subset, and one reverse all-to-all restores sequence
    sharding. Communication volume: 4 activation-sized tensors per
    forward (q+k+v in, out back), independent of P, and the attention
    itself needs NO cross-device math — best when H >= P and the
    interconnect does all-to-all well (TPU ICI does).

Both compose with the same outer sharding: inputs/outputs are
sequence-sharded, so either can drop into a tp/dp program unchanged.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from ..jax_compat import shard_map
from ..jax_compat import axis_size as _axis_size
from jax.sharding import PartitionSpec as P

from ..ops.pallas_kernels import flash_block_attention

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None):
    """Call INSIDE shard_map with q/k/v sequence-sharded: (B, S/P, H, Dh).
    Requires H divisible by the axis size. Returns (B, S/P, H, Dh)."""
    p = _axis_size(axis_name)
    b, s_loc, h, dh = q.shape
    if h % p:
        raise ValueError(f"ulysses_attention: heads {h} not divisible by "
                         f"axis {axis_name!r} size {p}")

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    # ONE stacked collective for q/k/v instead of three back-to-back
    # all_to_alls (collective launch latency dominates at small shards):
    # (3, B, S/P, H, Dh) -> split heads (axis 3), gather sequence (axis 2)
    qkv = jnp.stack([q, k, v])
    qkv = lax.all_to_all(qkv, axis_name, split_axis=3, concat_axis=2,
                         tiled=True)
    qh, kh, vh = qkv[0], qkv[1], qkv[2]
    # local FULL attention over this device's head subset; flash kernel
    # wants (B, H, S, Dh)
    qt = jnp.swapaxes(qh, 1, 2)
    kt = jnp.swapaxes(kh, 1, 2)
    vt = jnp.swapaxes(vh, 1, 2)
    out, _lse = flash_block_attention(qt, kt, vt, causal, sm_scale)
    out = jnp.swapaxes(out, 1, 2)            # (B, S, H/P, Dh)
    return head_to_seq(out)                   # (B, S/P, H, Dh)


def ulysses_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False):
    """Convenience wrapper: shard (B, S, H, Dh) arrays over S and run the
    all-to-all attention."""
    spec = P(None, axis_name, None, None)
    f = shard_map(
        functools.partial(ulysses_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)
