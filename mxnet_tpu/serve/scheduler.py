"""Continuous (inflight) batching scheduler (ISSUE 6).

Every `step()` is one turn of the serving crank:

  1. ADMIT — pop queued requests into free decode slots while pages are
     available (all-or-nothing first-page grant), running the cached
     prefill executable per admission;
  2. DECODE — one shared decode dispatch for ALL active slots (mixed
     lengths share the ragged-paged-attention launch), growing each
     active request by one token and one cache position, allocating a
     fresh page exactly when a request crosses a page boundary;
  3. EVICT — requests that emitted EOS or hit their token budget leave
     their slot and return every page to the pool immediately, so the
     NEXT step can admit into the freed capacity. No drain barriers:
     short requests never wait for long ones (`static_batching=True`
     flips exactly this off — admission only into an EMPTY batch — and is
     the baseline `bench_serve.py` beats).

Backpressure: the admission queue is bounded (`max_queue`); a submit into
a full queue raises `ServeOverloaded` (counted) instead of buffering
unboundedly. A request that cannot get its next page mid-decode is
PREEMPTED — pages freed, requeued at the front — rather than deadlocking
the pool (`serve_page_preemptions`).

The serving fast path (ISSUE 12) stacks two optimisations on the same
crank:

  * PREFIX CACHE — requests may carry a decoder-side `prompt_tokens`
    sequence (system prompt / few-shot template) that is teacher-forced
    into the paged KV cache before generation. Full prompt pages are
    indexed in a content-hashed radix tree (`prefix_cache.PrefixCache`);
    a later request with the same source and a matching prompt prefix
    ADOPTS those pages (refcounted sharing, never a copy) and skips that
    part of prefill. Under page pressure admission evicts LRU cache-only
    pages instead of failing (`serve_prefix_evictions`).
  * SPECULATIVE DECODING — with `width > 1` (Server(speculative_k=k)),
    each turn drafts up to k tokens by n-gram prompt lookup over the
    request's own committed history and verifies the whole window with
    ONE pass through the widened decode executable; the accepted run +
    one corrective token commit together. Greedy output is IDENTICAL to
    the 1-wide loop — drafts only change how many turns it takes.

Fault discipline (fault/injection.py points `serve.admit` /
`serve.decode` / `serve.prefix` / `serve.speculate`): an admit-time
fault fails ONLY the request being admitted. A decode-time fault kills
the whole in-flight batch — every active request frees its pages and is
retried from scratch (bounded by `max_retries`) or failed cleanly;
either way `kv_pages_in_use` returns to baseline (the chaos test
asserts this). An error raised by the decode executable itself
additionally resets the page pools AND clears the prefix cache (their
contents are no longer trustworthy after a partial in-place step). A
`serve.prefix` or `serve.speculate` fault merely DEGRADES — cache
lookup/insert skipped, turn runs unspeculated — with bitwise-identical
request output.
"""
from __future__ import annotations

import collections
import threading
import time

from ..base import MXNetError
from ..fault import injection as _finj
from ..observability import registry as _obs_registry
from ..observability import tracer as _tracer
from .decode import MemoryStateLost
from .kv_pages import NULL_PAGE, PageAllocError
from .prefix_cache import PrefixCache, content_key
from .speculate import propose_ngram

__all__ = ["Request", "Scheduler", "ServeError", "ServeOverloaded",
           "ServeDeadlineExceeded", "StepResult"]

_STREAM_END = object()


class ServeError(MXNetError):
    """A request failed inside the serving engine."""


class ServeOverloaded(ServeError):
    """Admission queue full — backpressure; retry later."""


class ServeDeadlineExceeded(ServeError):
    """The request's `deadline_ms` elapsed before it finished: it was
    evicted (queued or mid-decode), its pages freed, and
    `serve_deadline_expired` counted it."""


class Request:
    """One inference request + its result/stream plumbing. Create via
    `Server.submit`; consume via `.result()` / `.stream()` / `.tokens`."""

    def __init__(self, rid, src, max_new_tokens, prompt=None,
                 deadline_ms=None):
        self.id = rid
        self.src = src
        # decoder-side prompt (ISSUE 12): tokens teacher-forced into the
        # paged KV cache before free-running generation — the shared-
        # system-prompt material the radix prefix cache deduplicates
        self.prompt = [] if prompt is None else [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        # absolute monotonic deadline: survives retries/preemptions (the
        # budget is end-to-end, not per-attempt)
        self.deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        self.state = "queued"       # queued|running|done|failed
        self.tokens = []            # generated ids (EOS included if hit)
        self.error = None
        self._exc = None            # typed failure (ServeDeadlineExceeded)
        self.retries = 0            # fault retries (budget: max_retries)
        self.preemptions = 0        # page-pressure requeues (own budget)
        self.t_submit = time.perf_counter()
        self.t_first_token = None
        self.t_done = None
        self._slot = None
        self._pages = []
        self.known = None           # [BOS] + prompt + committed tokens
        self._n_table = 0           # valid page-table entries this attempt
        self._cache_done = False    # prompt pages offered to the cache
        self.prompt_cached_tokens = 0   # adopted prefix length (positions)
        self._content_key = None    # memoized source hash (Scheduler)
        self._admit_bypassed = 0    # warm-preference skips of THIS head
        self._done = threading.Event()
        self._chunks = collections.deque()  # streamed tokens + sentinel
        self._chunk_cv = threading.Condition()
        self._inline_sched = None   # set by Server(engine_driven=False)
        self._on_finish = None      # one-shot scheduler bookkeeping hook

    # ------------------------------------------------------- consumer
    @property
    def ttft(self):
        """Seconds from submit to first generated token (None until)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def latency(self):
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the request finishes; returns the generated token
        list, or raises `ServeError` if it failed. In inline mode
        (Server(engine_driven=False)) this call cranks the scheduler,
        still honouring the deadline."""
        wait_timeout = timeout
        if self._inline_sched is not None:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while not self._done.is_set():
                if deadline is not None and time.monotonic() > deadline:
                    break
                self._inline_sched.step()
            if deadline is not None:
                # the crank spent (part of) the budget; only the
                # remainder may be slept away below
                wait_timeout = max(0.0, deadline - time.monotonic())
        if not self._done.wait(wait_timeout):
            raise ServeError(f"request {self.id} timed out after "
                             f"{timeout}s")
        if self.state == "failed":
            if self._exc is not None:
                raise self._exc
            raise ServeError(f"request {self.id} failed: {self.error}")
        return list(self.tokens)

    def stream(self, timeout=None):
        """Yield generated token ids as they are produced; raises
        `ServeError` at the end if the request failed. `timeout` bounds
        the wait for EACH token (inline mode cranks the scheduler up to
        that per-token deadline)."""
        while True:
            with self._chunk_cv:
                item = self._chunks.popleft() if self._chunks else None
            if item is None:
                if self._inline_sched is not None:
                    deadline = None if timeout is None \
                        else time.monotonic() + timeout
                    while True:
                        with self._chunk_cv:
                            if self._chunks:
                                break
                        if deadline is not None and \
                                time.monotonic() > deadline:
                            raise ServeError(
                                f"request {self.id}: no token within "
                                f"{timeout}s")
                        self._inline_sched.step()
                    continue
                with self._chunk_cv:
                    while not self._chunks:
                        if not self._chunk_cv.wait(timeout):
                            raise ServeError(
                                f"request {self.id}: no token within "
                                f"{timeout}s")
                    item = self._chunks.popleft()
            if item is _STREAM_END:
                if self.state == "failed":
                    if self._exc is not None:
                        raise self._exc
                    raise ServeError(
                        f"request {self.id} failed: {self.error}")
                return
            yield item

    # ------------------------------------------------------- producer
    def _emit(self, tok):
        self.tokens.append(tok)
        with self._chunk_cv:
            self._chunks.append(tok)
            self._chunk_cv.notify_all()

    def _finish(self, state, error=None):
        self.state = state
        self.error = error
        self.t_done = time.perf_counter()
        cb, self._on_finish = self._on_finish, None
        if cb is not None:
            cb()
        with self._chunk_cv:
            self._chunks.append(_STREAM_END)
            self._chunk_cv.notify_all()
        self._done.set()


class StepResult:
    """What one scheduler turn did (truthy = progress was made)."""
    __slots__ = ("admitted", "decoded", "completed", "preempted", "retried")

    def __init__(self, admitted=0, decoded=0, completed=0, preempted=0,
                 retried=0):
        self.admitted = admitted
        self.decoded = decoded
        self.completed = completed
        self.preempted = preempted
        self.retried = retried

    def __bool__(self):
        return bool(self.admitted or self.decoded)


class Scheduler:
    def __init__(self, runtime, pool, bos_id=2, eos_id=3, max_queue=64,
                 max_retries=1, max_preemptions=8, static_batching=False,
                 prefix_cache=True, spec_ngram=2, quant_fallback=None):
        import numpy as np
        self._np = np
        self._rt = runtime
        self._pool = pool
        # speculative decoding rides the runtime's widened executable:
        # width = spec_k + 1 (window = current token + k drafts)
        self.width = int(getattr(runtime, "width", 1))
        self.spec_k = self.width - 1
        self.spec_ngram = int(spec_ngram)
        if prefix_cache is True:
            self._cache = PrefixCache(pool)
        elif prefix_cache:
            self._cache = prefix_cache      # caller-supplied instance
        else:
            self._cache = None
        self.bos_id = int(bos_id)
        self.eos_id = int(eos_id)
        self.max_queue = int(max_queue)
        self.max_retries = int(max_retries)
        # page-pressure preemptions are legitimate queueing, not faults —
        # they get their own (laxer) restart budget so transient capacity
        # pressure cannot burn a request's fault retries
        self.max_preemptions = int(max_preemptions)
        self.static_batching = bool(static_batching)
        # low-precision degradation path (ISSUE 14): on a `serve.quant`
        # fault, a quantized server routes THAT request through this
        # full-precision callback instead of the int8 executables —
        # identical greedy output to an fp32 server, no pages touched
        self._quant_fallback = quant_fallback
        s = runtime.slots
        self._slots = [None] * s                       # Request per slot
        self._page_tables = np.full(
            (s, runtime.max_pages_per_slot), NULL_PAGE, np.int32)
        self._lens = np.zeros((s,), np.int32)
        self._queue = collections.deque()
        self._lock = threading.Lock()
        # live admitted requests carrying a deadline — gates the per-turn
        # expiry sweep so deadline-free workloads never pay the O(queue)
        # scan (same idiom as engine._admit's _deadline_queued gate)
        self._deadline_live = 0
        self._deadline_lock = threading.Lock()
        # serialises whole turns: step() (engine loop or inline result()
        # cranks from several threads), defrag()'s device remap, and
        # shutdown() must never interleave mid-turn
        self._step_lock = threading.Lock()
        self._next_id = 0
        self.tokens_generated = 0   # per-instance (the registry counter
                                    # below is process-global)
        reg = _obs_registry()
        self._m_queue = reg.gauge("serve_queue_depth")
        self._m_queue.set(0)
        self._m_active = reg.gauge("serve_active_slots")
        self._m_active.set(0)
        self._m_tokens = reg.counter("serve_tokens")
        self._m_ok = reg.counter("serve_requests", result="ok")
        self._m_failed = reg.counter("serve_requests", result="failed")
        self._m_rejected = reg.counter("serve_requests", result="rejected")
        self._m_retries = reg.counter("serve_decode_retries")
        self._m_preempt = reg.counter("serve_page_preemptions")
        self._m_deadline = reg.counter("serve_deadline_expired")
        self._m_ttft = reg.histogram("serve_ttft_seconds")
        self._m_latency = reg.histogram("serve_request_seconds")
        self._m_step = reg.histogram("serve_decode_step_seconds")
        # speculative decoding telemetry (ISSUE 12): the acceptance
        # distribution is the regression signal — profiler.dumps() shows
        # it as a [serve-spec] row
        self._m_spec_hist = reg.histogram("serve_spec_accepted_tokens")
        self._m_spec_drafted = reg.counter("serve_spec_drafted")
        self._m_spec_accepted = reg.counter("serve_spec_accepted")
        self._m_spec_degraded = reg.counter("serve_spec_degraded")
        self._m_prefix_degraded = reg.counter("serve_prefix_degraded")
        self._m_quant_degraded = reg.counter("serve_quant_degraded")
        self._m_warm_pref = reg.counter("serve_prefix_admit_preferred")
        # per-instance tallies (registry counters are process-global)
        self.decode_turns = 0
        self.spec_drafted = 0
        self.spec_accepted = 0

    # ------------------------------------------------------------ API
    @property
    def prefix_cache(self):
        """The radix prefix cache (None when disabled)."""
        return self._cache

    def submit(self, src_tokens, max_new_tokens, prompt_tokens=None,
               deadline_ms=None):
        """Enqueue a request; returns the `Request` handle. Raises
        `ServeOverloaded` when the bounded admission queue is full and
        `ServeError` when the `serve.admit` fault point fires.
        `prompt_tokens` (ISSUE 12) is a decoder-side prompt teacher-
        forced before generation begins — its full KV pages are shared
        through the radix prefix cache, so a later request with the same
        source and a matching prompt prefix adopts them and skips that
        part of prefill. `deadline_ms` bounds the request END-TO-END
        (queue wait included): once it elapses the request is evicted
        wherever it is — queued or mid-decode — with
        `ServeDeadlineExceeded`, its pages freed and
        `serve_deadline_expired` counting the eviction."""
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        prompt = [] if prompt_tokens is None else [
            int(t) for t in self._np.asarray(prompt_tokens,
                                             self._np.int32).reshape(-1)]
        budget = self._rt.max_pages_per_slot * self._rt.page_size
        if len(prompt) + max_new > budget:
            raise MXNetError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the per-slot page budget "
                f"({self._rt.max_pages_per_slot} pages x "
                f"{self._rt.page_size})")
        need = self._pool.pages_for(len(prompt) + max_new)
        if need > self._pool.capacity:
            # doomed even with the pool to itself: reject at submit time
            # instead of burning prefills + retries on guaranteed
            # mid-decode page exhaustion
            raise MXNetError(
                f"prompt + max_new_tokens ({len(prompt)} + {max_new}) "
                f"needs {need} pages but the pool only has "
                f"{self._pool.capacity} total")
        src = self._np.asarray(src_tokens, self._np.int32).reshape(-1)
        if src.size == 0:
            raise MXNetError("src_tokens must be non-empty (an empty "
                             "source has no cross-attention context)")
        if src.size > self._rt.max_src_len:
            raise MXNetError(f"source length {src.size} exceeds the "
                             f"server's max_src_len "
                             f"{self._rt.max_src_len}")
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        req = Request(rid, src, max_new, prompt=prompt,
                      deadline_ms=deadline_ms)
        try:
            if _finj.ENABLED:
                _finj.check("serve.admit", context=f"request {rid}")
        except Exception as e:
            self._m_failed.inc()
            req._finish("failed", f"admit fault: {e!r}")
            raise ServeError(f"request {rid} rejected at admission: "
                             f"{e}") from e
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self._m_rejected.inc()
                req._finish("failed", "admission queue full")
                raise ServeOverloaded(
                    f"admission queue full ({self.max_queue}); retry "
                    "later")
            self._queue.append(req)
            self._m_queue.set(len(self._queue))
            if req.deadline is not None:
                with self._deadline_lock:
                    self._deadline_live += 1
                req._on_finish = self._dec_deadline_live
        if _tracer.ACTIVE:
            _tracer.instant("serve.submit", args={"id": rid})
        return req

    def pending_work(self):
        with self._lock:
            return bool(self._queue) or any(
                r is not None for r in self._slots)

    def active_count(self):
        return sum(1 for r in self._slots if r is not None)

    # ----------------------------------------------------------- step
    def step(self):
        """One serving turn: admit -> decode -> evict. Returns a
        `StepResult` (truthy when any progress was made). Turns are
        serialised on an internal lock (inline handles may crank from
        several threads; `defrag`/`shutdown` take the same lock)."""
        with self._step_lock:
            return self._step_locked()

    def _step_locked(self):
        res = StepResult()
        self._expire_deadlines()
        res.admitted = self._admit(res)
        active = [(s, r) for s, r in enumerate(self._slots)
                  if r is not None]
        if not active:
            self._m_active.set(0)
            return res
        t0 = time.perf_counter()
        try:
            if _finj.ENABLED:
                _finj.check("serve.decode",
                            context=f"{len(active)} active")
            plans = self._plan_turn(active, res)
            active = [(s, r) for s, r in enumerate(self._slots)
                      if r is not None]
            if not active:
                return res
            next_tok = self._decode(active, plans)
        except _finj.FaultInjected as e:
            self._fail_inflight(active, res, e, reset_pages=False)
            return res
        except Exception as e:  # executable error: pages untrustworthy
            self._fail_inflight(active, res, e, reset_pages=True)
            return res
        self._m_step.observe(time.perf_counter() - t0)
        res.decoded = len(active)
        self.decode_turns += 1
        now = time.perf_counter()
        for s, r in active:
            window, f = plans[s]
            q = len(window)
            g = next_tok[s]                    # (width,) host int32
            L = int(self._lens[s])
            commits = []
            accepted = 0
            if L + f == len(r.known):
                # the window reaches the generation frontier: g[f-1] is
                # the greedy token after the last known one, and each
                # accepted draft (window[i+1] == g[i]) validates one
                # more greedy commit — EXACTLY the tokens the 1-wide
                # loop would have produced over as many turns
                i = f - 1
                while True:
                    tok = int(g[i])
                    commits.append(tok)
                    if tok == self.eos_id or \
                            len(r.tokens) + len(commits) \
                            >= r.max_new_tokens:
                        break
                    if i + 1 < q and window[i + 1] == tok:
                        i += 1
                        continue
                    break
                accepted = i - (f - 1)
                self._lens[s] = L + f + accepted
            else:
                # pure prompt turn: every window token was forced, every
                # prediction is for a position we already know
                self._lens[s] = L + q
            if q > f:
                drafted = q - f
                self._m_spec_drafted.inc(drafted)
                self.spec_drafted += drafted
                self._m_spec_accepted.inc(accepted)
                self.spec_accepted += accepted
                self._m_spec_hist.observe(accepted)
            self._offer_prompt_pages(s, r)
            if not commits:
                continue
            if r.t_first_token is None:
                r.t_first_token = now
            r.known.extend(commits)
            for tok in commits:
                r._emit(tok)
            if commits[-1] == self.eos_id \
                    or len(r.tokens) >= r.max_new_tokens:
                self._evict(s, r, "done")
                res.completed += 1
        self._m_active.set(self.active_count())
        return res

    def defrag(self):
        """Compact the page pool: renumber live pages into the low ids,
        remap the device pools (one gather dispatch) and every active
        slot's page table + request page list. Takes the step lock, so
        it is safe to call from any thread while the engine loop is
        decoding; a no-op when the pool is already compact. Returns the
        number of pages that moved."""
        with self._step_lock:
            return self._defrag_locked()

    def _defrag_locked(self):
        mapping = self._pool.defrag()
        if not mapping:
            return 0
        self._rt.remap_pages(mapping)
        np = self._np
        remap = np.arange(self._rt.num_pages)
        for old, new in mapping.items():
            remap[old] = new
        self._page_tables = remap[self._page_tables].astype(np.int32)
        for r in self._slots:
            if r is not None:
                r._pages = [mapping.get(p, p) for p in r._pages]
        if self._cache is not None:
            self._cache.remap(mapping)
        return len(mapping)

    def shutdown(self, reason="server closed"):
        """Fail every queued and in-flight request (pages freed, events
        set) — `Server.close()` calls this so held handles can never
        block forever on a stopped loop."""
        with self._step_lock:
            self._shutdown_locked(reason)

    def _shutdown_locked(self, reason):
        with self._lock:
            queued = list(self._queue)
            self._queue.clear()
            self._m_queue.set(0)
        for r in queued:
            self._m_failed.inc()
            r._finish("failed", reason)
        for s, r in enumerate(self._slots):
            if r is not None:
                self._release_slot(s, r)
                self._m_failed.inc()
                r._finish("failed", reason)
        if self._cache is not None:
            self._cache.clear()
        self._m_active.set(0)

    def run_until_idle(self, max_steps=100000):
        """Drive `step()` until queue and slots drain (tests/bench)."""
        for _ in range(max_steps):
            if not self.pending_work():
                return
            self.step()
        raise MXNetError("scheduler failed to drain")

    # ------------------------------------------------------- internals
    def _dec_deadline_live(self):
        with self._deadline_lock:
            self._deadline_live -= 1

    def _expire_deadlines(self):
        """Evict every request whose end-to-end deadline has elapsed —
        queued requests leave the admission queue, running ones leave
        their slot with pages freed — finishing each with a clean
        `ServeDeadlineExceeded` (serve_deadline_expired counts them).
        Gated on the live deadline count: a deadline-free workload pays
        one lock acquire per turn, not an O(queue) sweep."""
        with self._deadline_lock:
            if not self._deadline_live:
                return
        now = time.monotonic()
        expired = []
        with self._lock:
            stale = [r for r in self._queue
                     if r.deadline is not None and now > r.deadline]
            if stale:
                stale_ids = {id(r) for r in stale}   # O(n) rebuild, not
                keep = collections.deque(r for r in self._queue  # O(n*k)
                                         if id(r) not in stale_ids)
                self._queue = keep
                self._m_queue.set(len(keep))
                expired.extend(stale)
        for s, r in enumerate(self._slots):
            if r is not None and r.deadline is not None \
                    and now > r.deadline:
                self._release_slot(s, r)
                expired.append(r)
        for r in expired:
            self._m_deadline.inc()
            self._m_failed.inc()
            r._exc = ServeDeadlineExceeded(
                f"request {r.id} exceeded its deadline "
                f"({len(r.tokens)} token(s) generated)")
            r._finish("failed", "deadline exceeded")
            if _tracer.ACTIVE:
                _tracer.instant("serve.deadline_expired",
                                args={"id": r.id})
        if expired:
            self._m_active.set(self.active_count())

    def _admit(self, res=None):
        admitted = 0
        while True:
            # static mode: admit only into an EMPTY batch — but fill the
            # whole batch in that one turn (requests admitted THIS call
            # don't close the window, or "static" would degenerate to
            # sequential batch-size-1 decoding)
            if self.static_batching and self.active_count() > admitted:
                break
            free = [s for s, r in enumerate(self._slots) if r is None]
            if not free:
                break
            with self._lock:
                if not self._queue:
                    break
                req = self._pop_next_locked()
                self._m_queue.set(len(self._queue))
            # serve.quant fault (ISSUE 14): degrade THIS request to the
            # full-precision path before it touches pages or slots —
            # leak-freedom is structural (nothing was allocated yet)
            if self._quant_fallback is not None and _finj.ENABLED:
                try:
                    _finj.check("serve.quant",
                                context=f"request {req.id}")
                except _finj.FaultInjected:
                    self._degrade_quant(req)
                    continue
            psize = self._pool.page_size
            known = [self.bos_id] + req.prompt
            # prefix-cache adoption (ISSUE 12): the longest cached chain
            # of FULL prompt pages under this source's content hash is
            # adopted (shared, never copied) — those positions skip
            # teacher-forced prefill entirely. Capped so the next input
            # token is still a KNOWN one (the page after the adopted run
            # starts with prompt material).
            hit = []
            if self._cache is not None and len(req.prompt) >= psize:
                try:
                    if _finj.ENABLED:
                        _finj.check("serve.prefix",
                                    context=f"lookup request {req.id}")
                    hit = self._cache.lookup(self._src_key(req), known,
                                             len(req.prompt) // psize)
                except _finj.FaultInjected:
                    # degrade to the cold path: same output, no reuse
                    self._m_prefix_degraded.inc()
                    hit = []
                if hit:
                    # the adopter's reference FIRST: pressure eviction
                    # below must never reap the pages just handed out
                    self._pool.share(hit)
            try:
                first = self._alloc_pages(1)
            except PageAllocError:
                # no first page -> push back and stop admitting; decode
                # progress on the current actives will free pages
                if hit:
                    self._pool.free(hit)
                with self._lock:
                    self._queue.appendleft(req)
                    self._m_queue.set(len(self._queue))
                break
            pages = hit + first
            s = free[0]
            try:
                self._rt.prefill(s, req.src)
            except Exception as e:
                self._pool.free(pages)
                self._m_failed.inc()
                req._finish("failed", f"prefill error: {e!r}")
                if isinstance(e, MemoryStateLost):
                    # the donated memory buffers died: EVERY in-flight
                    # slot lost its encoder state (the runtime already
                    # rebuilt zeroed buffers) — restart those requests
                    # from scratch; re-admission re-prefills each slot
                    self._fail_inflight(
                        [(s2, r2) for s2, r2 in enumerate(self._slots)
                         if r2 is not None],
                        res if res is not None else StepResult(), e,
                        reset_pages=False)
                    break
                continue
            req.state = "running"
            req._slot = s
            req._pages = pages
            req.known = known
            req.prompt_cached_tokens = len(hit) * psize
            req._cache_done = False
            self._slots[s] = req
            self._page_tables[s, :] = NULL_PAGE
            for i, p in enumerate(pages):
                self._page_tables[s, i] = p
            req._n_table = len(pages)
            self._lens[s] = len(hit) * psize
            admitted += 1
        if admitted:
            self._m_active.set(self.active_count())
        return admitted

    # a cold queue head is bypassed by warm-preferred admissions at most
    # this many times before FIFO order reasserts itself — bounds
    # starvation under sustained warm traffic
    MAX_ADMIT_BYPASS = 4

    def _pop_next_locked(self):
        """Cache-aware admission order: FIFO normally, but when pages
        are TIGHT (the head's full cold working set no longer fits the
        free pool) prefer the queued request with the LONGEST warm
        cached prefix — it admits at a smaller fresh-page cost, which
        cuts the mid-decode preemptions page pressure would otherwise
        cause. A head bypassed `MAX_ADMIT_BYPASS` times is admitted
        regardless (no starvation under sustained warm arrivals). Probes
        use `PrefixCache.peek` (no metrics, no LRU touch);
        `serve_prefix_admit_preferred` counts reorders. Caller holds
        `self._lock`."""
        if self._cache is None or len(self._queue) <= 1:
            return self._queue.popleft()
        head = self._queue[0]
        if head._admit_bypassed >= self.MAX_ADMIT_BYPASS \
                or self._pool.available() >= self._pool.pages_for(
                    len(head.prompt) + head.max_new_tokens):
            return self._queue.popleft()
        psize = self._pool.page_size
        best_i, best_warm = 0, -1
        for i, r in enumerate(self._queue):
            warm = 0
            if len(r.prompt) >= psize:
                warm = self._cache.peek(self._src_key(r),
                                        [self.bos_id] + r.prompt,
                                        len(r.prompt) // psize)
            if warm > best_warm:
                best_i, best_warm = i, warm
        if best_i == 0:
            return self._queue.popleft()
        head._admit_bypassed += 1
        req = self._queue[best_i]
        del self._queue[best_i]
        self._m_warm_pref.inc()
        return req

    @staticmethod
    def _src_key(req):
        """Memoized content hash of the request's source (immutable per
        request; the admission hot path probes it repeatedly)."""
        if req._content_key is None:
            req._content_key = content_key(req.src)
        return req._content_key

    def _alloc_pages(self, n):
        """`pool.alloc` with prefix-cache pressure relief: when the pool
        is dry, evict least-recently-used CACHE-ONLY pages (nothing in
        flight adopted them) and retry, so cached prefixes cost capacity
        only while it is spare — admission never fails because of them."""
        try:
            return self._pool.alloc(n)
        except PageAllocError:
            if self._cache is None or not self._cache.evict(n):
                raise
            return self._pool.alloc(n)

    def _plan_turn(self, active, res):
        """Build every active slot's token window for this turn — the
        FORCED tokens first (known-but-uncached prompt / committed
        tokens), then up to `spec_k` n-gram drafts once the window
        reaches the generation frontier — and allocate the pages those
        positions need. A slot whose current page is full when the pool
        is dry is preempted (pages freed, requeued) exactly like the
        1-wide path; a slot that can only fit part of its window just
        runs a shorter window (ragged qlens are free — same executable,
        same dispatch)."""
        psize = self._rt.page_size
        budget = self._rt.max_pages_per_slot * psize
        width = self.width
        draft_ok = self.spec_k > 0
        if draft_ok and _finj.ENABLED:
            try:
                _finj.check("serve.speculate", context="draft window")
            except _finj.FaultInjected:
                # degrade: run the turn unspeculated — committed output
                # is IDENTICAL, only turns/token suffers
                self._m_spec_degraded.inc()
                draft_ok = False
        plans = {}
        for s, r in active:
            L = int(self._lens[s])
            window = list(r.known[L:L + width])
            f = len(window)
            if draft_ok and f < width:
                window.extend(propose_ngram(r.known, width - f,
                                            self.spec_ngram))
            del window[budget - L:]     # never write past the page budget
            need_idx = (L + len(window) - 1) // psize
            while r._n_table <= need_idx:
                try:
                    page = self._alloc_pages(1)[0]
                except PageAllocError:
                    del window[r._n_table * psize - L:]
                    break
                r._pages.append(page)
                self._page_tables[s, r._n_table] = page
                r._n_table += 1
            if not window:
                self._m_preempt.inc()
                self._requeue(s, r, "page pool exhausted mid-decode",
                              preempted=True)
                res.preempted += 1
                continue
            plans[s] = (window, min(f, len(window)))
        return plans

    def _decode(self, active, plans):
        np = self._np
        width = self.width
        mask = np.zeros((self._rt.slots,), np.int32)
        toks = np.zeros((self._rt.slots, width), np.int32)
        qlens = np.ones((self._rt.slots,), np.int32)
        for s, r in active:
            window, _f = plans[s]
            mask[s] = 1
            toks[s, :len(window)] = window
            qlens[s] = len(window)

        def launch():
            if width == 1:
                out, _ = self._rt.decode(self._page_tables, self._lens,
                                         toks[:, 0], mask)
                return out.reshape(-1, 1)
            out, _ = self._rt.decode_multi(self._page_tables, self._lens,
                                           toks, qlens, mask)
            return out

        if _tracer.ACTIVE:
            with _tracer.span("serve.decode_step", cat="serve",
                              args={"active": len(active)}):
                return launch()
        return launch()

    def _offer_prompt_pages(self, s, r):
        """Once a request's prompt positions are fully cached, index its
        FULL prompt pages in the radix cache (the cache takes its own
        reference; chunks another request already cached keep theirs).
        One-shot per admission attempt; a `serve.prefix` fault degrades
        to not caching — the request itself is unaffected."""
        if self._cache is None or r._cache_done:
            return
        psize = self._rt.page_size
        ncache = (len(r.prompt) + 1) // psize   # [BOS] + prompt chunks
        if ncache == 0:
            r._cache_done = True
            return
        if int(self._lens[s]) < ncache * psize:
            return
        r._cache_done = True
        try:
            if _finj.ENABLED:
                _finj.check("serve.prefix",
                            context=f"insert request {r.id}")
        except _finj.FaultInjected:
            self._m_prefix_degraded.inc()
            return
        pages = [int(p) for p in self._page_tables[s, :ncache]]
        self._cache.insert(self._src_key(r), r.known, pages)

    def _degrade_quant(self, req):
        """Run one request through the full-precision fallback (a
        `serve.quant` fault fired at its admission): greedy output is
        IDENTICAL to an fp32 server's, the quantized executables and the
        page pool are never touched for it, and the handle's stream/
        result plumbing behaves normally (tokens arrive in one burst).
        The request's end-to-end deadline stays in force — the remaining
        budget rides into the fallback, and expiry surfaces as the same
        `ServeDeadlineExceeded` the normal path raises."""
        self._m_quant_degraded.inc()
        req.state = "running"
        try:
            toks = self._quant_fallback(req.src, req.prompt,
                                        req.max_new_tokens,
                                        deadline=req.deadline)
        except ServeDeadlineExceeded:
            self._m_deadline.inc()
            self._m_failed.inc()
            req._exc = ServeDeadlineExceeded(
                f"request {req.id} exceeded its deadline (degraded "
                f"full-precision attempt)")
            req._finish("failed", "deadline exceeded")
            return
        except Exception as e:
            self._m_failed.inc()
            req._finish("failed", f"quant degrade failed: {e!r}")
            return
        now = time.perf_counter()
        if toks and req.t_first_token is None:
            req.t_first_token = now
        for tok in toks:
            req._emit(tok)
        self._m_ok.inc()
        self._m_tokens.inc(len(req.tokens))
        self.tokens_generated += len(req.tokens)
        if req.ttft is not None:
            self._m_ttft.observe(req.ttft)
        self._m_latency.observe(time.perf_counter() - req.t_submit)
        req._finish("done")
        if _tracer.ACTIVE:
            _tracer.instant("serve.quant_degraded",
                            args={"id": req.id, "tokens": len(req.tokens)})

    def _release_slot(self, s, r):
        if r._pages:
            self._pool.free(r._pages)
        r._pages = []
        r._slot = None
        r._n_table = 0
        self._slots[s] = None
        self._page_tables[s, :] = NULL_PAGE
        self._lens[s] = 0

    def _evict(self, s, r, state):
        self._release_slot(s, r)
        self._m_ok.inc()
        # token/TTFT metrics land ONCE, at completion — per-step counting
        # would double-report any request a fault or preemption restarted
        self._m_tokens.inc(len(r.tokens))
        self.tokens_generated += len(r.tokens)
        if r.ttft is not None:
            self._m_ttft.observe(r.ttft)
        self._m_latency.observe(time.perf_counter() - r.t_submit)
        r._finish(state)
        if _tracer.ACTIVE:
            _tracer.instant("serve.request_done", args={
                "id": r.id, "tokens": len(r.tokens),
                "ttft_ms": round((r.ttft or 0) * 1e3, 3)})

    def _requeue(self, s, r, why, preempted=False):
        """Restart a request from scratch (pages freed, queued at the
        front); fail it cleanly when the relevant restart budget is
        spent (fault retries and page preemptions count separately). The
        stream restarts too: undelivered chunks from the aborted attempt
        are dropped and TTFT re-arms, so consumers see one clean token
        sequence (tokens a live streamer already pulled before the fault
        are superseded by the retry — inherent to streaming + retry)."""
        self._release_slot(s, r)
        if preempted:
            r.preemptions += 1
            exhausted = r.preemptions > self.max_preemptions
        else:
            r.retries += 1
            exhausted = r.retries > self.max_retries
        r.tokens = []
        r.known = None              # rebuilt (and re-adopted) at admission
        r._cache_done = False
        r.prompt_cached_tokens = 0
        r.t_first_token = None
        with r._chunk_cv:
            r._chunks.clear()
        if exhausted:
            self._m_failed.inc()
            r._finish("failed", why)
            return False
        r.state = "queued"
        with self._lock:
            self._queue.appendleft(r)
            self._m_queue.set(len(self._queue))
        return True

    def _fail_inflight(self, active, res, exc, reset_pages):
        """A decode-time fault killed the whole in-flight batch: every
        active request retries from scratch or fails cleanly; page
        accounting returns to baseline either way."""
        self._m_retries.inc()
        for s, r in active:
            if self._requeue(s, r, f"decode fault: {exc!r}"):
                res.retried += 1
        if reset_pages:
            self._rt.reset_pages()
            if self._cache is not None:
                # page CONTENTS are no longer trustworthy — cached
                # prefixes must not be adopted into fresh requests
                self._cache.clear()
        self._m_active.set(self.active_count())
