"""mx.observability — unified tracing & metrics (new subsystem; reference
capability: the MXNet profiler's profile.json + aggregate stats, rebuilt
as two orthogonal pieces).

  * `tracer` — host-side Chrome-trace span recorder (nestable spans,
    instants, counter tracks, per-thread rows, ring-buffer bounded).
    `profiler.start()/stop()/dump()` drive it for reference parity;
    it can also run standalone: `tracer.start(); ...; tracer.dump(path)`.
  * `metrics_registry` — labelled counters/gauges/histograms with
    snapshot/reset and a JSONL sink. The profiler's dispatch/jit-cache/
    bucket telemetry records here; engine, KVStore and Trainer
    instrumentation add queue-depth, collective-bytes, var-wait and
    step-rate series.
  * `compilex` — the compile observatory: every framework-owned jitted
    executable (captured/sharded step, serve prefill/decode, fused
    update kernels, cached backward) reports compile counts/seconds,
    optimized-HLO structure (fusions, collectives, copies, donation
    aliases) and persistent-compilation-cache hits/misses
    (`mx.set_compilation_cache`; gated in tier-1 by
    tools/check_fusion.py).

`summary()` renders a human-readable step breakdown from all three.

Env knobs: MXTPU_TRACE_BUFFER (ring capacity, events, default 65536),
MXTPU_TRACE_OP_SAMPLE (imperative-op sampling rate, default 16),
MXTPU_COMPILE_CACHE (persistent compile-cache dir),
MXTPU_HLO_TELEMETRY (auto|always|0) and MXTPU_HLO_MAX_S (inspection
cost ceiling, default 20s).
"""
from __future__ import annotations

from . import tracer
from . import metrics_registry
from .metrics_registry import MetricsRegistry, registry
from . import compilex
from .compilex import set_compilation_cache, compile_cache_stats

__all__ = ["tracer", "metrics_registry", "MetricsRegistry", "registry",
           "compilex", "set_compilation_cache", "compile_cache_stats",
           "summary"]


def _fmt_labels(labels):
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def summary(max_rows=25):
    """Human-readable breakdown of the captured trace + current metrics:
    per-span-name total/avg host time (from the tracer buffer) and every
    registered metric series. Returns the report as a string."""
    lines = []
    trace = tracer.to_chrome_trace()["traceEvents"]
    # fold B/E and X events into per-name (count, total_us) using a
    # per-tid stack for B/E pairing
    agg = {}
    stacks = {}
    for ev in trace:
        ph = ev["ph"]
        if ph == "B":
            stacks.setdefault(ev["tid"], []).append((ev["name"], ev["ts"]))
        elif ph == "E":
            stack = stacks.get(ev["tid"])
            if stack:
                name, t0 = stack.pop()
                c, tot = agg.get(name, (0, 0.0))
                agg[name] = (c + 1, tot + ev["ts"] - t0)
        elif ph == "X":
            c, tot = agg.get(ev["name"], (0, 0.0))
            agg[ev["name"]] = (c + 1, tot + ev.get("dur", 0.0))
    if agg:
        lines.append(f"{'span':<44}{'count':>8}{'total_ms':>12}"
                     f"{'avg_us':>10}")
        ranked = sorted(agg.items(), key=lambda kv: -kv[1][1])
        for name, (count, total_us) in ranked[:max_rows]:
            lines.append(f"{name[:43]:<44}{count:>8}{total_us / 1e3:>12.3f}"
                         f"{total_us / count:>10.1f}")
        if len(ranked) > max_rows:
            lines.append(f"... {len(ranked) - max_rows} more span names")
    else:
        lines.append("(no spans captured — profiler.start() or "
                     "tracer.start() first)")
    snap = registry().snapshot()
    if snap:
        lines.append("")
        lines.append(f"{'metric':<44}{'value':>26}")
        for name in sorted(snap):
            for series in snap[name]:
                label = name
                if series["labels"]:
                    label += "{" + _fmt_labels(series["labels"]) + "}"
                val = series["value"]
                if series["kind"] == "histogram":
                    val = (f"n={val['count']} mean={val['mean']:.3g} "
                           f"p95={val['p95']:.3g} p99={val['p99']:.3g}")
                lines.append(f"{label[:43]:<44}{str(val)[:26]:>26}")
    return "\n".join(lines)
