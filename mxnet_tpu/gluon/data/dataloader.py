"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

TPU-native design: the reference forks worker *processes* that serialise
batches over shared-memory recordio. Here batches are assembled by the native
engine's threadpool (numpy staging, GIL released inside numpy/jax) and
prefetched ahead of consumption, overlapping host batching + H2D transfer
with device compute — the same pipeline role as the reference's
multi-worker loader, without pickling overhead.
"""
from __future__ import annotations

import numpy as np

from ... import engine
from ...ndarray.ndarray import NDArray, array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(s)) for s in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * max(num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._prefetch == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        # pipelined prefetch through the engine threadpool
        from collections import deque
        pending = deque()
        it = iter(self._batch_sampler)

        def submit():
            try:
                indices = next(it)
            except StopIteration:
                return False
            pending.append(engine.push(lambda idx=indices: self._make_batch(idx)))
            return True

        for _ in range(self._prefetch):
            if not submit():
                break
        while pending:
            fut = pending.popleft()
            submit()
            yield fut.result()
