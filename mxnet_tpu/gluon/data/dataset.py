"""Datasets (reference: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import os

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(x, *rest):
            return (fn(x),) + rest if rest else fn(x)
        return self.transform(first, lazy)

    def filter(self, fn):
        kept = [i for i in range(len(self)) if fn(self[i])]
        return _IndexedDataset(self, kept)

    def take(self, count):
        return _IndexedDataset(self, list(range(min(count, len(self)))))

    def shard(self, num_shards, index):
        idx = list(range(index, len(self), num_shards))
        return _IndexedDataset(self, idx)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _IndexedDataset(Dataset):
    def __init__(self, data, indices):
        self._data = data
        self._indices = indices

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._data[self._indices[idx]]


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of equal-length arrays/lists (reference: ArrayDataset)."""

    def __init__(self, *args):
        assert args, "needs at least 1 array"
        self._length = len(args[0])
        self._data = []
        for a in args:
            assert len(a) == self._length, "all arrays must be same length"
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """A dataset over a real RecordIO .rec file (reference:
    gluon.data.RecordFileDataset over recordio.MXIndexedRecordIO). Uses the
    .idx sidecar for random access when present, else loads sequentially."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO, open_record_file
        idx_path = os.path.splitext(filename)[0] + ".idx"
        if os.path.exists(idx_path):
            self._rec = MXIndexedRecordIO(idx_path, filename, "r")
            self._keys = self._rec.keys
            self._records = None
        else:
            self._rec = None
            # native mmap reader (cpp/recordio.cc) when it builds; list of
            # bytes from the Python scan otherwise — same random access
            self._records = open_record_file(filename)

    def __len__(self):
        return len(self._keys) if self._records is None else \
            len(self._records)

    def __getitem__(self, idx):
        if self._records is None:
            return self._rec.read_idx(self._keys[idx])
        return self._records[idx]
