"""Captured one-executable training step (mxnet_tpu/cachedop.py):
captured-vs-imperative parity (fused and unfused optimizers, AMP
overflow-skip, the 'ici' kvstore on the CPU test mesh, sharded_update),
single-dispatch guarantees, cache hit/miss/fallback telemetry, and the
cached-backward interplay."""
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, fault, gluon, nd, profiler
from mxnet_tpu.observability import registry
from mxnet_tpu.parallel.mesh import make_mesh

BATCH, DIM, CLS = 8, 16, 4


def _data():
    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(BATCH, DIM).astype(np.float32))
    y = nd.array(rng.randint(0, CLS, BATCH).astype(np.float32))
    return X, y


def _build(X, layers=3, hidden=16, seed=0, bn=False):
    mx.random.seed(seed)
    net = gluon.nn.Sequential()
    for _ in range(layers):
        net.add(gluon.nn.Dense(hidden, activation="relu"))
    if bn:
        net.add(gluon.nn.BatchNorm())
    net.add(gluon.nn.Dense(CLS))
    net.initialize(mx.init.Xavier())
    net(X)
    return net


_lossf = gluon.loss.SoftmaxCrossEntropyLoss()


def _weights(net):
    return [p.data().asnumpy().astype(np.float32)
            for p in net.collect_params().values()]


def _train_imperative(net, tr, X, y, steps):
    for _ in range(steps):
        with autograd.record():
            L = _lossf(net(X), y).mean()
        L.backward()
        tr.step(BATCH)
    return _weights(net)


def _train_captured(net, tr, X, y, steps, **cap_kw):
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean(), **cap_kw)
    for _ in range(steps):
        step(X, y)
        assert step.last_fallback_reason is None, step.last_fallback_reason
    assert step.cache_size == 1          # one executable for the whole run
    return _weights(net)


def _assert_parity(a, b, rtol=1e-4, atol=1e-6, tag=""):
    for i, (x, z) in enumerate(zip(a, b)):
        np.testing.assert_allclose(x, z, rtol=rtol, atol=atol,
                                   err_msg=f"{tag} param {i}")


# ------------------------------------------------------------------ parity
@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_captured_parity_fused(opt):
    """Captured step == fused imperative path, per optimizer family."""
    X, y = _data()
    kw = {"learning_rate": 0.05, "wd": 0.01}
    if opt == "sgd":
        kw["momentum"] = 0.9
    net_i = _build(X)
    imp = _train_imperative(
        net_i, gluon.Trainer(net_i.collect_params(), opt, dict(kw)), X, y, 4)
    net_c = _build(X)
    cap = _train_captured(
        net_c, gluon.Trainer(net_c.collect_params(), opt, dict(kw)), X, y, 4)
    _assert_parity(cap, imp, tag=opt)


def test_captured_parity_unfused_trainer():
    """fused=False Trainer: the captured program still matches the
    per-param reference updates."""
    X, y = _data()
    net_i = _build(X)
    imp = _train_imperative(
        net_i, gluon.Trainer(net_i.collect_params(), "adam",
                             {"learning_rate": 0.05}, fused=False), X, y, 3)
    net_c = _build(X)
    cap = _train_captured(
        net_c, gluon.Trainer(net_c.collect_params(), "adam",
                             {"learning_rate": 0.05}, fused=False), X, y, 3)
    _assert_parity(cap, imp, tag="unfused")


def test_captured_batchnorm_aux_carried():
    """BN running stats (aux updates) are outputs of the captured program
    and match the imperative path."""
    X, y = _data()
    net_i = _build(X, bn=True)
    imp = _train_imperative(
        net_i, gluon.Trainer(net_i.collect_params(), "sgd",
                             {"learning_rate": 0.05}), X, y, 3)
    net_c = _build(X, bn=True)
    cap = _train_captured(
        net_c, gluon.Trainer(net_c.collect_params(), "sgd",
                             {"learning_rate": 0.05}), X, y, 3)
    _assert_parity(cap, imp, tag="bn")
    fresh = _weights(_build(X, bn=True))
    assert any(not np.array_equal(c, f) for c, f in zip(cap, fresh))


def test_captured_amp_overflow_skip_parity():
    """fp16 loss-scaler protocol inside the lax.cond guard: a NaN step
    (grad.nan fault point -> in-graph poison) skips the update and halves
    the scale exactly like the imperative path."""
    X, y = _data()

    def run(captured):
        amp.reset()
        amp.init("float16")
        fault.injection.clear()
        fault.injection.inject("grad.nan", at=[2])
        net = _build(X)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05})
        try:
            if captured:
                step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
                for _ in range(4):
                    step(X, y)
                    assert step.last_fallback_reason is None
            else:
                for _ in range(4):
                    with autograd.record():
                        L = amp.scale_loss(_lossf(net(X), y).mean())
                    L.backward()
                    tr.step(BATCH)
            return _weights(net), amp._state["scaler"].loss_scale
        finally:
            amp.reset()
            fault.injection.clear()

    wc, sc = run(True)
    wi, si = run(False)
    assert sc == si
    _assert_parity(wc, wi, tag="amp")


def test_captured_skip_nonfinite_and_streak():
    """skip_nonfinite guard skips poisoned steps in-graph; the skip streak
    escalation still fires on the captured path."""
    X, y = _data()
    fault.injection.clear()
    fault.injection.inject("grad.nan", at=[2])
    try:
        net = _build(X)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, skip_nonfinite=True)
        step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
        step(X, y)
        before = _weights(net)
        step(X, y)                      # poisoned: must skip
        _assert_parity(_weights(net), before, rtol=0, atol=0, tag="skip")
        assert tr.consecutive_skipped_steps == 1
        step(X, y)                      # clean: applies, streak resets
        assert tr.consecutive_skipped_steps == 0
    finally:
        fault.injection.clear()
    # escalation: every step poisoned + max_skipped_steps=1 -> raises
    fault.injection.inject("grad.nan", prob=1.0)
    try:
        net = _build(X)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, skip_nonfinite=True,
                           max_skipped_steps=1)
        step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
        step(X, y)
        with pytest.raises(Exception, match="consecutive skipped"):
            step(X, y)
    finally:
        fault.injection.clear()


# ------------------------------------------------------ 'ici' on the mesh
def test_captured_ici_psum_and_sharded_update_parity():
    """Captured step over the CPU test mesh: batch sharded over 'dp',
    gradients psum'd IN-GRAPH — matches the imperative replicated run;
    and sharded_update=True (in-graph reduce-scatter + per-shard update +
    all-gather, arXiv:2004.13336) matches the replicated-update capture
    on the same 2-device mesh."""
    X, y = _data()
    mesh = make_mesh({"dp": 2})
    net_i = _build(X)
    tr_i = gluon.Trainer(net_i.collect_params(), "adam",
                         {"learning_rate": 0.05}, kvstore="ici")
    tr_i._kvstore.set_mesh(mesh)
    imp = _train_imperative(net_i, tr_i, X, y, 4)

    def run(sharded):
        net = _build(X)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.05}, kvstore="ici")
        tr._kvstore.set_mesh(mesh)
        return _train_captured(net, tr, X, y, 4, sharded_update=sharded)

    cap = run(False)
    _assert_parity(cap, imp, rtol=2e-4, atol=1e-5, tag="ici")
    # the in-graph collective is accounted per step
    snap = registry().snapshot()
    ops = {tuple(s["labels"].items()) for s in snap["kv_collective_bytes"]}
    assert (("op", "in_graph_psum"),) in ops
    _assert_parity(run(True), cap, rtol=2e-4, atol=1e-5, tag="sharded")
    assert (("op", "in_graph_reduce_scatter"),) in {
        tuple(s["labels"].items())
        for s in registry().snapshot()["kv_collective_bytes"]}


def test_sharded_update_requires_mesh():
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean(),
                      sharded_update=True)
    with pytest.raises(Exception, match="sharded_update"):
        step(X, y)


def test_sharded_update_lamb_falls_back_to_replicated_update():
    """LAMB's trust ratio is a whole-tensor norm (elementwise=False): its
    params take the replicated-update path inside the same sharded
    program, and the numerics still match the imperative run."""
    X, y = _data()
    mesh = make_mesh({"dp": 2})
    net_i = _build(X)
    tr_i = gluon.Trainer(net_i.collect_params(), "lamb",
                         {"learning_rate": 0.01}, kvstore="ici")
    tr_i._kvstore.set_mesh(mesh)
    imp = _train_imperative(net_i, tr_i, X, y, 3)
    net_c = _build(X)
    tr_c = gluon.Trainer(net_c.collect_params(), "lamb",
                         {"learning_rate": 0.01}, kvstore="ici")
    tr_c._kvstore.set_mesh(mesh)
    cap = _train_captured(net_c, tr_c, X, y, 3, sharded_update=True)
    _assert_parity(cap, imp, rtol=2e-4, atol=1e-5, tag="lamb")


# ----------------------------------------------- dispatch-count guarantees
def test_captured_single_dispatch_per_step():
    """Acceptance guard: ONE device dispatch per warm captured step, zero
    imperative op dispatches (the loss_fn is not re-executed eagerly),
    while the per-param escape hatch on the SAME net is O(num_params)."""
    from mxnet_tpu.ndarray import ndarray as nd_mod
    X, y = _data()
    net_u = _build(X)
    tr_u = gluon.Trainer(net_u.collect_params(), "sgd",
                         {"learning_rate": 0.05}, fused=False)
    with autograd.record():
        L = _lossf(net_u(X), y).mean()
    L.backward()
    profiler.reset_dispatches()
    tr_u.step(BATCH)
    imperative = profiler.dispatch_count()
    assert imperative >= len(net_u.collect_params())   # O(num_params)

    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    for _ in range(2):                   # warm: compile once
        step(X, y)
    calls = [0]
    orig = nd_mod._apply

    def counting_apply(*a, **kw):
        calls[0] += 1
        return orig(*a, **kw)

    profiler.reset_dispatches()
    nd_mod._apply = counting_apply
    try:
        step(X, y)
    finally:
        nd_mod._apply = orig
    assert profiler.dispatch_count() == 1 < imperative, profiler.dumps()
    assert profiler.jit_cache_stats() == (1, 0)   # warm: pure cache hit
    assert calls[0] == 0                  # no eager op dispatch at all


# --------------------------------------------------- cache / fallback / obs
def test_cache_hit_miss_counters_and_reasons():
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())

    def series(name):
        snap = registry().snapshot().get(name, [])
        return {tuple(sorted(s["labels"].items())): s["value"] for s in snap}

    h0 = series("cachedop_cache_hits").get((), 0)
    m0 = series("cachedop_cache_misses")
    step(X, y)
    step(X, y)
    assert series("cachedop_cache_hits").get((), 0) == h0 + 1
    m1 = series("cachedop_cache_misses")
    assert m1.get((("reason", "first"),), 0) == \
        m0.get((("reason", "first"),), 0) + 1
    # shape change: a labelled miss, then the old shape still hits
    rng = np.random.RandomState(1)
    X2 = nd.array(rng.randn(4, DIM).astype(np.float32))
    y2 = nd.array(rng.randint(0, CLS, 4).astype(np.float32))
    step(X2, y2)
    m2 = series("cachedop_cache_misses")
    assert m2.get((("reason", "shape_change"),), 0) == \
        m1.get((("reason", "shape_change"),), 0) + 1
    assert step.cache_size == 2
    h1 = series("cachedop_cache_hits").get((), 0)
    step(X, y)
    assert series("cachedop_cache_hits").get((), 0) == h1 + 1
    # scale-mode flip: another labelled miss
    amp.reset()
    amp.init("float16")
    try:
        step(X, y)
    finally:
        amp.reset()
    m3 = series("cachedop_cache_misses")
    assert m3.get((("reason", "scale_mode"),), 0) == \
        m2.get((("reason", "scale_mode"),), 0) + 1


def test_fallback_transparent_and_labelled():
    """A loss_fn that syncs to host cannot capture: the step still trains
    (imperative fallback) and the reason lands on the counter."""
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})

    def bad_loss(a, b):
        L = _lossf(net(a), b).mean()
        float(L.asnumpy())              # host sync inside the forward
        return L

    before = _weights(net)
    step = tr.capture(bad_loss)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        L = step(X, y)
    assert step.last_fallback_reason.startswith("trace_error")
    assert np.isfinite(float(L.asnumpy()))
    after = _weights(net)
    assert any(not np.array_equal(a, b) for a, b in zip(before, after))
    snap = registry().snapshot()
    reasons = {s["labels"].get("reason", "") for s in
               snap.get("cachedop_fallbacks", [])}
    assert any(r.startswith("trace_error") for r in reasons)


def test_unsupported_optimizer_falls_back():
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "dcasgd",
                       {"learning_rate": 0.05})
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        step(X, y)
    assert step.last_fallback_reason == "optimizer"


def test_captured_step_span_and_counters():
    """Trainer.captured_step span is recorded when tracing, and the step
    counter ticks like the imperative path."""
    from mxnet_tpu.observability import tracer
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    step(X, y)
    snap = registry().snapshot()
    steps0 = snap["trainer_steps"][0]["value"]
    tracer.start()
    try:
        step(X, y)
    finally:
        tracer.stop()
    names = {e.get("name") for e in
             tracer.to_chrome_trace()["traceEvents"]}
    tracer.clear()
    assert "Trainer.captured_step" in names
    snap = registry().snapshot()
    assert snap["trainer_steps"][0]["value"] == steps0 + 1


def test_jit_step_convenience_and_save_load_states(tmp_path):
    """mx.jit_step == Trainer.capture; optimizer state updated by the
    captured program round-trips through save_states/load_states."""
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.05})
    step = mx.jit_step(tr, lambda a, b: _lossf(net(a), b).mean())
    assert isinstance(step, mx.CachedStep)
    step(X, y)
    step(X, y)
    f = str(tmp_path / "states.bin")
    tr.save_states(f)
    tr2 = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.05})
    tr2.load_states(f)
    assert tr2._optimizer.num_update == tr._optimizer.num_update == 2
    for k, v in tr._updater.states.items():
        for a, b in zip(v, tr2._updater.states[k]):
            np.testing.assert_allclose(np.asarray(a._data),
                                       np.asarray(b._data))


def test_lr_schedule_rides_without_retrace():
    """Changing the learning rate between steps must NOT grow the capture
    cache (lr is a weak-typed argument), and the schedule is honored."""
    X, y = _data()
    net_c = _build(X)
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.05})
    step = tr_c.capture(lambda a, b: _lossf(net_c(a), b).mean())
    step(X, y)
    tr_c.set_learning_rate(0.005)
    step(X, y)
    assert step.cache_size == 1
    net_i = _build(X)
    tr_i = gluon.Trainer(net_i.collect_params(), "sgd",
                         {"learning_rate": 0.05})
    _train_imperative(net_i, tr_i, X, y, 1)
    tr_i.set_learning_rate(0.005)
    _train_imperative(net_i, tr_i, X, y, 1)
    _assert_parity(_weights(net_c), _weights(net_i), tag="lr-schedule")


def test_captured_parity_multi_precision():
    """bf16 weights + fp32 master copies: the captured update stages the
    master exactly like update_multi_precision."""
    X, y = _data()

    def run(captured):
        net = _build(X)
        net.cast("bfloat16")
        Xb = X.astype("bfloat16")
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9,
                            "multi_precision": True})
        if captured:
            step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
            for _ in range(3):
                step(Xb, y)
                assert step.last_fallback_reason is None
        else:
            for _ in range(3):
                with autograd.record():
                    L = _lossf(net(Xb), y).mean()
                L.backward()
                tr.step(BATCH)
        return _weights(net)

    _assert_parity(run(True), run(False), rtol=2e-2, atol=1e-3, tag="mp")


def test_captured_interleaves_with_imperative_steps():
    """Captured and imperative steps share the optimizer state dict, so a
    mixed loop equals an all-imperative loop."""
    X, y = _data()
    net_i = _build(X)
    tr_i = gluon.Trainer(net_i.collect_params(), "adam",
                         {"learning_rate": 0.05})
    imp = _train_imperative(net_i, tr_i, X, y, 4)

    net_m = _build(X)
    tr_m = gluon.Trainer(net_m.collect_params(), "adam",
                         {"learning_rate": 0.05})
    step = tr_m.capture(lambda a, b: _lossf(net_m(a), b).mean())
    for k in range(4):
        if k % 2 == 0:
            step(X, y)
        else:
            with autograd.record():
                L = _lossf(net_m(X), y).mean()
            L.backward()
            tr_m.step(BATCH)
    _assert_parity(_weights(net_m), imp, tag="mixed")


def test_frozen_params_promoted_not_baked():
    """Fine-tuning: params OUTSIDE the trainer's list (frozen backbone)
    must become program inputs, not baked constants — set_data() on the
    frozen subtree must be visible to later captured steps."""
    X, y = _data()
    mx.random.seed(0)
    backbone = gluon.nn.Dense(16, activation="relu")
    head = gluon.nn.Dense(CLS)
    net = gluon.nn.Sequential()
    net.add(backbone, head)
    net.initialize(mx.init.Xavier())
    net(X)
    tr = gluon.Trainer(head.collect_params(), "sgd",   # head ONLY
                       {"learning_rate": 0.05})
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    l0 = float(step(X, y).asnumpy())
    assert step.last_fallback_reason is None
    # zero the backbone: the captured loss must change immediately and
    # match an eager forward over the SAME parameter values
    for p in backbone.collect_params().values():
        p.set_data(nd.zeros(p.shape))
    expected = float(_lossf(net(X), y).mean().asnumpy())
    l1 = float(step(X, y).asnumpy())
    assert step.last_fallback_reason is None
    assert step.cache_size == 1            # same executable, new input
    assert abs(l1 - l0) > 1e-4
    np.testing.assert_allclose(l1, expected, rtol=2e-4)


# ------------------------------------------- device-resident input (ISSUE 5)
def test_captured_step_accepts_prefetched_sharded_batches():
    """A DevicePrefetcher staged with the step's capture_spec feeds the
    captured mesh step with ZERO synchronous H2D on warm steps, no
    fallback, and bitwise the numerics of the host-fed captured twin."""
    from mxnet_tpu.prefetch import DevicePrefetcher
    X, y = _data()
    Xh, yh = X.asnumpy(), y.asnumpy()
    mesh = make_mesh({"dp": 2})

    def trainer_for(net):
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore="ici")
        tr._kvstore.set_mesh(mesh)
        return tr

    # host-fed captured twin
    net_h = _build(X)
    host = _train_captured(net_h, trainer_for(net_h), X, y, 4)

    # prefetched twin: identical batches arrive pre-sharded
    net_p = _build(X)
    tr_p = trainer_for(net_p)
    step = tr_p.capture(lambda a, b: _lossf(net_p(a), b).mean())
    step(X, y)                                  # compile (1st update)
    sync = registry().counter("prefetch_h2d_sync")
    pf = DevicePrefetcher(((Xh, yh) for _ in range(3)),
                          capture_spec=tr_p._kvstore)
    before = sync.value
    for xb, yb in pf:
        step(xb, yb)
        assert step.last_fallback_reason is None
    pf.close()
    assert sync.value == before                  # zero critical-path H2D
    assert step.cache_size == 1                  # no retrace either

    # same 4 updates, same batches -> bitwise-identical parameters
    for a, b in zip(_weights(net_p), host):
        np.testing.assert_array_equal(a, b)


def test_resharded_input_counted_not_fallen_back():
    """A device-COMMITTED batch in the WRONG layout still runs captured
    (explicit reshard), but the mismatch is recorded on
    cachedop_fallbacks{reason=resharded_input}."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    X, y = _data()
    mesh = make_mesh({"dp": 2})
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore="ici")
    tr._kvstore.set_mesh(mesh)
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    step(X, y)                                   # compile
    repl = NamedSharding(mesh, P())              # committed, NOT P('dp')
    xr = nd.NDArray(jax.device_put(X._data, repl))
    yr = nd.NDArray(jax.device_put(y._data, repl))
    c = registry().counter("cachedop_fallbacks", reason="resharded_input")
    before = c.value
    step(xr, yr)
    assert c.value - before == 2                 # both batch args resharded
    assert step.last_fallback_reason is None     # captured path, not fallback
    assert step.cache_size == 1


def test_kvstore_batch_sharding_matches_capture_spec():
    from jax.sharding import NamedSharding, PartitionSpec as P
    import mxnet_tpu as mx
    kv = mx.kv.create("ici")
    assert kv.batch_sharding() is None           # no mesh yet
    mesh = make_mesh({"dp": 2})
    kv.set_mesh(mesh)
    assert kv.batch_sharding() == NamedSharding(mesh, P("dp"))
