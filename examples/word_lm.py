"""Word-level language model: Embedding -> LSTM -> tied-weight softmax,
trained with truncated BPTT (reference: example/rnn/word_lm — the classic
MXNet RNN example, here on a synthetic corpus since the environment has no
network access).

Usage: python examples/word_lm.py [--epochs N] [--smoke]

TPU notes: the unrolled LSTM compiles to ONE lax.scan XLA program via
hybridize; hidden states are carried across BPTT windows and detached
(reference: detach() between truncated-BPTT segments).
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn


class RNNModel(gluon.HybridBlock):
    """Embedding -> LSTM -> Dense decoder with tied input/output weights
    (Press & Wolf 2017, used by the reference word_lm example)."""

    def __init__(self, vocab_size, embed_size, hidden_size, num_layers,
                 dropout=0.2, tie_weights=True):
        super().__init__()
        self.vocab_size = vocab_size
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, embed_size)
            self.rnn = rnn.LSTM(hidden_size, num_layers=num_layers,
                                dropout=dropout, input_size=embed_size)
            if tie_weights and embed_size == hidden_size:
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        params=self.encoder.params)
            else:
                self.decoder = nn.Dense(vocab_size, flatten=False)

    def forward(self, inputs, state):
        emb = self.drop(self.encoder(inputs))          # (T, B, E)
        output, state = self.rnn(emb, state)
        decoded = self.decoder(self.drop(output))      # (T, B, V)
        return decoded, state

    def begin_state(self, batch_size):
        return self.rnn.begin_state(batch_size=batch_size)


def synthetic_corpus(vocab_size, length, seed=0):
    """Markov-chain text: each word strongly predicts the next — a model
    that learns the transitions reaches low perplexity."""
    rs = np.random.RandomState(seed)
    trans = rs.randint(0, vocab_size, (vocab_size, 2))
    words = np.empty(length, np.int32)
    words[0] = 0
    for i in range(1, length):
        words[i] = trans[words[i - 1], rs.randint(2)]
    return words


def batchify(data, batch_size):
    nbatch = len(data) // batch_size
    return data[:nbatch * batch_size].reshape(batch_size, nbatch).T


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    corpus_len = 4096
    if args.smoke:
        args.epochs, corpus_len = 2, 2048
        args.vocab = 16

    mx.random.seed(0)
    data = batchify(synthetic_corpus(args.vocab, corpus_len),
                    args.batch_size)  # (T_total, B)

    model = RNNModel(args.vocab, args.embed, args.hidden, args.layers)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        state = model.begin_state(args.batch_size)
        total, count = 0.0, 0
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = nd.array(data[i:i + args.bptt])
            y = nd.array(data[i + 1:i + 1 + args.bptt])
            state = [s.detach() for s in state]  # truncate BPTT
            with autograd.record():
                logits, state = model(x, state)
                loss = loss_fn(logits.reshape((-1, args.vocab)),
                               y.reshape((-1,)))
            loss.backward()
            gluon.utils.clip_global_norm(
                [p.grad() for p in model.collect_params().values()
                 if p.grad_req != "null"], 0.25)
            trainer.step(1)
            total += float(loss.mean().asscalar()) * x.shape[0]
            count += x.shape[0]
        ppl = math.exp(total / count)
        print(f"epoch {epoch}: train ppl {ppl:.2f}")

    # a 2-successor markov chain has ideal ppl 2; random init starts at
    # ~vocab. Require clear learning signal even in smoke mode.
    limit = args.vocab * 0.5 if args.smoke else 3.0
    assert ppl < limit, f"LM failed to learn: ppl={ppl}"
    print("word_lm done")


if __name__ == "__main__":
    main()
