"""Object-detection ops (reference: src/operator/contrib/multibox_prior.cc,
multibox_target.cc, multibox_detection.cc, roi_align.cc — behavior parity,
rebuilt as static-shape XLA programs).

TPU-first choices: every op is a pure jax function with STATIC output shapes
(fixed top-k / max-detections budgets instead of dynamic filtering), so the
whole detection pipeline — backbone, heads, target assignment, decode + NMS —
compiles into one XLA executable. Suppression loops are `lax.fori_loop`s over
vectorised IoU rows, not per-box Python.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["box_iou", "box_encode", "box_decode", "multibox_prior",
           "multibox_target", "multibox_detection", "nms", "roi_align", "roi_align_mm"]


def box_iou(a, b):
    """IoU matrix. a: (N, 4), b: (M, 4) corner boxes -> (N, M)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.prod(jnp.clip(a[:, 2:] - a[:, :2], 0.0), -1)
    area_b = jnp.prod(jnp.clip(b[:, 2:] - b[:, :2], 0.0), -1)
    return inter / (area_a[:, None] + area_b[None, :] - inter + 1e-12)


def _to_center(boxes):
    wh = boxes[..., 2:] - boxes[..., :2]
    return jnp.concatenate([boxes[..., :2] + 0.5 * wh, wh], -1)


def box_encode(gt, anchors, variances=(0.1, 0.1, 0.2, 0.2)):
    """Encode corner gt boxes as (dx, dy, dw, dh) offsets from anchors."""
    g, a = _to_center(gt), _to_center(anchors)
    v = jnp.asarray(variances)
    dxy = (g[..., :2] - a[..., :2]) / (a[..., 2:] + 1e-12) / v[:2]
    dwh = jnp.log(jnp.clip(g[..., 2:] / (a[..., 2:] + 1e-12), 1e-12)) / v[2:]
    return jnp.concatenate([dxy, dwh], -1)


def box_decode(pred, anchors, variances=(0.1, 0.1, 0.2, 0.2)):
    """Inverse of box_encode -> corner boxes."""
    a = _to_center(anchors)
    v = jnp.asarray(variances)
    xy = pred[..., :2] * v[:2] * a[..., 2:] + a[..., :2]
    wh = jnp.exp(jnp.clip(pred[..., 2:] * v[2:], -10.0, 10.0)) * a[..., 2:]
    return jnp.concatenate([xy - 0.5 * wh, xy + 0.5 * wh], -1)


def multibox_prior(feat_h, feat_w, sizes=(1.0,), ratios=(1.0,),
                   offsets=(0.5, 0.5), steps=(-1.0, -1.0)):
    """Anchor boxes for one feature map, normalised corner format
    (reference: MultiBoxPrior). Returns (feat_h*feat_w*K, 4) numpy, where
    K = len(sizes) + len(ratios) - 1 (first size pairs with every ratio).
    `steps` (y, x) overrides the implicit 1/feat cell stride when > 0
    (upstream's explicit-stride attr used by SSD presets)."""
    ws, hs = [], []
    for i, s in enumerate(sizes):
        for j, r in enumerate(ratios):
            if i > 0 and j > 0:
                continue  # reference convention: K = |sizes| + |ratios| - 1
            ws.append(s * np.sqrt(r))
            hs.append(s / np.sqrt(r))
    ws, hs = np.asarray(ws), np.asarray(hs)
    step_y = steps[0] if steps[0] > 0 else 1.0 / feat_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / feat_w
    cy = (np.arange(feat_h) + offsets[0]) * step_y
    cx = (np.arange(feat_w) + offsets[1]) * step_x
    cyx = np.stack(np.meshgrid(cy, cx, indexing="ij"), -1)  # (H, W, 2)
    cyx = np.repeat(cyx.reshape(-1, 1, 2), len(ws), 1)      # (HW, K, 2)
    wh = np.stack([ws, hs], -1)[None]                        # (1, K, 2)
    boxes = np.concatenate([cyx[..., ::-1] - wh / 2, cyx[..., ::-1] + wh / 2],
                           -1)
    return boxes.reshape(-1, 4).astype(np.float32)


def multibox_target(anchors, labels, ious_threshold=0.5,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Assign ground truth to anchors (reference: MultiBoxTarget).

    anchors: (A, 4); labels: (B, M, 5) rows [cls, x0, y0, x1, y1], cls=-1 pad.
    Returns (cls_targets (B, A) int32 [0=bg, cls+1], loc_targets (B, A, 4),
    loc_mask (B, A, 1)).
    """
    def per_image(lab):
        gt_boxes = lab[:, 1:]
        gt_cls = lab[:, 0]
        valid = gt_cls >= 0
        iou = box_iou(anchors, gt_boxes)                 # (A, M)
        iou = jnp.where(valid[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, 1)                     # (A,)
        best_iou = jnp.max(iou, 1)
        # force-match: each valid gt claims its best anchor. Invalid
        # (padding) gts must not scatter at all — their argmax lands on
        # anchor 0 and a duplicate-index write could overwrite a valid
        # gt's claim — so their index is pushed out of bounds and dropped.
        best_anchor = jnp.argmax(iou, 0)                 # (M,)
        scatter_idx = jnp.where(valid, best_anchor, anchors.shape[0])
        forced = jnp.zeros(anchors.shape[0], bool)
        forced = forced.at[scatter_idx].set(True, mode="drop")
        gt_of_forced = jnp.zeros(anchors.shape[0], jnp.int32)
        gt_of_forced = gt_of_forced.at[scatter_idx].set(
            jnp.arange(lab.shape[0], dtype=jnp.int32), mode="drop")
        pos = jnp.logical_or(best_iou >= ious_threshold, forced)
        assigned = jnp.where(forced, gt_of_forced, best_gt.astype(jnp.int32))
        cls_t = jnp.where(pos, gt_cls[assigned].astype(jnp.int32) + 1, 0)
        loc_t = box_encode(gt_boxes[assigned], anchors, variances)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0)
        return cls_t, loc_t, pos[:, None].astype(loc_t.dtype)

    return jax.vmap(per_image)(labels)


def roi_align_mm(features, rois, out_size=(7, 7), spatial_scale=1.0,
                 sampling_ratio=2):
    """RoIAlign as two MXU contractions instead of a per-sample gather
    (perf lever for the Faster-RCNN head; same contract as roi_align).

    Bilinear sampling along each axis is a sparse (S, H) weight matrix
    with two nonzeros per row; building it as one-hot mixes turns the
    whole pool into samples = Wy @ F @ Wx^T — batched over rois it is
    einsum("rsh,chw,rtw->rcst"), which the MXU eats, where the gather
    formulation serializes through the memory system. Numerics match
    roi_align exactly (same clipping, same corner weights).
    """
    C, H, W = features.shape
    oh, ow = out_size
    sr = sampling_ratio

    def axis_weights(lo, length, bins, size):
        # sample centres along one axis: (bins*sr,)
        s = lo + (jnp.arange(bins * sr) + 0.5) * (length / bins / sr)
        s = jnp.clip(s, 0.0, size - 1.0)
        i0 = jnp.floor(s).astype(jnp.int32)
        i1 = jnp.minimum(i0 + 1, size - 1)
        f = s - i0
        eye = jnp.eye(size, dtype=features.dtype)
        return eye[i0] * (1.0 - f)[:, None] + eye[i1] * f[:, None]

    def one_roi(roi):
        x0, y0, x1, y1 = roi * spatial_scale
        rw = jnp.maximum(x1 - x0, 1.0)
        rh = jnp.maximum(y1 - y0, 1.0)
        wy = axis_weights(y0, rh, oh, H)          # (oh*sr, H)
        wx = axis_weights(x0, rw, ow, W)          # (ow*sr, W)
        return wy, wx

    WY, WX = jax.vmap(one_roi)(rois)              # (R, oh*sr, H) ...
    samples = jnp.einsum("rsh,chw,rtw->rcst", WY,
                         features.astype(WY.dtype), WX)
    R = rois.shape[0]
    return samples.reshape(R, C, oh, sr, ow, sr).mean((3, 5))


def nms(boxes, scores, iou_threshold=0.45, max_out=100, class_ids=None):
    """Static-shape greedy NMS. boxes (N,4), scores (N,) -> keep mask (N,)
    with at most max_out survivors. With `class_ids` (N,), only same-class
    boxes suppress each other (reference box_nms force_suppress=False)."""
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    iou = box_iou(boxes_s, boxes_s)
    if class_ids is not None:
        cls_s = class_ids[order]
        same = cls_s[:, None] == cls_s[None, :]
        iou = jnp.where(same, iou, 0.0)
    n = boxes.shape[0]

    def body(i, keep):
        # suppress j>i overlapping i if i survives
        sup = jnp.logical_and(iou[i] > iou_threshold, jnp.arange(n) > i)
        return jnp.where(jnp.logical_and(keep[i], sup), False, keep)

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones(n, bool))
    # cap at max_out best survivors
    rank = jnp.cumsum(keep_sorted.astype(jnp.int32)) - 1
    keep_sorted = jnp.logical_and(keep_sorted, rank < max_out)
    keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
    return keep


def multibox_detection(cls_probs, loc_preds, anchors, nms_threshold=0.45,
                       score_threshold=0.01, nms_topk=400, max_det=100,
                       variances=(0.1, 0.1, 0.2, 0.2)):
    """Decode + per-class NMS (reference: MultiBoxDetection).

    cls_probs: (B, C+1, A) softmaxed (class 0 = background);
    loc_preds: (B, A*4); anchors (A, 4).
    Returns (B, max_det, 6) rows [cls_id, score, x0, y0, x1, y1], cls_id=-1
    for empty slots — fixed-size output, XLA-friendly.
    """
    B, C1, A = cls_probs.shape
    n_cls = C1 - 1

    def per_image(probs, loc):
        boxes = box_decode(loc.reshape(A, 4), anchors, variances)  # (A, 4)

        def per_class(c_probs):
            s = jnp.where(c_probs > score_threshold, c_probs, 0.0)
            top_s, top_i = lax.top_k(s, min(nms_topk, A))
            b = boxes[top_i]
            keep = nms(b, top_s, nms_threshold, max_det)
            s_kept = jnp.where(keep & (top_s > 0), top_s, 0.0)
            return s_kept, b

        scores_c, boxes_c = jax.vmap(per_class)(probs[1:])  # (C, topk)
        flat_s = scores_c.reshape(-1)
        flat_b = boxes_c.reshape(-1, 4)
        cls_id = jnp.repeat(jnp.arange(n_cls), scores_c.shape[1])
        top_s, top_i = lax.top_k(flat_s, max_det)
        det = jnp.concatenate([
            jnp.where(top_s > 0, cls_id[top_i], -1)[:, None].astype(flat_b.dtype),
            top_s[:, None], flat_b[top_i]], -1)
        return det

    return jax.vmap(per_image)(cls_probs, loc_preds)


def roi_align(features, rois, out_size=(7, 7), spatial_scale=1.0,
              sampling_ratio=2):
    """ROIAlign (reference: roi_align.cc). features (C, H, W) NCHW single
    image; rois (R, 4) corner boxes in input coords -> (R, C, oh, ow).
    Bilinear sampling at sampling_ratio^2 points per bin, averaged."""
    C, H, W = features.shape
    oh, ow = out_size
    sr = sampling_ratio

    def one_roi(roi):
        x0, y0, x1, y1 = roi * spatial_scale
        rw = jnp.maximum(x1 - x0, 1.0)
        rh = jnp.maximum(y1 - y0, 1.0)
        bin_w, bin_h = rw / ow, rh / oh
        # sample grid: (oh*sr, ow*sr)
        ys = y0 + (jnp.arange(oh * sr) + 0.5) * (bin_h / sr)
        xs = x0 + (jnp.arange(ow * sr) + 0.5) * (bin_w / sr)
        yg, xg = jnp.meshgrid(ys, xs, indexing="ij")

        def bilinear(y, x):
            y = jnp.clip(y, 0.0, H - 1.0)
            x = jnp.clip(x, 0.0, W - 1.0)
            y0i = jnp.floor(y).astype(jnp.int32)
            x0i = jnp.floor(x).astype(jnp.int32)
            y1i = jnp.minimum(y0i + 1, H - 1)
            x1i = jnp.minimum(x0i + 1, W - 1)
            wy, wx = y - y0i, x - x0i
            v00 = features[:, y0i, x0i]
            v01 = features[:, y0i, x1i]
            v10 = features[:, y1i, x0i]
            v11 = features[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        samples = bilinear(yg, xg)                      # (C, oh*sr, ow*sr)
        samples = samples.reshape(C, oh, sr, ow, sr)
        return samples.mean((2, 4))

    return jax.vmap(one_roi)(rois)
