"""Device mesh utilities (reference role: kvstore device topology + NCCL
communicator setup; TPU-native: jax.sharding.Mesh over ICI).

Canonical axis names used across the framework:
  dp — data parallel        tp — tensor parallel
  pp — pipeline parallel    sp — sequence/context parallel
  ep — expert parallel
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "single_axis_mesh", "Mesh", "NamedSharding", "P",
           "replicated", "shard_batch", "local_mesh_devices"]


def local_mesh_devices():
    return jax.devices()


def make_mesh(axes, devices=None):
    """Create a Mesh from {axis_name: size}. Sizes must multiply to the
    device count; -1 infers one axis."""
    devices = devices if devices is not None else jax.devices()
    names = list(axes.keys())
    sizes = list(axes.values())
    n = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = max(n // known, 1)
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {n}")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def single_axis_mesh(axis="dp", n=None):
    devices = jax.devices()
    n = n or len(devices)
    return Mesh(np.asarray(devices[:n]), (axis,))


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard_batch(mesh, batch, axis="dp"):
    """Shard leading batch dim over the mesh axis."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)
