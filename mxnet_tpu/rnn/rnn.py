"""RNN checkpoint helpers (reference: python/mxnet/rnn/rnn.py).

Upstream converts between the fused cuDNN parameter blob and per-matrix
weights here (unpack/pack around every save/load). On TPU the fused
``sym.RNN`` node already binds the per-matrix names (rnn_cell.py), so
pack/unpack are identity — these wrappers keep the reference's API and
calling convention so classic training scripts port unchanged."""
from __future__ import annotations

from ..base import _as_list
from ..checkpoint import load_checkpoint, save_checkpoint

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """reference: rnn.save_rnn_checkpoint — save with cell weights in
    the unfused (per-matrix) layout."""
    args = dict(arg_params)
    for cell in _as_list(cells):
        args = cell.unpack_weights(args)
    save_checkpoint(prefix, epoch, symbol, args, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """reference: rnn.load_rnn_checkpoint."""
    sym, args, aux = load_checkpoint(prefix, epoch)
    for cell in _as_list(cells):
        args = cell.pack_weights(args)
    return sym, args, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback (reference: rnn.do_rnn_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
