"""gluon.contrib.rnn (reference: python/mxnet/gluon/contrib/rnn/
conv_rnn_cell.py + rnn_cell.py).

Convolutional LSTM cells (gates are convolutions over spatial feature maps),
VariationalDropoutCell (one dropout mask reused across all time steps), and
LSTMPCell (projected LSTM). All cells are step functions compatible with
`RecurrentCell.unroll`; under `hybridize`/`foreach` the whole unroll compiles
to one XLA program (`lax.scan` on the traced path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import autograd
from ...base import MXNetError
from ...ndarray.ndarray import _apply
from ...ops import nn_ops as K
from ..block import _layer_rng
from ..rnn.rnn_cell import RecurrentCell

__all__ = ["Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "VariationalDropoutCell", "LSTMPCell", "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell", "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _sigmoid(v):
    return jax.nn.sigmoid(v)


class _ConvLSTMCell(RecurrentCell):
    """ConvLSTM: x/h-to-gates are convolutions; state is a feature map
    (Shi et al. 2015; reference: gluon.contrib.rnn.Conv*DLSTMCell).

    input_shape is (C, *spatial) in the NC* layout, required up front like
    the reference (state shape must be known before the first step)."""
    _ndim = None
    _gmul = 4

    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, i2h_pad=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout=None, **kwargs):
        super().__init__(**kwargs)
        nd = self._ndim
        self._input_shape = tuple(input_shape)
        self._hc = hidden_channels
        self._ik = (i2h_kernel,) * nd if isinstance(i2h_kernel, int) \
            else tuple(i2h_kernel)
        self._hk = (h2h_kernel,) * nd if isinstance(h2h_kernel, int) \
            else tuple(h2h_kernel)
        if any(k % 2 == 0 for k in self._hk):
            raise MXNetError("h2h_kernel must be odd ('same' padding "
                             "preserves the state's spatial shape)")
        self._ip = tuple(k // 2 for k in self._ik) if i2h_pad is None \
            else ((i2h_pad,) * nd if isinstance(i2h_pad, int)
                  else tuple(i2h_pad))
        self._hp = tuple(k // 2 for k in self._hk)
        in_c = self._input_shape[0]
        g = self._gmul      # gates per hidden channel (LSTM 4, GRU 3, RNN 1)
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(g * hidden_channels, in_c) + self._ik,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(g * hidden_channels, hidden_channels) + self._hk,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(g * hidden_channels,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(g * hidden_channels,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        spatial = tuple(
            (s + 2 * p - k) + 1
            for s, p, k in zip(self._input_shape[1:], self._ip, self._ik))
        shape = (batch_size, self._hc) + spatial
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._ndim:]},
                {"shape": shape, "__layout__": "NC" + "DHW"[-self._ndim:]}]

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h, c = states

        def fn(xv, hv, cv, wi, wh, bi, bh, _ip=self._ip, _hp=self._hp,
               _hc=self._hc):
            gates = (K.convolution(xv, wi, bi, stride=1, pad=_ip)
                     + K.convolution(hv, wh, bh, stride=1, pad=_hp))
            i, f, g, o = jnp.split(gates, 4, axis=1)
            new_c = _sigmoid(f) * cv + _sigmoid(i) * jnp.tanh(g)
            new_h = _sigmoid(o) * jnp.tanh(new_c)
            return new_h, new_c

        new_h, new_c = _apply(fn, [x, h, c, i2h_weight, h2h_weight,
                                   i2h_bias, h2h_bias], n_out=2)
        return new_h, [new_h, new_c]


class Conv1DLSTMCell(_ConvLSTMCell):
    _ndim = 1


class Conv2DLSTMCell(_ConvLSTMCell):
    _ndim = 2


class Conv3DLSTMCell(_ConvLSTMCell):
    _ndim = 3


class _ConvRNNCell(_ConvLSTMCell):
    """Conv RNN cell, tanh/relu (reference: contrib.rnn.Conv*DRNNCell)."""
    _gmul = 1

    def __init__(self, *args, activation="tanh", **kwargs):
        super().__init__(*args, **kwargs)
        if activation not in ("tanh", "relu"):
            raise MXNetError(f"Conv RNN cell: activation must be "
                             f"tanh/relu, got {activation!r}")
        self._act = activation

    def state_info(self, batch_size=0):
        return super().state_info(batch_size)[:1]

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        (h,) = states

        def fn(xv, hv, wi, wh, bi, bh, _ip=self._ip, _hp=self._hp,
               _a=self._act):
            z = (K.convolution(xv, wi, bi, stride=1, pad=_ip)
                 + K.convolution(hv, wh, bh, stride=1, pad=_hp))
            return jnp.tanh(z) if _a == "tanh" else jnp.maximum(z, 0)

        new_h = _apply(fn, [x, h, i2h_weight, h2h_weight, i2h_bias,
                            h2h_bias])
        return new_h, [new_h]


class Conv1DRNNCell(_ConvRNNCell):
    _ndim = 1


class Conv2DRNNCell(_ConvRNNCell):
    _ndim = 2


class Conv3DRNNCell(_ConvRNNCell):
    _ndim = 3


class _ConvGRUCell(_ConvLSTMCell):
    """Conv GRU cell, [r, z, n] gate order (reference:
    contrib.rnn.Conv*DGRUCell)."""
    _gmul = 3

    def state_info(self, batch_size=0):
        return super().state_info(batch_size)[:1]

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        (h,) = states

        def fn(xv, hv, wi, wh, bi, bh, _ip=self._ip, _hp=self._hp):
            xg = K.convolution(xv, wi, bi, stride=1, pad=_ip)
            hg = K.convolution(hv, wh, bh, stride=1, pad=_hp)
            xr, xz, xn = jnp.split(xg, 3, axis=1)
            hr, hz, hn = jnp.split(hg, 3, axis=1)
            r = _sigmoid(xr + hr)
            z = _sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * hv

        new_h = _apply(fn, [x, h, i2h_weight, h2h_weight, i2h_bias,
                            h2h_bias])
        return new_h, [new_h]


class Conv1DGRUCell(_ConvGRUCell):
    _ndim = 1


class Conv2DGRUCell(_ConvGRUCell):
    _ndim = 2


class Conv3DGRUCell(_ConvGRUCell):
    _ndim = 3


class VariationalDropoutCell(RecurrentCell):
    """Wrap a cell so input/state/output dropout masks are sampled ONCE per
    sequence and reused at every step (Gal & Ghahramani 2016; reference:
    gluon.contrib.rnn.VariationalDropoutCell). Call reset() between
    sequences to resample."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self._di, self._ds, self._do = drop_inputs, drop_states, drop_outputs
        self.reset()

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def reset(self):
        # draw ONE concrete base key now (reset runs eagerly); per-kind
        # keys are fold_in-derived at use, never cached — caching anything
        # produced under a jax trace would leak tracers into later calls.
        # The same key regenerates the identical mask at every step (XLA
        # dedups the bernoulli inside one compiled unroll).
        self._base_key = _layer_rng()
        self.base_cell.reset()

    @staticmethod
    def _kind_id(kind):
        if kind == "i":
            return 0
        if kind == "o":
            return 1
        return 2 + int(kind[1:])  # "s{k}" state masks

    def _mask(self, kind, rate, x):
        if not rate or not autograd.is_training():
            return x
        m = _apply(lambda a, _k=self._base_key, _id=self._kind_id(kind),
                   _p=rate: (
            jax.random.bernoulli(jax.random.fold_in(_k, _id), 1 - _p,
                                 a.shape) / (1 - _p)
        ).astype(a.dtype), [x])
        return x * m

    def __call__(self, x, states):
        x = self._mask("i", self._di, x)
        states = [self._mask(f"s{k}", self._ds, s)
                  for k, s in enumerate(states)]
        out, next_states = self.base_cell(x, states)
        out = self._mask("o", self._do, out)
        return out, next_states

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError("VariationalDropoutCell dispatches to "
                                  "its base cell")


class LSTMPCell(RecurrentCell):
    """LSTM with a projected recurrent state (Sak et al. 2014; reference:
    gluon.contrib.rnn.LSTMPCell). States: [r (B, projection), c (B, hidden)];
    the h2h matmul runs on the smaller projected state — the same
    wide-matmul-friendly shape the MXU wants."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def _infer_shapes(self, x, *args):
        self.i2h_weight._finish_deferred_init(
            (4 * self._hidden_size, x.shape[-1]))
        self._input_size = x.shape[-1]

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        r, c = states

        def fn(xv, rv, cv, wi, wh, wr, bi, bh, _h=self._hidden_size):
            gates = (xv @ wi.T + bi) + (rv @ wh.T + bh)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            new_c = _sigmoid(f) * cv + _sigmoid(i) * jnp.tanh(g)
            new_h = _sigmoid(o) * jnp.tanh(new_c)
            new_r = new_h @ wr.T
            return new_r, new_c

        new_r, new_c = _apply(fn, [x, r, c, i2h_weight, h2h_weight,
                                   h2r_weight, i2h_bias, h2h_bias], n_out=2)
        return new_r, [new_r, new_c]
