"""Serving decode runtime: ONE cached decode executable + ONE cached
prefill executable over device-resident paged KV state (ISSUE 6).

The decode step is compiled exactly once per server: every shape in the
program is static — `(slots, num_pages, page_size)` for the self-attention
page pools, `(slots, max_src_len)` for the per-slot encoder memory — and
everything that changes between steps (slot occupancy, page tables,
per-slot lengths, current tokens) rides as ARGUMENTS, so ragged batch
composition never retraces (`decode_traces` stays 1; enforced by
tools/check_dispatch.py's serve phase in tier-1). The K/V page pools are
DONATED to the executable, so the per-step page writes are in-place
scatters into the same device buffers — the paged cache never doubles in
HBM.

Slot conventions (shared with serve.scheduler):

  * inactive slots route their scatter writes to the pool's reserved null
    page 0 and their outputs are garbage the scheduler never reads — no
    branches on occupancy inside the program;
  * `lens[s]` is the number of cached positions BEFORE this step — also
    the position index of the token being decoded (BOS decodes at 0);
  * page tables are padded with the null page, so unused entries gather
    valid memory.

The per-layer math is `models.transformer`'s factored decode core
(`decode_embed` / `decoder_layer_*`), and the self-attention is
`ops.pallas_kernels.ragged_paged_attention` — the Pallas kernel on TPU,
the shared-math lax gather on the CPU mesh — so a paged decode is
bitwise-identical to the dense-cache `decode_step` on equal context
width (tests/test_serve.py pins this).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import profiler
from ..base import MXNetError
from ..models.transformer import (decode_embed, decode_project,
                                  decoder_layer_qkv, decoder_layer_self_post,
                                  decoder_layer_cross,
                                  decoder_layer_cross_multi,
                                  decoder_layer_ffn,
                                  encode_memory, precompute_memory_kv)
from ..observability import tracer as _tracer
from ..observability import compilex as _compilex
from ..ops.pallas_kernels import ragged_paged_attention
from .kv_pages import NULL_PAGE

__all__ = ["DecodeRuntime", "MemoryStateLost"]


class MemoryStateLost(MXNetError):
    """A prefill dispatch failed AFTER consuming its donated encoder-
    memory buffers: every slot's cross-attention state is gone, not just
    the request being admitted. The runtime has already rebuilt zeroed
    buffers; the scheduler must restart ALL in-flight requests (their
    re-admission re-prefills each slot)."""


class DecodeRuntime:
    """Device state + the two cached executables of one serving engine.

    weights / enc_weights: `models.transformer.decoder_weights` /
    `encoder_weights` snapshots. All device state (K/V page pools, per-slot
    encoder memory) lives on this object; the scheduler only ever hands it
    host-side int arrays."""

    def __init__(self, weights, enc_weights, slots, num_pages, page_size,
                 max_pages_per_slot, max_src_len, width=1):
        u = weights["embed"].shape[1]
        h = weights["num_heads"]
        if u % h:
            raise MXNetError("units not divisible by heads")
        self._w = weights
        self._ew = enc_weights
        self.slots = int(slots)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.max_src_len = int(max_src_len)
        self._h = h
        self._dh = u // h
        self._n_layers = len(weights["layers"])
        max_pos = weights["pos"].shape[0]
        if self.max_pages_per_slot * self.page_size > max_pos:
            raise MXNetError(
                f"page budget covers {self.max_pages_per_slot * page_size} "
                f"positions but the decoder pos table has only {max_pos}")
        enc_pos = enc_weights["pos"].shape[0]
        if self.max_src_len > enc_pos:
            raise MXNetError(
                f"max_src_len {self.max_src_len} exceeds the encoder pos "
                f"table ({enc_pos}) — every prefill would fail")
        dtype = weights["embed"].dtype
        shape = (self._n_layers, self.num_pages, self.page_size, h, self._dh)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        self.reset_mem()
        self.width = int(width)
        if self.width < 1:
            raise MXNetError("decode width must be >= 1")
        # retrace telemetry: the python bodies run ONLY while jax traces,
        # so these counters are exactly the number of compilations — the
        # check_dispatch serve gate asserts they stay at 1 across every
        # slot-occupancy / page-table variation (and, for the widened
        # verify executable, across every draft-acceptance variation)
        self.decode_traces = 0
        self.prefill_traces = 0
        self.verify_traces = 0
        # compile observatory: prefill vs decode publish as separate
        # executables (`compiles{executable=serve_decode}` == number of
        # decode compilations, the same invariant decode_traces counts —
        # check_fusion budgets the decode HLO, test_serve pins zero warm
        # recompiles against these counters)
        self._decode_fn = _compilex.instrument(
            jax.jit(self._decode_program, donate_argnums=(0, 1)),
            "serve_decode")
        self._prefill_fn = _compilex.instrument(
            jax.jit(self._prefill_program, donate_argnums=(0, 1, 2)),
            "serve_prefill")
        self._remap_fn = _compilex.instrument(
            jax.jit(lambda kp, vp, perm: (kp[:, perm], vp[:, perm]),
                    donate_argnums=(0, 1)),
            "serve_page_remap")
        # the WIDENED verify executable (ISSUE 12): width > 1 servers run
        # every decode turn through one (slots, width) program — drafted
        # tokens verified by a single batched target pass, chunked prompt
        # prefill teacher-forced width tokens at a time. Static shapes;
        # per-slot ragged window lengths ride as arguments, so varying
        # draft acceptance never retraces (verify_traces stays 1).
        self._verify_fn = None
        if self.width > 1:
            self._verify_fn = _compilex.instrument(
                jax.jit(self._verify_program, donate_argnums=(0, 1)),
                "serve_verify")

    # ------------------------------------------------------- programs
    def _decode_program(self, k_pages, v_pages, page_tables, lens, tok,
                        active, mem_k, mem_v, mem_vl):
        self.decode_traces += 1
        w, h, psize = self._w, self._h, self.page_size
        s_n = tok.shape[0]
        x = decode_embed(w, tok, lens)                       # (S, U)
        rows = jnp.arange(s_n)
        page = page_tables[rows, lens // psize]
        page = jnp.where(active > 0, page, NULL_PAGE)
        off = lens % psize
        for li, L in enumerate(w["layers"]):
            q, k, v = decoder_layer_qkv(L, x)
            qh = q.reshape(s_n, h, self._dh)
            kh = k.reshape(s_n, h, self._dh)
            vh = v.reshape(s_n, h, self._dh)
            k_pages = k_pages.at[li, page, off].set(kh)
            v_pages = v_pages.at[li, page, off].set(vh)
            a = ragged_paged_attention(qh, k_pages[li], v_pages[li],
                                       page_tables, lens + 1)
            x = decoder_layer_self_post(L, x, a.reshape(s_n, h * self._dh))
            x = decoder_layer_cross(L, h, x, mem_k[li], mem_v[li], mem_vl)
            x = decoder_layer_ffn(L, x)
        logits = decode_project(w, x)
        next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return k_pages, v_pages, next_tok, logits

    def _verify_program(self, k_pages, v_pages, page_tables, lens, toks,
                        qlens, active, mem_k, mem_v, mem_vl):
        """The widened decode step: toks (S, W) window tokens per slot at
        positions lens..lens+W-1, qlens (S,) valid window lengths (ragged
        — rows past qlen scatter to the null page and their outputs are
        garbage the scheduler never commits). Returns logits for EVERY
        window position, so one dispatch verifies a whole drafted run."""
        self.verify_traces += 1
        w, h, psize = self._w, self._h, self.page_size
        s_n, width = toks.shape
        npages = page_tables.shape[1]
        rows = jnp.arange(s_n)
        pos = lens[:, None] + jnp.arange(width, dtype=lens.dtype)[None, :]
        x = decode_embed(w, toks, pos)                   # (S, W, U)
        slot_page = jnp.minimum(pos // psize, npages - 1)
        page = page_tables[rows[:, None], slot_page]     # (S, W)
        valid = (jnp.arange(width)[None, :] < qlens[:, None]) \
            & (active[:, None] > 0)
        page = jnp.where(valid, page, NULL_PAGE)
        off = pos % psize
        for li, L in enumerate(w["layers"]):
            q, k, v = decoder_layer_qkv(L, x)
            qh = q.reshape(s_n, width, h, self._dh)
            kh = k.reshape(s_n, width, h, self._dh)
            vh = v.reshape(s_n, width, h, self._dh)
            k_pages = k_pages.at[li, page, off].set(kh)
            v_pages = v_pages.at[li, page, off].set(vh)
            # query i sees positions 0..lens+i (its own included): the
            # ragged-query-length form of the shared paged attention
            a = ragged_paged_attention(qh, k_pages[li], v_pages[li],
                                       page_tables, lens + 1)
            x = decoder_layer_self_post(
                L, x, a.reshape(s_n, width, h * self._dh))
            x = decoder_layer_cross_multi(L, h, x, mem_k[li], mem_v[li],
                                          mem_vl)
            x = decoder_layer_ffn(L, x)
        logits = decode_project(w, x)                    # (S, W, V)
        next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return k_pages, v_pages, next_tok, logits

    def _prefill_program(self, mem_k, mem_v, mem_vl, src, src_len, slot):
        self.prefill_traces += 1
        memory = encode_memory(self._ew, src, src_len)       # (1, Ssrc, U)
        kv = precompute_memory_kv(self._w, memory)
        mk = jnp.stack([k for k, _ in kv])   # (n_layers, 1, H, Ssrc, dh)
        mv = jnp.stack([v for _, v in kv])
        mem_k = lax.dynamic_update_slice(mem_k, mk, (0, slot, 0, 0, 0))
        mem_v = lax.dynamic_update_slice(mem_v, mv, (0, slot, 0, 0, 0))
        mem_vl = lax.dynamic_update_slice(mem_vl,
                                          src_len.astype(jnp.int32), (slot,))
        return mem_k, mem_v, mem_vl

    # ---------------------------------------------------------- calls
    def prefill(self, slot, src_tokens, src_len=None):
        """Encode one request's source into decode slot `slot`: pads to
        the static (1, max_src_len) shape, runs the cached prefill
        executable (encoder + cross-attention K/V projection + slot
        write, ONE dispatch) against the donated memory buffers."""
        src = np.asarray(src_tokens, np.int32).reshape(-1)
        if src_len is None:
            src_len = src.size
        if src.size > self.max_src_len:
            raise MXNetError(f"source length {src.size} exceeds the "
                             f"server's max_src_len {self.max_src_len}")
        padded = np.zeros((1, self.max_src_len), np.int32)
        padded[0, :src.size] = src
        profiler.record_dispatch("serve_prefill")
        old = (self.mem_k, self.mem_v, self.mem_vl)
        try:
            with _tracer.span("serve.prefill", cat="serve",
                              args={"slot": int(slot),
                                    "src_len": int(src_len)}):
                self.mem_k, self.mem_v, self.mem_vl = self._prefill_fn(
                    self.mem_k, self.mem_v, self.mem_vl,
                    jnp.asarray(padded), jnp.asarray([src_len], jnp.int32),
                    jnp.int32(slot))
        except Exception as e:
            # donation hazard (same rule as cachedop): a failure that
            # consumed the donated memory buffers loses EVERY slot's
            # encoder state, not just this request's — rebuild zeroed
            # buffers and tell the scheduler to restart the in-flight
            # requests. A failure that left the buffers alive (trace/
            # compile-stage, CPU no-op donation) stays per-request.
            if any(getattr(a, "is_deleted", lambda: False)()
                   for a in old):
                self.reset_mem()
                raise MemoryStateLost(
                    f"prefill failed after consuming donated memory "
                    f"buffers: {type(e).__name__}: {e}") from e
            raise

    def decode(self, page_tables, lens, tok, active):
        """One decode step for every slot (ONE dispatch): writes each
        active slot's K/V into its current page in place, runs the shared
        ragged-paged-attention launch, returns (next_tok (S,) host int32,
        logits (S, V) device array)."""
        profiler.record_dispatch("serve_decode")
        self.k_pages, self.v_pages, next_tok, logits = self._decode_fn(
            self.k_pages, self.v_pages,
            jnp.asarray(page_tables, jnp.int32),
            jnp.asarray(lens, jnp.int32), jnp.asarray(tok, jnp.int32),
            jnp.asarray(active, jnp.int32),
            self.mem_k, self.mem_v, self.mem_vl)
        return np.asarray(next_tok), logits

    def decode_multi(self, page_tables, lens, toks, qlens, active):
        """One WIDENED decode turn for every slot (still ONE dispatch):
        writes each active slot's window K/V into its pages in place,
        runs the shared ragged-paged-attention launch with per-slot
        ragged query lengths, returns (next_tok (S, W) host int32,
        logits (S, W, V) device array). Greedy commits derived from
        these outputs are identical to `decode` run token-by-token —
        the bitwise-greedy contract tests/test_serve.py pins."""
        if self._verify_fn is None:
            raise MXNetError("decode_multi needs width > 1 (construct "
                             "DecodeRuntime(width=k+1))")
        profiler.record_dispatch("serve_decode")
        self.k_pages, self.v_pages, next_tok, logits = self._verify_fn(
            self.k_pages, self.v_pages,
            jnp.asarray(page_tables, jnp.int32),
            jnp.asarray(lens, jnp.int32), jnp.asarray(toks, jnp.int32),
            jnp.asarray(qlens, jnp.int32), jnp.asarray(active, jnp.int32),
            self.mem_k, self.mem_v, self.mem_vl)
        return np.asarray(next_tok), logits

    def remap_pages(self, mapping):
        """Apply a `PagePool.defrag()` renumbering to the device pools:
        one gather-permutation dispatch (donated, in-place)."""
        if not mapping:
            return
        perm = np.arange(self.num_pages)
        for old, new in mapping.items():
            perm[new] = old
        profiler.record_dispatch("serve_page_remap")
        self.k_pages, self.v_pages = self._remap_fn(
            self.k_pages, self.v_pages, jnp.asarray(perm))

    def reset_pages(self):
        """Drop ALL cached KV state (used by the scheduler's catastrophic
        failure path after an executable error, when page contents can no
        longer be trusted)."""
        shape = self.k_pages.shape
        dtype = self.k_pages.dtype
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)

    def reset_mem(self):
        """Rebuild zeroed per-slot encoder memory (after a prefill
        failure consumed the donated buffers)."""
        shape = (self._n_layers, self.slots, self._h, self.max_src_len,
                 self._dh)
        self.mem_k = jnp.zeros(shape, self._w["embed"].dtype)
        self.mem_v = jnp.zeros(shape, self._w["embed"].dtype)
        self.mem_vl = jnp.zeros((self.slots,), jnp.int32)
