"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

Required by the build brief (ep sharding in dryrun_multichip). Switch-style
top-1 routing with capacity factor; expert FFN weights carry a leading
expert axis sharded P('ep'), dispatch/combine are einsums whose expert
contraction XLA partitions into all-to-alls over ICI.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["moe_ffn", "init_moe_params", "moe_param_specs"]


def init_moe_params(key, n_experts, d_model, d_ff, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / (d_model ** 0.5)
    scale_out = 1.0 / (d_ff ** 0.5)
    return {
        "gate": (jax.random.normal(k1, (d_model, n_experts)) * scale_in
                 ).astype(dtype),
        "wi": (jax.random.normal(k2, (n_experts, d_model, d_ff)) * scale_in
               ).astype(dtype),
        "wo": (jax.random.normal(k3, (n_experts, d_ff, d_model)) * scale_out
               ).astype(dtype),
    }


def moe_param_specs(ep_axis="ep"):
    return {"gate": P(), "wi": P(ep_axis, None, None),
            "wo": P(ep_axis, None, None)}


def moe_ffn(params, x, capacity_factor=1.25, activation=jax.nn.gelu):
    """x: (B, T, D) -> (B, T, D), plus aux load-balancing loss.

    Dense dispatch (Mesh-TensorFlow style): dispatch mask (B,T,E,C) einsummed
    against expert weights; the E axis is sharded over 'ep'.
    """
    b, t, d = x.shape
    e = params["gate"].shape[1]
    tokens = b * t
    capacity = max(int(capacity_factor * tokens / e), 1)

    logits = jnp.einsum("btd,de->bte", x, params["gate"],
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)              # (B,T)
    expert_mask = jax.nn.one_hot(expert_idx, e)          # (B,T,E)
    gate_val = jnp.sum(probs * expert_mask, axis=-1)     # (B,T)

    # position of each token within its expert's buffer
    flat_mask = expert_mask.reshape(tokens, e)
    pos = jnp.cumsum(flat_mask, axis=0) * flat_mask - 1.0   # (BT, E)
    keep = pos < capacity
    pos = jnp.where(keep, pos, 0.0).astype(jnp.int32)
    flat_mask = flat_mask * keep

    # load-balance aux loss (Switch Transformer eq. 4)
    density = jnp.mean(expert_mask.reshape(tokens, e), axis=0)
    density_proxy = jnp.mean(probs.reshape(tokens, e), axis=0)
    aux_loss = e * jnp.sum(density * density_proxy)

    dispatch = flat_mask[:, :, None] * jax.nn.one_hot(pos, capacity)  # BT,E,C
    dispatch = dispatch.reshape(b, t, e, capacity)
    gate_dispatch = dispatch * gate_val[:, :, None, None]

    # route tokens to expert buffers: (E, C, D)
    expert_in = jnp.einsum("btec,btd->ecd", dispatch, x)
    h = activation(jnp.einsum("ecd,edf->ecf", expert_in, params["wi"],
                              preferred_element_type=jnp.float32)
                   .astype(x.dtype))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["wo"],
                            preferred_element_type=jnp.float32
                            ).astype(x.dtype)
    out = jnp.einsum("btec,ecd->btd", gate_dispatch, expert_out)
    return out, aux_loss
