"""gluon.contrib.estimator: fit loop + event handlers (reference:
python/mxnet/gluon/contrib/estimator)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import Trainer, loss as loss_mod, nn
from mxnet_tpu.gluon.contrib import estimator as est
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader


def _toy():
    rs = np.random.RandomState(0)
    x = rs.randn(128, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize()
    data = DataLoader(ArrayDataset(nd.array(x), nd.array(y)), batch_size=32)
    return net, data, x, y


def test_estimator_fit_converges():
    net, data, x, y = _toy()
    e = est.Estimator(net, loss_mod.SoftmaxCrossEntropyLoss(),
                      train_metrics=["accuracy"],
                      trainer=Trainer(net.collect_params(), "adam",
                                      {"learning_rate": 0.01}))
    e.fit(data, epochs=12)
    res = e.evaluate(data, ["accuracy"])
    assert res["accuracy"] > 0.95, res


def test_estimator_stop_by_batches():
    net, data, *_ = _toy()
    e = est.Estimator(net, loss_mod.SoftmaxCrossEntropyLoss())
    seen = []

    class Counter(est.BatchEnd):
        def batch_end(self, estimator, **kwargs):
            seen.append(1)

    e.fit(data, batches=3, event_handlers=[Counter()])
    assert len(seen) == 3


def test_checkpoint_and_early_stopping():
    net, data, *_ = _toy()
    acc = mx.metric.create("accuracy")
    e = est.Estimator(net, loss_mod.SoftmaxCrossEntropyLoss(),
                      train_metrics=[acc],
                      trainer=Trainer(net.collect_params(), "adam",
                                      {"learning_rate": 0.01}))
    with tempfile.TemporaryDirectory() as d:
        ckpt = est.CheckpointHandler(d, monitor=acc, mode="max",
                                     save_best=True)
        early = est.EarlyStoppingHandler(monitor=acc, mode="max",
                                         patience=2)
        e.fit(data, epochs=10, event_handlers=[ckpt, early])
        assert os.path.exists(os.path.join(d, "model-epoch1.params"))
        assert os.path.exists(os.path.join(d, "model-best.params"))
        # early stopping kicks in once accuracy plateaus at 1.0
        assert early.best is not None
    # loss metric auto-added and populated
    lm = [m for m in e.train_metrics if "loss" in m.name][0]
    assert np.isfinite(lm.get()[1])


def test_validation_handler_runs():
    net, data, *_ = _toy()
    runs = []
    e = est.Estimator(net, loss_mod.SoftmaxCrossEntropyLoss())
    vh = est.ValidationHandler(data, lambda d: runs.append(e.evaluate(d)))
    e.fit(data, epochs=2, event_handlers=[vh])
    assert len(runs) == 2 and "accuracy" in runs[0]


def test_val_metrics_monitored_and_handler_reuse():
    """Validation metrics are observable (monitored by EarlyStopping) and
    handlers reset across fit() calls (round-2 review findings)."""
    net, data, *_ = _toy()
    e = est.Estimator(net, loss_mod.SoftmaxCrossEntropyLoss(),
                      val_metrics=["accuracy"],
                      trainer=Trainer(net.collect_params(), "adam",
                                      {"learning_rate": 0.01}))
    val_acc = e.val_metrics[0]
    early = est.EarlyStoppingHandler(monitor=val_acc, mode="max",
                                     patience=1)
    e.fit(data, val_data=data, epochs=6, event_handlers=[early])
    assert len(e.val_results) >= 1          # results recorded
    assert val_acc.get()[1] > 0.5           # monitored object updated
    first_best = early.best
    # reuse the same handler: state must reset, training must not
    # insta-stop from stale stop_training
    seen = []

    class Counter(est.BatchEnd):
        def batch_end(self, estimator, **kwargs):
            seen.append(1)

    e.fit(data, val_data=data, epochs=2, event_handlers=[early, Counter()])
    assert len(seen) >= 8                   # 2 epochs x 4 batches ran
    assert early.current_epoch <= 2


def test_metric_handler_ordering():
    """User handlers at batch_end see CURRENT-batch metric values."""
    net, data, *_ = _toy()
    acc = mx.metric.create("accuracy")
    e = est.Estimator(net, loss_mod.SoftmaxCrossEntropyLoss(),
                      train_metrics=[acc])
    counts = []

    class Probe(est.BatchEnd):
        def batch_end(self, estimator, **kwargs):
            counts.append(acc.num_inst)

    e.fit(data, batches=3, event_handlers=[Probe()])
    # metric already includes the current batch (32 samples each) when the
    # user handler fires
    assert counts == [32, 64, 96]
