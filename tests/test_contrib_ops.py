"""Reference `contrib` op namespace parity (VERDICT r3 item 3; upstream:
src/operator/contrib/*.cc). Every op is exercised from nd AND sym, with
parity pinned against closed forms (lax conv, numpy FFT, hand-computed
sketches) rather than against our own kernels."""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


# --------------------------------------------------------------- fft / ifft
def test_fft_matches_numpy_interleaved():
    x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    out = nd.contrib.fft(nd.array(x)).asnumpy()
    assert out.shape == (3, 16)
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(out[:, 0::2], ref.real, atol=1e-4)
    np.testing.assert_allclose(out[:, 1::2], ref.imag, atol=1e-4)


def test_ifft_unnormalised_roundtrip():
    """Upstream contrib.ifft does NOT divide by d: ifft(fft(x)) == d*x."""
    x = np.random.RandomState(1).randn(2, 16).astype(np.float32)
    back = nd.contrib.ifft(nd.contrib.fft(nd.array(x))).asnumpy()
    np.testing.assert_allclose(back, 16 * x, rtol=1e-4, atol=1e-3)


# ------------------------------------------------------------- count_sketch
def test_count_sketch_closed_form():
    d, out_dim = 6, 4
    rs = np.random.RandomState(2)
    x = rs.randn(3, d).astype(np.float32)
    h = rs.randint(0, out_dim, size=d)
    s = rs.choice([-1.0, 1.0], size=d).astype(np.float32)
    out = nd.contrib.count_sketch(nd.array(x), nd.array(h),
                                  nd.array(s), out_dim).asnumpy()
    ref = np.zeros((3, out_dim), np.float32)
    for j in range(d):
        ref[:, h[j]] += s[j] * x[:, j]
    np.testing.assert_allclose(out, ref, atol=1e-5)


# ---------------------------------------------------- DeformableConvolution
def _ref_conv(x, w, stride, pad):
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=[(pad[0], pad[0]),
                                              (pad[1], pad[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def test_deformable_conv_zero_offset_is_conv():
    """Zero offsets reduce deformable conv to a standard convolution —
    the upstream-documented identity, pinned against lax.conv."""
    rs = np.random.RandomState(3)
    x = rs.randn(2, 4, 9, 9).astype(np.float32)
    w = rs.randn(5, 4, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 3 * 3, 9, 9), np.float32)
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        pad=(1, 1)).asnumpy()
    ref = np.asarray(_ref_conv(jnp.asarray(x), jnp.asarray(w), (1, 1),
                               (1, 1)))
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)


def test_deformable_conv_integer_offset_shifts_sampling():
    """A constant integer offset (dy=0, dx=1) must equal convolving the
    x-shifted image (checks the [dy, dx] channel layout)."""
    rs = np.random.RandomState(4)
    x = rs.randn(1, 2, 8, 8).astype(np.float32)
    w = rs.randn(3, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 8, 8), np.float32)
    off[:, 1::2] = 1.0          # dx = +1 for every tap
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        pad=(1, 1)).asnumpy()
    x_shift = np.zeros_like(x)
    x_shift[..., :-1] = x[..., 1:]       # sample at x+1 == image shifted left
    ref = np.asarray(_ref_conv(jnp.asarray(x_shift), jnp.asarray(w), (1, 1),
                               (1, 1)))
    # interior only: the zero-padding border differs (shifted-image pad
    # column vs out-of-image samples) — same sampling everywhere else
    np.testing.assert_allclose(out[..., 1:-1, 1:-1], ref[..., 1:-1, 1:-1],
                               rtol=1e-3, atol=1e-3)


def test_deformable_conv_groups_and_stride():
    rs = np.random.RandomState(5)
    x = rs.randn(1, 4, 8, 8).astype(np.float32)
    w = rs.randn(4, 2, 3, 3).astype(np.float32)     # num_group=2
    off = np.zeros((1, 2 * 2 * 9, 3, 3), np.float32)  # dg=2, OH=OW=3
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        stride=(2, 2), num_group=2, num_deformable_group=2).asnumpy()
    ref = np.asarray(lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), window_strides=(2, 2),
        padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=2))
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)


# ----------------------------------------------------------------- ROIAlign
def test_roi_align_batch_indexing_and_identity():
    """A stride-1 unit-scale ROI over an aligned grid reproduces bilinear
    averages; batch_idx selects the right image; idx<0 zeros the output."""
    rs = np.random.RandomState(6)
    feats = rs.randn(2, 3, 10, 10).astype(np.float32)
    rois = np.array([[0, 2.0, 2.0, 6.0, 6.0],
                     [1, 0.0, 0.0, 4.0, 4.0],
                     [-1, 0.0, 0.0, 4.0, 4.0]], np.float32)
    out = nd.contrib.ROIAlign(nd.array(feats), nd.array(rois),
                              pooled_size=(2, 2), spatial_scale=1.0,
                              sample_ratio=2).asnumpy()
    assert out.shape == (3, 3, 2, 2)
    assert np.all(out[2] == 0.0)                     # invalid batch idx
    assert not np.allclose(out[0], out[1])           # different images
    # parity vs the single-image kernel on image 1
    from mxnet_tpu.ops.detection_ops import roi_align
    ref = np.asarray(roi_align(jnp.asarray(feats[1]),
                               jnp.asarray(rois[1:2, 1:]),
                               out_size=(2, 2), spatial_scale=1.0,
                               sampling_ratio=2))[0]
    np.testing.assert_allclose(out[1], ref, rtol=1e-5)


# ------------------------------------------------------------------ box ops
def test_box_nms_suppression_and_layout():
    # rows: [id, score, x0, y0, x1, y1]
    data = np.array([
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],
        [0, 0.8, 0.05, 0.05, 1.05, 1.05],   # IoU ~0.82 with row 0 -> dead
        [0, 0.7, 2.0, 2.0, 3.0, 3.0],       # disjoint -> survives
        [1, 0.6, 0.0, 0.0, 1.0, 1.0],       # other class -> survives
    ], np.float32)
    out = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5,
                             id_index=0).asnumpy()
    assert out.shape == data.shape
    kept_scores = sorted(out[out[:, 1] > 0][:, 1].tolist(), reverse=True)
    assert kept_scores == pytest.approx([0.9, 0.7, 0.6])
    assert np.all(out[-1] == -1.0)          # suppressed row is all -1
    # force_suppress ignores the class id -> row 3 dies too
    out_f = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5,
                               id_index=0, force_suppress=True).asnumpy()
    assert sorted(out_f[out_f[:, 1] > 0][:, 1].tolist(),
                  reverse=True) == pytest.approx([0.9, 0.7])


def test_box_iou_formats_and_batching():
    a = np.array([[0.0, 0.0, 2.0, 2.0]], np.float32)
    b = np.array([[1.0, 1.0, 3.0, 3.0]], np.float32)
    iou = nd.contrib.box_iou(nd.array(a), nd.array(b)).asnumpy()
    np.testing.assert_allclose(iou, [[1.0 / 7.0]], rtol=1e-5)
    # center format: same boxes expressed as (cx, cy, w, h)
    ac = np.array([[1.0, 1.0, 2.0, 2.0]], np.float32)
    bc = np.array([[2.0, 2.0, 2.0, 2.0]], np.float32)
    iou_c = nd.contrib.box_iou(nd.array(ac), nd.array(bc),
                               format="center").asnumpy()
    np.testing.assert_allclose(iou_c, iou, rtol=1e-5)
    # batched
    iou_b = nd.contrib.box_iou(nd.array(np.stack([a, a])),
                               nd.array(np.stack([b, b]))).asnumpy()
    assert iou_b.shape == (2, 1, 1)


# ------------------------------------------------------------ MultiBox trio
def test_multibox_reference_layouts():
    B, C, Hf, Wf = 2, 8, 4, 4
    feat = nd.random.uniform(shape=(B, C, Hf, Wf))
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.4, 0.8),
                                       ratios=(1.0, 2.0), clip=True)
    A = Hf * Wf * 3          # K = |sizes| + |ratios| - 1
    assert anchors.shape == (1, A, 4)
    an = anchors.asnumpy()
    assert an.min() >= 0.0 and an.max() <= 1.0

    labels = np.full((B, 2, 5), -1.0, np.float32)
    labels[0, 0] = [1, 0.1, 0.1, 0.4, 0.4]
    labels[1, 0] = [0, 0.5, 0.5, 0.9, 0.9]
    cls_pred = nd.random.uniform(shape=(B, 3, A))
    loc_t, loc_mask, cls_t = nd.contrib.MultiBoxTarget(
        anchors, nd.array(labels), cls_pred)
    assert loc_t.shape == (B, A * 4)
    assert loc_mask.shape == (B, A * 4)
    assert cls_t.shape == (B, A)
    ct = cls_t.asnumpy()
    assert (ct[0] == 2).any() and not (ct[0] == 1).any()  # cls+1 encoding
    assert (ct[1] == 1).any()

    probs = np.zeros((B, 3, A), np.float32)
    probs[:, 0] = 1.0
    probs[0, 0, 5], probs[0, 1, 5] = 0.1, 0.9   # one confident class-0 det
    dets = nd.contrib.MultiBoxDetection(
        nd.array(probs), nd.zeros((B, A * 4)), anchors, max_det=10)
    assert dets.shape == (B, 10, 6)
    d0 = dets.asnumpy()[0]
    assert d0[0, 0] == 0 and d0[0, 1] == pytest.approx(0.9, rel=1e-3)
    assert np.all(dets.asnumpy()[1][:, 0] == -1)  # nothing above threshold


# ---------------------------------------------------------------- proposals
def test_multi_proposal_basics():
    B, A, Hf, Wf = 2, 2, 5, 5    # A = |scales| * |ratios| = 2*1
    rs = np.random.RandomState(7)
    cls_prob = rs.rand(B, 2 * A, Hf, Wf).astype(np.float32)
    bbox_pred = (rs.randn(B, 4 * A, Hf, Wf) * 0.1).astype(np.float32)
    im_info = np.array([[80.0, 80.0, 1.0]] * B, np.float32)
    rois = nd.contrib.MultiProposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        rpn_pre_nms_top_n=40, rpn_post_nms_top_n=8, feature_stride=16,
        scales=(2, 4), ratios=(1.0,), threshold=0.7,
        rpn_min_size=4).asnumpy()
    assert rois.shape == (B * 8, 5)
    # batch indices blocked [0]*8 then [1]*8
    np.testing.assert_array_equal(rois[:8, 0], 0)
    np.testing.assert_array_equal(rois[8:, 0], 1)
    # proposals clipped to the image
    assert rois[:, 1:].min() >= 0.0
    assert rois[:, [1, 3]].max() <= 79.0 and rois[:, [2, 4]].max() <= 79.0
    # scores come back too when asked
    r2, scores = nd.contrib.MultiProposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        rpn_pre_nms_top_n=40, rpn_post_nms_top_n=8, feature_stride=16,
        scales=(2, 4), ratios=(1.0,), rpn_min_size=4, output_score=True)
    s = scores.asnumpy().reshape(B, 8)
    assert np.all(np.diff(s, axis=1) <= 1e-6)       # sorted descending


def test_proposal_rejects_batched_input():
    with pytest.raises(mx.base.MXNetError):
        nd.contrib.Proposal(nd.zeros((2, 6, 4, 4)), nd.zeros((2, 12, 4, 4)),
                            nd.zeros((2, 3)))


# ------------------------------------------------------------ symbol parity
def test_sym_contrib_json_roundtrip_and_parity():
    """Every new contrib op must build symbolically, round-trip through
    tojson/load_json, and evaluate to the nd result."""
    rs = np.random.RandomState(8)
    feats = rs.randn(1, 2, 6, 6).astype(np.float32)
    rois = np.array([[0, 1.0, 1.0, 4.0, 4.0]], np.float32)

    d = sym.Variable("d")
    r = sym.Variable("r")
    out = sym.contrib.ROIAlign(d, r, pooled_size=(2, 2), spatial_scale=1.0,
                               sample_ratio=2)
    loaded = mx.sym.load_json(out.tojson())
    got = loaded.eval_with({"d": nd.array(feats), "r": nd.array(rois)})
    want = nd.contrib.ROIAlign(nd.array(feats), nd.array(rois),
                               pooled_size=(2, 2))
    np.testing.assert_allclose(got.asnumpy(), want.asnumpy(), rtol=1e-5)

    x = rs.randn(2, 8).astype(np.float32)
    v = sym.Variable("x")
    f = sym.contrib.ifft(sym.contrib.fft(v))
    f2 = mx.sym.load_json(f.tojson())
    got = f2.eval_with({"x": nd.array(x)})
    np.testing.assert_allclose(got.asnumpy(), 8 * x, rtol=1e-4, atol=1e-3)

    # one JSON round-trip building every remaining op (graph validity)
    a = sym.Variable("a")
    b = sym.Variable("b")
    graph = sym.Group([
        sym.contrib.box_nms(a),
        sym.contrib.box_iou(a, b),
        sym.contrib.MultiBoxPrior(a, sizes=(0.5,)),
        sym.contrib.fft(a),
        sym.contrib.count_sketch(a, b, b, out_dim=4),
    ]) if hasattr(sym, "Group") else None
    if graph is not None:
        js = graph.tojson()
        assert mx.sym.load_json(js).tojson() == js


def test_sym_deformable_conv_matches_nd():
    rs = np.random.RandomState(9)
    x = rs.randn(1, 2, 6, 6).astype(np.float32)
    w = rs.randn(3, 2, 3, 3).astype(np.float32)
    off = (rs.randn(1, 18, 6, 6) * 0.3).astype(np.float32)
    dv, ov, wv = sym.Variable("x"), sym.Variable("o"), sym.Variable("w")
    out = sym.contrib.DeformableConvolution(dv, ov, wv, kernel=(3, 3),
                                            pad=(1, 1))
    out = mx.sym.load_json(out.tojson())
    got = out.eval_with({"x": nd.array(x), "o": nd.array(off),
                         "w": nd.array(w)})
    want = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3), pad=(1, 1))
    np.testing.assert_allclose(got.asnumpy(), want.asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_sym_multibox_target_three_outputs():
    anchors = nd.contrib.MultiBoxPrior(nd.zeros((1, 1, 3, 3)), sizes=(0.5,))
    A = anchors.shape[1]
    labels = np.full((1, 1, 5), -1.0, np.float32)
    labels[0, 0] = [0, 0.2, 0.2, 0.7, 0.7]
    av, lv, cv = (sym.Variable(n) for n in "alc")
    outs = sym.contrib.MultiBoxTarget(av, lv, cv)
    grp = mx.sym.Group(outs) if isinstance(outs, list) else outs
    js = mx.sym.load_json(grp.tojson())
    got = js.eval_with({"a": anchors, "l": nd.array(labels),
                        "c": nd.zeros((1, 2, A))})
    got = got if isinstance(got, (list, tuple)) else [got]
    assert [tuple(g.shape) for g in got] == [(1, A * 4), (1, A * 4), (1, A)]


def test_box_encode_mean_std_order():
    """Targets are (raw - mean)/std — upstream order, not raw/std - mean."""
    anchors = np.array([[[0.0, 0.0, 2.0, 2.0]]], np.float32)
    refs = np.array([[[0.5, 0.5, 2.5, 2.5]]], np.float32)   # shifted gt
    samples = np.ones((1, 1), np.float32)
    matches = np.zeros((1, 1), np.float32)
    means, stds = (0.1, 0.1, 0.1, 0.1), (0.2, 0.2, 0.3, 0.3)
    t, mask = nd.contrib.box_encode(
        nd.array(samples), nd.array(matches), nd.array(anchors),
        nd.array(refs), means=means, stds=stds)
    # closed form: center offsets dx=dy=0.5/2=0.25, dw=dh=log(1)=0
    raw = np.array([0.25, 0.25, 0.0, 0.0], np.float32)
    want = (raw - np.asarray(means)) / np.asarray(stds)
    np.testing.assert_allclose(t.asnumpy()[0, 0], want, rtol=1e-5)
    assert mask.asnumpy().min() == 1.0


def test_multibox_prior_steps_override():
    """Explicit steps move the anchor grid (SSD presets rely on this)."""
    feat = nd.zeros((1, 1, 4, 4))
    default = nd.contrib.MultiBoxPrior(feat, sizes=(0.2,)).asnumpy()
    stepped = nd.contrib.MultiBoxPrior(
        feat, sizes=(0.2,), steps=(0.5, 0.5)).asnumpy()
    assert not np.allclose(default, stepped)
    # first anchor center with steps=(0.5, 0.5): (0.25, 0.25)
    c0 = (stepped[0, 0, :2] + stepped[0, 0, 2:]) / 2.0
    np.testing.assert_allclose(c0, [0.25, 0.25], atol=1e-6)
    # default spacing is 1/feat: first center (0.125, 0.125)
    c0d = (default[0, 0, :2] + default[0, 0, 2:]) / 2.0
    np.testing.assert_allclose(c0d, [0.125, 0.125], atol=1e-6)


# ------------------------------------------- adaptive pool / bilinear alias
def test_adaptive_avg_pooling2d_matches_torch():
    """Region rule parity (upstream adaptive_avg_pooling-inl.h uses the
    same floor/ceil regions torch does)."""
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(1).rand(2, 3, 13, 17).astype(np.float32)
    out = nd.contrib.AdaptiveAvgPooling2D(
        nd.array(x), output_size=(5, 6)).asnumpy()
    ref = torch.nn.functional.adaptive_avg_pool2d(
        torch.from_numpy(x), (5, 6)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5)
    # int output_size means square, and dividing sizes reduce to plain
    # average pooling
    sq = nd.contrib.AdaptiveAvgPooling2D(nd.array(x[:, :, :12, :16]),
                                         output_size=4).asnumpy()
    ref_sq = x[:, :, :12, :16].reshape(2, 3, 4, 3, 4, 4).mean((3, 5))
    np.testing.assert_allclose(sq, ref_sq, atol=1e-5)


def test_adaptive_avg_pooling2d_sym_json_roundtrip():
    x = np.random.RandomState(2).rand(1, 2, 9, 9).astype(np.float32)
    s = sym.contrib.AdaptiveAvgPooling2D(sym.Variable("data"),
                                         output_size=(3, 3))
    s2 = mx.sym.load_json(s.tojson())
    out = s2.bind(mx.cpu(), {"data": nd.array(x)}).forward()[0].asnumpy()
    ref = x.reshape(1, 2, 3, 3, 3, 3).mean((3, 5))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_bilinear_resize2d_contrib_alias():
    """upstream documents BilinearResize2D under contrib; both nd.contrib
    and sym.contrib must carry the alias (VERDICT r4 missing #5)."""
    x = np.random.RandomState(3).rand(1, 2, 5, 5).astype(np.float32)
    top = mx.nd.BilinearResize2D(nd.array(x), height=10, width=10).asnumpy()
    via_contrib = nd.contrib.BilinearResize2D(
        nd.array(x), height=10, width=10).asnumpy()
    np.testing.assert_allclose(top, via_contrib, atol=1e-6)
    s = sym.contrib.BilinearResize2D(sym.Variable("data"),
                                     height=10, width=10)
    s2 = mx.sym.load_json(s.tojson())
    out = s2.bind(mx.cpu(), {"data": nd.array(x)}).forward()[0].asnumpy()
    np.testing.assert_allclose(out, via_contrib, atol=1e-6)


def test_log_validation_metrics_callback(caplog):
    import logging
    from mxnet_tpu.callback import (BatchEndParam,
                                    LogValidationMetricsCallback)
    from mxnet_tpu.metric import Accuracy
    m = Accuracy()
    m.update([nd.array([0, 1])], [nd.array([[0.9, 0.1], [0.2, 0.8]])])
    cb = LogValidationMetricsCallback()
    with caplog.at_level(logging.INFO):
        cb(BatchEndParam(epoch=3, nbatch=0, eval_metric=m, locals=None))
    assert any("Validation-accuracy" in r.message for r in caplog.records)


def test_bilinear_resize2d_scale_mode_and_errors():
    x = np.random.RandomState(4).rand(1, 2, 6, 8).astype(np.float32)
    y = nd.contrib.BilinearResize2D(nd.array(x), scale_height=2.0,
                                    scale_width=0.5)
    assert y.shape == (1, 2, 12, 4)
    s = sym.contrib.BilinearResize2D(sym.Variable("d"), scale_height=2.0,
                                     scale_width=0.5)
    out = mx.sym.load_json(s.tojson()).bind(
        mx.cpu(), {"d": nd.array(x)}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), y.asnumpy(), atol=1e-6)
    with pytest.raises(mx.base.MXNetError):
        nd.contrib.BilinearResize2D(nd.array(x), height=10)  # no width
    with pytest.raises(mx.base.MXNetError):
        sym.contrib.BilinearResize2D(sym.Variable("d"), width=4)
