"""ONNX export: structure validated node-by-node via the wire-format
decoder, numerics validated by executing the decoded graph with a
torch-backed mini-interpreter (an implementation independent of the
framework's own compute path)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.contrib.onnx import export_model, proto


def _mlp():
    x = sym.Variable("data")
    h = sym.FullyConnected(x, num_hidden=16, name="fc1")
    h = sym.Activation(h, act_type="relu", name="relu1")
    h = sym.FullyConnected(h, num_hidden=10, name="fc2")
    out = sym.softmax(h, name="sm")
    shapes = out.infer_shape(data=(2, 8))[0]
    args = {n: nd.random.uniform(shape=s)
            for n, s in zip(out.list_arguments(), shapes)}
    params = {k: v for k, v in args.items() if k != "data"}
    return out, args, params


def test_mlp_structure_node_by_node(tmp_path):
    out, args, params = _mlp()
    path = export_model(out, params, {"data": (2, 8)},
                        onnx_file_path=str(tmp_path / "mlp.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    assert m["opset"] == [("", 11)]
    g = m["graph"]
    assert g["inputs"] == [("data", (2, 8))]
    assert [o[0] for o in g["outputs"]] == ["sm"]
    got = [(n["op_type"], n["inputs"], n["outputs"]) for n in g["nodes"]]
    assert got == [
        ("Flatten", ["data"], ["fc1_flat__1"]),
        ("Gemm", ["fc1_flat__1", "fc1_weight", "fc1_bias"], ["fc1"]),
        ("Relu", ["fc1"], ["relu1"]),
        ("Flatten", ["relu1"], ["fc2_flat__2"]),
        ("Gemm", ["fc2_flat__2", "fc2_weight", "fc2_bias"], ["fc2"]),
        ("Softmax", ["fc2"], ["sm"]),
    ]
    gemm = g["nodes"][1]["attrs"]
    assert gemm == {"alpha": 1.0, "beta": 1.0, "transA": 0, "transB": 1}
    assert set(g["initializers"]) == set(params)
    for k, v in params.items():
        dims, dtype, raw = g["initializers"][k]
        assert dims == v.shape and dtype == proto.FLOAT
        assert np.allclose(np.frombuffer(raw, np.float32).reshape(dims),
                           v.asnumpy())


# ---------------------------------------------------------------- runtime
def _run_onnx(model, feeds):
    """Execute a decoded ONNX graph with torch ops — independent of the
    framework's jax compute path."""
    import torch
    import torch.nn.functional as F
    g = model["graph"]
    dt_of = {proto.FLOAT: np.float32, proto.INT64: np.int64,
             proto.INT32: np.int32, proto.FLOAT16: np.float16}
    env = {k: torch.from_numpy(np.frombuffer(raw, dt_of.get(_dt, np.float32))
                               .reshape([int(d) for d in dims]).copy())
           for k, (dims, _dt, raw) in g["initializers"].items()}
    for k, v in feeds.items():
        env[k] = torch.from_numpy(np.asarray(v, np.float32))

    for n in g["nodes"]:
        op, a = n["op_type"], n["attrs"]
        x = [env[i] for i in n["inputs"]]
        if op == "Conv":
            y = F.conv2d(x[0], x[1], x[2] if len(x) > 2 else None,
                         stride=list(a["strides"]),
                         padding=list(a["pads"][:2]),
                         dilation=list(a["dilations"]),
                         groups=a["group"])
        elif op == "BatchNormalization":
            y = F.batch_norm(x[0], x[3], x[4], x[1], x[2],
                             training=False, eps=a["epsilon"])
        elif op == "Relu":
            y = F.relu(x[0])
        elif op == "MaxPool":
            y = F.max_pool2d(x[0], list(a["kernel_shape"]),
                             stride=list(a["strides"]),
                             padding=list(a["pads"][:2]))
        elif op == "AveragePool":
            y = F.avg_pool2d(x[0], list(a["kernel_shape"]),
                             stride=list(a["strides"]),
                             padding=list(a["pads"][:2]),
                             count_include_pad=bool(
                                 a.get("count_include_pad", 1)))
        elif op == "GlobalAveragePool":
            y = x[0].mean(dim=(2, 3), keepdim=True)
        elif op == "GlobalMaxPool":
            y = x[0].amax(dim=(2, 3), keepdim=True)
        elif op == "Gemm":
            y = x[0] @ (x[1].t() if a["transB"] else x[1])
            if len(x) > 2:
                y = y + x[2]
        elif op == "Flatten":
            y = x[0].reshape(x[0].shape[0], -1)
        elif op == "Add":
            y = x[0] + x[1]
        elif op == "Sub":
            y = x[0] - x[1]
        elif op == "Mul":
            y = x[0] * x[1]
        elif op == "Div":
            y = x[0] / x[1]
        elif op == "Sqrt":
            y = x[0].sqrt()
        elif op == "Exp":
            y = x[0].exp()
        elif op == "Log":
            y = x[0].log()
        elif op == "ReduceMean":
            y = x[0].mean(dim=list(a["axes"]),
                          keepdim=bool(a.get("keepdims", 1)))
        elif op == "ReduceMax":
            y = x[0].amax(dim=list(a["axes"]),
                          keepdim=bool(a.get("keepdims", 1)))
        elif op == "ReduceSum":
            y = x[0].sum(dim=list(a["axes"]),
                         keepdim=bool(a.get("keepdims", 1)))
        elif op == "Softmax":
            y = F.softmax(x[0], dim=a.get("axis", -1))
        elif op == "Concat":
            y = __import__("torch").cat(x, dim=a["axis"])
        elif op == "Dropout":
            y = x[0]  # inference
        elif op == "Reshape":
            tgt = [int(d) for d in x[1].tolist()]
            shp = list(x[0].shape)
            tgt = [shp[i] if d == 0 else d for i, d in enumerate(tgt)]
            y = x[0].reshape(tgt)
        elif op == "Shape":
            y = __import__("torch").tensor(list(x[0].shape),
                                           dtype=__import__("torch").int64)
        elif op == "MatMul":
            y = x[0] @ x[1]
        elif op == "Transpose":
            y = x[0].permute(list(a["perm"])) if "perm" in a \
                else x[0].t()
        elif op == "Slice":
            starts, ends = x[1].tolist(), x[2].tolist()
            axes = x[3].tolist() if len(x) > 3 else list(range(len(starts)))
            steps = x[4].tolist() if len(x) > 4 else [1] * len(starts)
            slc = [slice(None)] * x[0].dim()
            for s, e, ax, st in zip(starts, ends, axes, steps):
                slc[ax] = slice(s, e, st)
            y = x[0][tuple(slc)]
        elif op == "Cast":
            tm = __import__("torch")
            to = {proto.FLOAT: tm.float32, proto.INT64: tm.int64,
                  proto.INT32: tm.int32, proto.FLOAT16: tm.float16}
            y = x[0].to(to[a["to"]])
        elif op == "Gather":
            got = np.take(x[0].numpy(), x[1].numpy().astype(np.int64),
                          axis=a.get("axis", 0))
            y = __import__("torch").from_numpy(np.asarray(got))
        elif op == "Range":
            y = __import__("torch").arange(
                int(x[0]), int(x[1]), int(x[2]))
        elif op == "Less":
            y = x[0] < x[1]
        elif op == "And":
            y = x[0] & x[1]
        elif op == "Where":
            y = __import__("torch").where(x[0], x[1], x[2])
        elif op == "Tanh":
            y = x[0].tanh()
        elif op == "Unsqueeze":
            y = x[0]
            for ax in sorted(a["axes"]):
                y = y.unsqueeze(ax)
        elif op == "Squeeze":
            y = x[0]
            if "axes" in a:
                for ax in sorted(a["axes"], reverse=True):
                    y = y.squeeze(ax)
            else:
                y = y.squeeze()
        elif op == "ConvTranspose":
            y = F.conv_transpose2d(
                x[0], x[1], x[2] if len(x) > 2 else None,
                stride=list(a["strides"]), padding=list(a["pads"][:2]),
                output_padding=list(a.get("output_padding", (0, 0))),
                groups=a.get("group", 1))
        elif op == "InstanceNormalization":
            y = F.instance_norm(x[0], weight=x[1], bias=x[2],
                                eps=a["epsilon"])
        elif op == "PRelu":
            # honest ONNX semantics: right-aligned unidirectional
            # broadcast of the slope AS SHIPPED (no flatten rescue —
            # a wrong slope shape must fail here like in onnxruntime)
            torch_mod = __import__("torch")
            y = torch_mod.where(x[0] >= 0, x[0], x[0] * x[1])
        else:
            raise AssertionError(f"mini-runtime: unimplemented op {op}")
        env[n["outputs"][0]] = y
    return [env[name].numpy() for name, _ in g["outputs"]]


def test_mlp_numerics_vs_torch_runtime(tmp_path):
    out, args, params = _mlp()
    path = export_model(out, params, {"data": (2, 8)},
                        onnx_file_path=str(tmp_path / "mlp.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    ref = out.bind(None, args).forward()[0].asnumpy()
    got = _run_onnx(m, {"data": args["data"].asnumpy()})[0]
    assert np.allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("name", ["resnet18_v1", "alexnet",
                                  "squeezenet1.0", "densenet121"])
def test_zoo_cnn_exports_and_runs(name, tmp_path):
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    net = get_model(name, classes=10)
    net.initialize()
    shape = (1, 3, 64, 64)
    x = nd.random.uniform(shape=shape)
    ref = net(x).asnumpy()
    graph = net(sym.Variable("data"))
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = export_model(graph, params, {"data": shape},
                        onnx_file_path=str(tmp_path / f"{name}.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    g = m["graph"]
    assert len(g["nodes"]) > 5
    # every non-data graph input is materialised as an initializer
    assert set(g["initializers"]) == set(graph.list_arguments() +
                                         graph.list_auxiliary_states()) - \
        {"data"}
    got = _run_onnx(m, {"data": x.asnumpy()})[0]
    assert np.allclose(got, ref, atol=1e-3), \
        f"{name}: onnx runtime diverges (max err " \
        f"{np.abs(got - ref).max():.2e})"


def test_unsupported_op_raises(tmp_path):
    g = sym.SequenceReverse(sym.Variable("d"))
    with pytest.raises(mx.base.MXNetError, match="no converter"):
        export_model(g, {}, {"d": (3, 2)},
                     onnx_file_path=str(tmp_path / "x.onnx"))


def test_fix_gamma_pins_ones(tmp_path):
    """sym.BatchNorm defaults fix_gamma=True (gamma pinned to ones in
    compute); the exporter must pin the serialized scale too."""
    x = sym.Variable("data")
    out = sym.BatchNorm(x, name="bn")  # fix_gamma=True default
    shapes = dict(zip(out.list_arguments() + out.list_auxiliary_states(),
                      list(out.infer_shape(data=(2, 3, 4, 4))[0]) +
                      list(out.infer_shape(data=(2, 3, 4, 4))[2])))
    params = {n: nd.random.uniform(1.5, 2.5, shape=s)
              for n, s in shapes.items() if n != "data"}
    path = export_model(out, params, {"data": (2, 3, 4, 4)},
                        onnx_file_path=str(tmp_path / "bn.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    bn = [n for n in m["graph"]["nodes"]
          if n["op_type"] == "BatchNormalization"][0]
    scale_name = bn["inputs"][1]
    assert scale_name != "bn_gamma", "raw gamma serialized despite fix_gamma"
    dims, _dt, raw = m["graph"]["initializers"][scale_name]
    assert np.allclose(np.frombuffer(raw, np.float32), 1.0)
    # numerics agree with the framework's fix_gamma compute (aux states
    # must go through aux_states=, not args — Executor defaults them
    # otherwise)
    aux_names = set(out.list_auxiliary_states())
    data = nd.random.uniform(shape=(2, 3, 4, 4))
    args = {"data": data,
            **{k: v for k, v in params.items() if k not in aux_names}}
    aux = {k: v for k, v in params.items() if k in aux_names}
    ref = out.bind(None, args, aux_states=aux).forward()[0].asnumpy()
    got = _run_onnx(m, {"data": data.asnumpy()})[0]
    assert np.allclose(got, ref, atol=1e-4)


def test_softmax_nonlast_axis_decomposed(tmp_path):
    """opset-11 Softmax coerces to 2D, so axis != -1 must be decomposed
    into max-shifted Exp/ReduceSum/Div to keep MXNet's per-axis meaning."""
    x = sym.Variable("data")
    out = sym.softmax(x, axis=1, name="sm")
    path = export_model(out, {}, {"data": (2, 3, 5)},
                        onnx_file_path=str(tmp_path / "sm.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    ops = [n["op_type"] for n in m["graph"]["nodes"]]
    assert "Softmax" not in ops and "Div" in ops and "ReduceMax" in ops
    d = nd.random.uniform(shape=(2, 3, 5))
    ref = out.bind(None, {"data": d}).forward()[0].asnumpy()
    got = _run_onnx(m, {"data": d.asnumpy()})[0]
    assert np.allclose(got, ref, atol=1e-5)


def test_unknown_output_shape_omits_shape_field(tmp_path):
    """Unknown shapes must omit TensorShapeProto (present-but-empty means
    rank 0 to ONNX consumers)."""
    out, args, params = _mlp()
    path = export_model(out, params, {"data": (2, 8)},
                        onnx_file_path=str(tmp_path / "m.onnx"))
    raw = open(path, "rb").read()
    g = proto.decode(proto.decode(raw)[7][0])
    (out_vi,) = g[12]
    v = proto.decode(out_vi)
    tensor = proto.decode(proto.decode(v[2][0])[1][0])
    assert 2 not in tensor, "shape field present for unknown output shape"


def test_stem_s2d_rejected(tmp_path):
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    net = resnet18_v1(layout="NHWC", stem_s2d=True)
    net.initialize()
    x = nd.random.uniform(shape=(1, 32, 32, 3))
    net(x)
    graph = net(sym.Variable("data"))
    with pytest.raises(mx.base.MXNetError, match="stem_s2d|NCHW|NHWC"):
        export_model(graph,
                     {k: v.data() for k, v in net.collect_params().items()},
                     {"data": (1, 32, 32, 3)},
                     onnx_file_path=str(tmp_path / "s.onnx"))


# -------------------------------------------------- import (onnx2mx)
def test_import_mlp_roundtrip(tmp_path):
    """export -> import -> bind reproduces the original network exactly
    (reference: onnx2mx import_model return convention)."""
    from mxnet_tpu.contrib.onnx import import_model
    out, args, params = _mlp()
    path = export_model(out, params, {"data": (2, 8)},
                        onnx_file_path=str(tmp_path / "m.onnx"))
    ref = out.bind(None, args).forward()[0].asnumpy()
    sym2, arg_p, aux_p = import_model(path)
    assert set(arg_p) == set(params) and not aux_p
    ex = sym2.bind(None, {"data": args["data"], **arg_p})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), ref, atol=1e-5)


@pytest.mark.parametrize("name", ["resnet18_v1", "squeezenet1.0"])
def test_import_zoo_cnn_roundtrip(name, tmp_path):
    """CNN with BatchNorm/pools/concat: import must classify running stats
    as aux and reproduce logits."""
    from mxnet_tpu.contrib.onnx import import_model
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    net = get_model(name, classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, 64, 64))
    ref = net(x).asnumpy()
    graph = net(sym.Variable("data"))
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = export_model(graph, params, {"data": (1, 3, 64, 64)},
                        onnx_file_path=str(tmp_path / "z.onnx"))
    sym2, arg_p, aux_p = import_model(path)
    if "resnet" in name:
        assert aux_p, "BN running stats should import as aux"
        assert all("running" in k for k in aux_p)
    ex = sym2.bind(None, {"data": x, **arg_p}, aux_states=aux_p)
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), ref, atol=1e-4)


def test_import_to_gluon_runs(tmp_path):
    from mxnet_tpu.contrib.onnx import import_to_gluon
    out, args, params = _mlp()
    path = export_model(out, params, {"data": (2, 8)},
                        onnx_file_path=str(tmp_path / "g.onnx"))
    ref = out.bind(None, args).forward()[0].asnumpy()
    block = import_to_gluon(path)
    got = block(args["data"]).asnumpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_import_unknown_op_raises(tmp_path):
    from mxnet_tpu.contrib.onnx import proto as P2, import_model
    node = P2.message(P2.f_bytes(1, "x"), P2.f_bytes(2, "y"),
                      P2.f_bytes(3, "n0"), P2.f_bytes(4, "NotAnOp"))
    vi = P2.message(P2.f_bytes(1, "x"))
    graph = P2.message(P2.f_bytes(1, node), P2.f_bytes(2, "g"),
                       P2.f_bytes(11, vi),
                       P2.f_bytes(12, P2.message(P2.f_bytes(1, "y"))))
    model = P2.message(P2.f_varint(1, 6), P2.f_bytes(7, graph))
    p = tmp_path / "bad.onnx"
    p.write_bytes(model)
    with pytest.raises(mx.base.MXNetError, match="no importer"):
        import_model(str(p))


def test_proto_decodes_packed_repeated_fields():
    """External ONNX writers pack repeated ints (proto3); the decoder must
    read packed and unpacked forms identically."""
    from mxnet_tpu.contrib.onnx import proto as P2
    # TensorProto with PACKED dims [2, 3] (field 1, wire type 2)
    packed_dims = P2._varint(2) + P2._varint(3)
    t = P2.message(P2.f_bytes(1, packed_dims),
                   P2.f_varint(2, P2.FLOAT),
                   P2.f_bytes(8, "w"),
                   P2.f_bytes(9, np.arange(6, np.float32).tobytes()
                              if False else
                              np.arange(6, dtype=np.float32).tobytes()))
    # AttributeProto with PACKED ints (field 8)
    at = P2.message(P2.f_bytes(1, "kernel_shape"),
                    P2.f_varint(20, P2.ATTR_INTS),
                    P2.f_bytes(8, P2._varint(3) + P2._varint(3)))
    node = P2.message(P2.f_bytes(1, "x"), P2.f_bytes(2, "y"),
                      P2.f_bytes(3, "n"), P2.f_bytes(4, "MaxPool"),
                      P2.f_bytes(5, at))
    graph = P2.message(P2.f_bytes(1, node), P2.f_bytes(2, "g"),
                       P2.f_bytes(5, t),
                       P2.f_bytes(12, P2.message(P2.f_bytes(1, "y"))))
    model = P2.message(P2.f_varint(1, 6), P2.f_bytes(7, graph))
    m = P2.decode_model(model)
    assert m["graph"]["initializers"]["w"][0] == (2, 3)
    assert m["graph"]["nodes"][0]["attrs"]["kernel_shape"] == (3, 3)


def test_import_reshape_net_no_orphan_params(tmp_path):
    """Reshape shape tensors are attrs after import, never params."""
    from mxnet_tpu.contrib.onnx import import_model
    x = sym.Variable("data")
    g = sym.reshape(sym.FullyConnected(x, num_hidden=12, name="fc"),
                    shape=(2, 3, 4))
    shapes = g.infer_shape(data=(2, 6))[0]
    args = {n: nd.random.uniform(shape=s)
            for n, s in zip(g.list_arguments(), shapes)}
    params = {k: v for k, v in args.items() if k != "data"}
    path = export_model(g, params, {"data": (2, 6)},
                        onnx_file_path=str(tmp_path / "r.onnx"))
    sym2, arg_p, aux_p = import_model(path)
    assert set(arg_p) == set(params), arg_p.keys()  # no shape-tensor leak
    ref = g.bind(None, args).forward()[0].asnumpy()
    got = sym2.bind(None, {"data": args["data"], **arg_p}).forward()[0]
    np.testing.assert_allclose(got.asnumpy(), ref, atol=1e-6)


def test_import_squeeze_multi_axis_roundtrip(tmp_path):
    from mxnet_tpu.contrib.onnx import import_model
    g = sym.squeeze(sym.Variable("data"), axis=(1, 3))
    path = export_model(g, {}, {"data": (2, 1, 3, 1)},
                        onnx_file_path=str(tmp_path / "sq.onnx"))
    sym2, _, _ = import_model(path)
    d = nd.random.uniform(shape=(2, 1, 3, 1))
    out = sym2.bind(None, {"data": d}).forward()[0]
    assert out.shape == (2, 3)


def test_import_pool_spec_defaults(tmp_path):
    """A spec-minimal external MaxPool (no strides attr) means stride 1."""
    from mxnet_tpu.contrib.onnx import proto as P2, import_model
    at = P2.message(P2.f_bytes(1, "kernel_shape"),
                    P2.f_varint(20, P2.ATTR_INTS),
                    P2.f_varint(8, 2), P2.f_varint(8, 2))
    node = P2.message(P2.f_bytes(1, "data"), P2.f_bytes(2, "y"),
                      P2.f_bytes(3, "p0"), P2.f_bytes(4, "MaxPool"),
                      P2.f_bytes(5, at))
    vi = P2.message(P2.f_bytes(1, "data"))
    graph = P2.message(P2.f_bytes(1, node), P2.f_bytes(2, "g"),
                       P2.f_bytes(11, vi),
                       P2.f_bytes(12, P2.message(P2.f_bytes(1, "y"))))
    model = P2.message(P2.f_varint(1, 6), P2.f_bytes(7, graph))
    p = tmp_path / "pool.onnx"
    p.write_bytes(model)
    sym2, _, _ = import_model(str(p))
    d = nd.array(np.arange(2 * 1 * 4 * 4, dtype=np.float32)
                 .reshape(2, 1, 4, 4))
    out = sym2.bind(None, {"data": d}).forward()[0]
    assert out.shape == (2, 1, 3, 3), out.shape  # stride 1, valid pads


def test_deconv_norm_prelu_export_runs(tmp_path):
    """Conv2DTranspose + InstanceNorm + GroupNorm + PReLU export and
    reproduce framework numerics under the torch runtime (the conv
    autoencoder deployment path)."""
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1),
            nn.GroupNorm(num_groups=2),
            nn.PReLU(),
            nn.Conv2DTranspose(4, 4, strides=2, padding=1),
            nn.InstanceNorm(),
            nn.Activation("relu"))
    net.initialize()
    x = nd.random.uniform(shape=(2, 3, 8, 8))
    ref = net(x).asnumpy()
    graph = net(sym.Variable("data"))
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = export_model(graph, params, {"data": (2, 3, 8, 8)},
                        onnx_file_path=str(tmp_path / "dn.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    ops = [n["op_type"] for n in m["graph"]["nodes"]]
    assert "ConvTranspose" in ops and "InstanceNormalization" in ops
    assert "PRelu" in ops and "Shape" in ops
    got = _run_onnx(m, {"data": x.asnumpy()})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def _bert_mini():
    from mxnet_tpu.models.bert import BERTModel
    net = BERTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
                    num_heads=4, max_length=16, dropout=0.0)
    net.initialize()
    return net


def test_bert_encoder_export_matches_torch_runtime(tmp_path):
    """BERT-mini (VERDICT r3 item 8): the symbolic encoder trace —
    fused-QKV attention decomposed to slice/batch_dot/length-masked
    softmax — exports to opset 11 and reproduces the framework's eager
    (flash-attention-path) logits under the independent torch runtime,
    including a ragged valid_length batch."""
    net = _bert_mini()
    B, S = 2, 12
    rng = np.random.RandomState(7)
    tok = rng.randint(0, 50, (B, S)).astype(np.float32)
    seg = rng.randint(0, 2, (B, S)).astype(np.float32)
    vl = np.array([12, 7], np.float32)
    ref_seq, ref_pool = net(nd.array(tok), nd.array(seg), nd.array(vl))
    g = sym.Group(list(net(sym.Variable("token_ids", shape=(B, S)),
                           sym.Variable("segment_ids", shape=(B, S)),
                           sym.Variable("valid_length", shape=(B,)))))
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = export_model(g, params,
                        {"token_ids": (B, S), "segment_ids": (B, S),
                         "valid_length": (B,)},
                        onnx_file_path=str(tmp_path / "bert.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    ops = [n["op_type"] for n in m["graph"]["nodes"]]
    # attention mask ops present and dynamic (no baked-in mask constant)
    for required in ("Range", "Less", "Where", "MatMul", "Tanh"):
        assert required in ops, f"missing {required} in exported graph"
    got = _run_onnx(m, {"token_ids": tok, "segment_ids": seg,
                        "valid_length": vl})
    np.testing.assert_allclose(got[0], ref_seq.asnumpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got[1], ref_pool.asnumpy(),
                               rtol=1e-4, atol=1e-4)
    # the mask must actually bite: full-length ref on the padded row
    # diverges from the ragged run
    ref_full, _ = net(nd.array(tok), nd.array(seg))
    assert not np.allclose(got[0][1], ref_full.asnumpy()[1], atol=1e-4)


def test_bert_export_no_valid_length(tmp_path):
    net = _bert_mini()
    B, S = 2, 8
    rng = np.random.RandomState(3)
    tok = rng.randint(0, 50, (B, S)).astype(np.float32)
    seg = np.zeros((B, S), np.float32)
    ref_seq, ref_pool = net(nd.array(tok), nd.array(seg))
    g = sym.Group(list(net(sym.Variable("token_ids", shape=(B, S)),
                           sym.Variable("segment_ids", shape=(B, S)))))
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = export_model(g, params,
                        {"token_ids": (B, S), "segment_ids": (B, S)},
                        onnx_file_path=str(tmp_path / "bert_nm.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    got = _run_onnx(m, {"token_ids": tok, "segment_ids": seg})
    np.testing.assert_allclose(got[1], ref_pool.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_bert_import_roundtrip(tmp_path):
    """Export bert-mini, import it back, bind, and match the framework's
    eager logits — the dynamic attention-mask idiom (Shape/Range/Less/
    Where) must rebuild and execute through the importer."""
    from mxnet_tpu.contrib.onnx import import_model
    net = _bert_mini()
    B, S = 2, 10
    rng = np.random.RandomState(11)
    tok = rng.randint(0, 50, (B, S)).astype(np.float32)
    seg = rng.randint(0, 2, (B, S)).astype(np.float32)
    vl = np.array([10, 4], np.float32)
    ref_seq, ref_pool = net(nd.array(tok), nd.array(seg), nd.array(vl))
    g = sym.Group(list(net(sym.Variable("token_ids", shape=(B, S)),
                           sym.Variable("segment_ids", shape=(B, S)),
                           sym.Variable("valid_length", shape=(B,)))))
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = export_model(g, params,
                        {"token_ids": (B, S), "segment_ids": (B, S),
                         "valid_length": (B,)},
                        onnx_file_path=str(tmp_path / "bert_i.onnx"))
    s2, args, aux = import_model(path)
    feed = dict(args)
    feed.update(token_ids=nd.array(tok), segment_ids=nd.array(seg),
                valid_length=nd.array(vl))
    outs = s2.bind(None, feed, aux_states=aux).forward()
    np.testing.assert_allclose(outs[0].asnumpy(), ref_seq.asnumpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(outs[1].asnumpy(), ref_pool.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_transformer_nmt_export_matches_torch_runtime(tmp_path):
    """Transformer NMT (encoder + CAUSAL decoder + tied projection)
    exports to opset 11 and reproduces eager teacher-forcing logits
    under the torch runtime. The causal mask exports dynamically
    (Range x2 + Less + And), the sinusoid tables ride
    collect_constants() as initializers, and the tied embedding exports
    once (reused by embed and the output MatMul)."""
    from mxnet_tpu.models.transformer import TransformerNMT
    net = TransformerNMT(vocab_size=40, units=16, hidden=32, num_layers=2,
                         num_heads=4, max_length=16, dropout=0.0)
    net.initialize()
    B, S = 2, 9
    rng = np.random.RandomState(5)
    src = rng.randint(0, 40, (B, S)).astype(np.float32)
    tgt = rng.randint(0, 40, (B, S)).astype(np.float32)
    vl = np.array([9, 5], np.float32)
    ref = net(nd.array(src), nd.array(tgt), nd.array(vl)).asnumpy()
    g = net(sym.Variable("src", shape=(B, S)),
            sym.Variable("tgt", shape=(B, S)),
            sym.Variable("src_valid_length", shape=(B,)))
    params = {k: v.data() for k, v in net.collect_params().items()}
    params.update(net.collect_constants())
    path = export_model(g, params,
                        {"src": (B, S), "tgt": (B, S),
                         "src_valid_length": (B,)},
                        onnx_file_path=str(tmp_path / "nmt.onnx"))
    m = proto.decode_model(open(path, "rb").read())
    ops = [n["op_type"] for n in m["graph"]["nodes"]]
    # both mask kinds export: length (encoder/cross) and causal rows
    # (decoder self) — at least two Range-based masks in the graph
    assert ops.count("Range") >= 2 and ops.count("Less") >= 2
    got = _run_onnx(m, {"src": src, "tgt": tgt, "src_valid_length": vl})[0]
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # causality must bite: changing a LATER tgt token can't affect
    # earlier positions' logits
    tgt2 = tgt.copy()
    tgt2[:, -1] = (tgt2[:, -1] + 7) % 40
    got2 = _run_onnx(m, {"src": src, "tgt": tgt2,
                         "src_valid_length": vl})[0]
    np.testing.assert_allclose(got2[:, :-1], got[:, :-1],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(got2[:, -1], got[:, -1], atol=1e-5)


def test_masked_softmax_causal_plus_length_export():
    """causal AND length masks compose (the And path): exported graph
    matches the framework kernel on a ragged causal attention map."""
    import tempfile, os
    d = sym.Variable("scores")
    ln = sym.Variable("ln")
    out = sym.softmax(d, length=ln, axis=-1, causal=True)
    scores = nd.random.uniform(shape=(2, 2, 5, 5))
    lens = nd.array(np.array([5, 3], np.float32))
    ref = mx.nd.softmax(scores, lens, causal=True).asnumpy()
    with tempfile.TemporaryDirectory() as td:
        path = export_model(out, {}, {"scores": (2, 2, 5, 5), "ln": (2,)},
                            onnx_file_path=os.path.join(td, "ms.onnx"))
        m = proto.decode_model(open(path, "rb").read())
    assert "And" in [n["op_type"] for n in m["graph"]["nodes"]]
    got = _run_onnx(m, {"scores": scores.asnumpy(), "ln": lens.asnumpy()})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # row 0 attends only to col 0; batch 1 cols >= 3 are dead
    assert np.allclose(got[:, :, 0, 1:], 0, atol=1e-7)
    assert np.allclose(got[1, :, :, 3:], 0, atol=1e-7)


def test_transformer_nmt_import_roundtrip(tmp_path):
    """Export the NMT model, import it back, bind, and match eager
    logits — the dynamic causal idiom (two Range chains + Less/And)
    must rebuild and execute through the importer too."""
    from mxnet_tpu.contrib.onnx import import_model
    from mxnet_tpu.models.transformer import TransformerNMT
    net = TransformerNMT(vocab_size=35, units=16, hidden=32, num_layers=1,
                         num_heads=4, max_length=12, dropout=0.0)
    net.initialize()
    B, S = 2, 8
    rng = np.random.RandomState(9)
    src = rng.randint(0, 35, (B, S)).astype(np.float32)
    tgt = rng.randint(0, 35, (B, S)).astype(np.float32)
    vl = np.array([8, 5], np.float32)
    ref = net(nd.array(src), nd.array(tgt), nd.array(vl)).asnumpy()
    g = net(sym.Variable("src", shape=(B, S)),
            sym.Variable("tgt", shape=(B, S)),
            sym.Variable("src_valid_length", shape=(B,)))
    params = {k: v.data() for k, v in net.collect_params().items()}
    params.update(net.collect_constants())
    path = export_model(g, params,
                        {"src": (B, S), "tgt": (B, S),
                         "src_valid_length": (B,)},
                        onnx_file_path=str(tmp_path / "nmt_i.onnx"))
    s2, args, aux = import_model(path)
    feed = dict(args)
    feed.update(src=nd.array(src), tgt=nd.array(tgt),
                src_valid_length=nd.array(vl))
    outs = s2.bind(None, feed, aux_states=aux).forward()
    np.testing.assert_allclose(outs[0].asnumpy(), ref,
                               rtol=2e-4, atol=2e-4)


def test_decode_model_malformed_raises_cleanly(tmp_path):
    """Truncated or garbage bytes must raise MXNetError('malformed...')
    — never hang (the wire walk only advances) and never leak a bare
    IndexError. Truncations that happen to land on a field boundary may
    decode leniently to a partial dict; both outcomes are acceptable,
    a hang or foreign exception is not."""
    out, args, params = _mlp()
    path = export_model(out, params, {"data": (2, 8)},
                        onnx_file_path=str(tmp_path / "m.onnx"))
    raw = open(path, "rb").read()
    for cut in (1, 7, len(raw) // 3, len(raw) // 2, len(raw) - 2):
        try:
            m = proto.decode_model(raw[:cut])
            assert isinstance(m, dict)          # lenient partial decode
        except mx.base.MXNetError as e:
            assert "malformed ONNX file" in str(e)
    # each of these drives a DIFFERENT underlying failure: bad wire type
    # (ValueError), scalar-where-submessage (TypeError), varint
    # truncation (IndexError) — all must surface as the one contract
    for garbage in (b"\xff" * 64, b"\x0b", b"\x38\x01"):
        with pytest.raises(mx.base.MXNetError, match="malformed ONNX"):
            proto.decode_model(garbage)


def test_decode_model_crafted_attr_garbage():
    """Value-level garbage the wire walk can't type-check also surfaces
    as MXNetError: a packed-floats blob of non-multiple-of-4 length
    (struct.error underneath) and an ATTR_INT whose payload arrives as
    bytes (TypeError underneath)."""
    name = proto.f_bytes(1, b"a")
    # AttributeProto type=FLOATS(6) with a 3-byte packed field-7 blob
    bad_floats = proto.message(name, proto.f_varint(20, 6),
                               proto.f_bytes(7, b"\x00\x01\x02"))
    # AttributeProto type=INT(2) with field 3 as length-delimited bytes
    bad_int = proto.message(name, proto.f_varint(20, 2),
                            proto.f_bytes(3, b"xy"))
    for attr in (bad_floats, bad_int):
        node = proto.message(proto.f_bytes(4, b"Relu"),
                             proto.f_bytes(5, attr))
        graph = proto.message(proto.f_bytes(1, node))
        model = proto.message(proto.f_bytes(7, graph))
        with pytest.raises(mx.base.MXNetError, match="malformed ONNX"):
            proto.decode_model(bytes(model))
