"""mx.image (reference: python/mxnet/image/image.py).

Image ops over HWC NDArrays. Decoding uses PIL (the reference uses
OpenCV); resize/crop/flip augmenters run through jax.image on device.
"""
from __future__ import annotations

import io as _io
import os

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array, _apply

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "HorizontalFlipAug", "ResizeAug",
           "CenterCropAug", "RandomCropAug", "ColorNormalizeAug",
           "CreateAugmenter", "Augmenter", "ForceResizeAug", "ImageIter",
           "ImageDetIter", "CastAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "LightingAug",
           "RandomOrderAug", "color_normalize", "random_size_crop", "ColorJitterAug", "DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug", "CreateDetAugmenter", "scale_down", "copyMakeBorder"]


def _finish_decode(arr, flag, to_rgb):
    """Common post-decode: channel-count per `flag`, order per `to_rgb`
    (reference cv2 semantics: to_rgb=False keeps BGR order)."""
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if flag == 0 and arr.shape[-1] == 3:         # luminance (ITU-R 601)
        arr = (arr.astype(np.float32)
               @ np.array([0.299, 0.587, 0.114], np.float32))
        arr = arr.astype(np.uint8)[:, :, None]
    if flag != 0 and not to_rgb and arr.shape[-1] == 3:
        arr = arr[:, :, ::-1]                    # RGB -> BGR
    return array(np.ascontiguousarray(arr))


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to an HWC uint8 NDArray (reference: cv2.imread;
    PIL here). flag=0 yields grayscale (H, W, 1); to_rgb=False returns
    BGR channel order (cv2 parity)."""
    if str(filename).endswith(".npy"):
        return _finish_decode(np.load(filename), flag, to_rgb)
    from PIL import Image
    img = Image.open(filename)
    img = img.convert("L") if flag == 0 else img.convert("RGB")
    return _finish_decode(np.asarray(img), flag, to_rgb)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode encoded image bytes (JPEG/PNG/... via PIL). A buffer with NO
    recognised image header falls back to raw-square interpretation (the
    synthetic pipeline's format); a RECOGNISED but corrupt image raises,
    like the reference's imdecode — silent garbage is worse than an
    error."""
    if isinstance(buf, NDArray):
        buf = bytes(buf.asnumpy().astype(np.uint8))
    from PIL import Image, UnidentifiedImageError
    try:
        img = Image.open(_io.BytesIO(buf))
    except UnidentifiedImageError:
        arr = np.frombuffer(buf, dtype=np.uint8)
        ch = 1 if flag == 0 else 3
        side = int(np.sqrt(arr.size // ch))
        if side == 0:
            raise MXNetError("imdecode: cannot decode buffer")
        return array(arr[:side * side * ch].reshape(side, side, ch))
    try:
        img = img.convert("L") if flag == 0 else img.convert("RGB")
        arr = np.asarray(img)
    except Exception as e:
        raise MXNetError(f"imdecode: corrupt image data: {e}") from e
    return _finish_decode(arr, flag, to_rgb)


def imresize(src, w, h, interp=1):
    import jax.image

    def fn(a, _w=w, _h=h):
        return jax.image.resize(a.astype("float32"), (_h, _w, a.shape[2]),
                                method="bilinear")
    return _apply(fn, [src])


def resize_short(src, size, interp=1):
    h, w = src.shape[0], src.shape[1]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    out = src[y0:y0 + h, x0:x0 + w, :]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=1):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=1):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = np.random.randint(0, w - new_w + 1)
    y0 = np.random.randint(0, h - new_h + 1)
    return fixed_crop(src, x0, y0, new_w, new_h, size), (x0, y0, new_w, new_h)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return resize_short(src, self.size)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return center_crop(src, self.size)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return random_crop(src, self.size)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return src[:, ::-1, :]
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = array(np.asarray(mean, np.float32)) \
            if not isinstance(mean, NDArray) else mean
        self.std = array(np.asarray(std, np.float32)) \
            if not isinstance(std, NDArray) else std

    def __call__(self, src):
        return (src.astype("float32") - self.mean) / self.std


class ForceResizeAug(Augmenter):
    """Resize to exactly (w, h), ignoring aspect ratio (reference:
    image/detection.py ForceResizeAug) — normalised det boxes stay
    valid under a full-image resize."""

    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1])


class CastAug(Augmenter):
    """Cast to float32 (reference: CastAug)."""

    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class BrightnessJitterAug(Augmenter):
    """src *= 1 + U(-brightness, brightness) (reference)."""

    def __init__(self, brightness, rng=None):
        super().__init__(brightness=brightness)
        self.brightness = brightness
        self._rng = rng or np.random.RandomState()

    def __call__(self, src):
        alpha = 1.0 + self._rng.uniform(-self.brightness, self.brightness)
        return src.astype("float32") * alpha


class ContrastJitterAug(Augmenter):
    """Blend with the grayscale mean (reference coefficients)."""

    def __init__(self, contrast, rng=None):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self._coef = array(np.array([0.299, 0.587, 0.114], np.float32))
        self._rng = rng or np.random.RandomState()

    def __call__(self, src):
        alpha = 1.0 + self._rng.uniform(-self.contrast, self.contrast)
        x = src.astype("float32")
        gray = (x * self._coef).sum() * (3.0 / x.size)
        return x * alpha + gray * (1 - alpha)


class SaturationJitterAug(Augmenter):
    """Blend with the per-pixel grayscale (reference coefficients)."""

    def __init__(self, saturation, rng=None):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self._coef = array(np.array([0.299, 0.587, 0.114], np.float32))
        self._rng = rng or np.random.RandomState()

    def __call__(self, src):
        alpha = 1.0 + self._rng.uniform(-self.saturation, self.saturation)
        x = src.astype("float32")
        gray_nd = (x * self._coef).sum(axis=2, keepdims=True)
        return x * alpha + gray_nd * (1 - alpha)


class LightingAug(Augmenter):
    """AlexNet-style PCA noise (reference: LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec, rng=None):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)
        self._rng = rng or np.random.RandomState()

    def __call__(self, src):
        alpha = self._rng.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval).astype(np.float32)
        return src.astype("float32") + array(rgb)


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (reference)."""

    def __init__(self, ts, rng=None):
        super().__init__()
        self.ts = list(ts)
        self._rng = rng or np.random.RandomState()

    def __call__(self, src):
        order = self._rng.permutation(len(self.ts))
        for i in order:
            src = self.ts[i](src)
        return src


class ColorJitterAug(RandomOrderAug):
    """Brightness+contrast+saturation jitter in random order
    (reference: image.ColorJitterAug)."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 rng=None):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness, rng))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast, rng))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation, rng))
        super().__init__(ts, rng)
        self._kwargs = {"brightness": brightness, "contrast": contrast,
                        "saturation": saturation}


def color_normalize(src, mean, std=None):
    """(src - mean) / std (reference: mx.image.color_normalize)."""
    out = src.astype("float32") - (mean if isinstance(mean, NDArray)
                                   else array(np.asarray(mean, np.float32)))
    if std is not None:
        out = out / (std if isinstance(std, NDArray)
                     else array(np.asarray(std, np.float32)))
    return out


def random_size_crop(src, size, area, ratio, rng=None, **kwargs):
    """Random area/aspect crop then resize (reference: the inception-style
    random_size_crop); falls back to center crop when no box fits."""
    rng = rng or np.random.RandomState()
    h, w = src.shape[0], src.shape[1]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = rng.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(rng.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = rng.randint(0, w - new_w + 1)
            y0 = rng.randint(0, h - new_h + 1)
            out = fixed_crop(src, x0, y0, new_w, new_h, size)
            return out, (x0, y0, new_w, new_h)
    out, box = center_crop(src, size)
    return out, box


def _color_augmenters(brightness=0, contrast=0, saturation=0,
                      pca_noise=0, mean=None, std=None):
    """The ONE color-jitter + PCA-noise + normalize tail shared by
    CreateAugmenter and CreateDetAugmenter (constants live here only)."""
    out = []
    jitters = []
    if brightness:
        jitters.append(BrightnessJitterAug(brightness))
    if contrast:
        jitters.append(ContrastJitterAug(contrast))
    if saturation:
        jitters.append(SaturationJitterAug(saturation))
    if jitters:
        out.append(RandomOrderAug(jitters))
    if pca_noise:
        eigval = np.array([55.46, 4.794, 1.148], np.float32)
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]], np.float32)
        out.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None and mean is not False:
        out.append(ColorNormalizeAug(mean, std if std is not None
                                     and std is not False else [1, 1, 1]))
    return out


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_mirror=False,
                    mean=None, std=None, **kwargs):
    """Build the reference's standard augmentation pipeline."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size))
    else:
        auglist.append(CenterCropAug(crop_size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())   # reference emits float32 unconditionally
    auglist.extend(_color_augmenters(
        brightness=kwargs.get("brightness", 0),
        contrast=kwargs.get("contrast", 0),
        saturation=kwargs.get("saturation", 0),
        pca_noise=kwargs.get("pca_noise", 0), mean=mean, std=std))
    return auglist


class ImageIter:
    """Image iterator over a RecordIO file or an image list (reference:
    python/mxnet/image.py ImageIter): decodes, runs the augmenter pipeline,
    and yields NCHW float batches.

    rec mode: path_imgrec (+ optional path_imgidx for shuffled access);
    list mode: path_imglist (.lst: "index\\tlabel...\\tpath") + path_root.
    A partial final batch raises StopIteration, like the reference.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imgidx=None, path_imglist=None,
                 path_root=None, shuffle=False, aug_list=None,
                 data_name="data", label_name="softmax_label", seed=0,
                 **kwargs):
        from .io import DataDesc
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(self.data_shape)
        self._record = None
        self._list = None
        if path_imgrec:
            from . import recordio
            if path_imgidx:
                self._record = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self._keys = list(self._record.keys)
            else:
                self._record = recordio.MXRecordIO(path_imgrec, "r")
                self._keys = None
                if shuffle:
                    raise MXNetError("shuffle needs path_imgidx "
                                     "(indexed record access)")
        elif path_imglist:
            self._list = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    labels = np.array([float(v) for v in parts[1:-1]],
                                      np.float32)
                    self._list.append((labels, parts[-1]))
            self._root = path_root or "."
        else:
            raise MXNetError("ImageIter needs path_imgrec or path_imglist")
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        lshape = (batch_size,) if label_width == 1 \
            else (batch_size, label_width)
        self.provide_label = [DataDesc(label_name, lshape)]
        self.reset()

    def reset(self):
        self._cursor = 0
        if self._record is not None and self._keys is None:
            self._record.reset()
        if self._shuffle:
            if self._list is not None:
                self._rng.shuffle(self._list)
            else:
                self._rng.shuffle(self._keys)

    def _read_sample(self):
        from . import recordio
        if self._list is not None:
            if self._cursor >= len(self._list):
                return None
            label, path = self._list[self._cursor]
            self._cursor += 1
            img = imread(os.path.join(self._root, path))
            return label, img
        if self._keys is not None:
            if self._cursor >= len(self._keys):
                return None
            s = self._record.read_idx(self._keys[self._cursor])
            self._cursor += 1
        else:
            s = self._record.read()
            if s is None:
                return None
        header, img = recordio.unpack_img(s)
        label = np.atleast_1d(np.asarray(header.label, np.float32))
        return label, array(np.ascontiguousarray(img))

    def _postprocess(self, label, img):
        for aug in self.auglist:
            img = aug(img)
        arr = img.asnumpy() if hasattr(img, "asnumpy") else np.asarray(img)
        return label, arr.astype(np.float32).transpose(2, 0, 1)  # HWC->CHW

    def _convert_label(self, label):
        out = np.zeros(self.label_width, np.float32)
        vals = label[:self.label_width]
        out[:len(vals)] = vals
        return out

    def _stack_labels(self, labels):
        stacked = np.stack(labels)
        return stacked[:, 0] if self.label_width == 1 else stacked

    def __iter__(self):
        return self

    def __next__(self):
        from .io import DataBatch
        data = np.empty((self.batch_size,) + self.data_shape, np.float32)
        labels = []
        for i in range(self.batch_size):
            sample = self._read_sample()
            if sample is None:
                raise StopIteration  # partial batch dropped (reference)
            label, img = self._postprocess(*sample)
            data[i] = img
            labels.append(self._convert_label(label))
        return DataBatch(data=[array(data)],
                         label=[array(self._stack_labels(labels))])

    next = __next__


class DetAugmenter:
    """Label-aware augmenter base (reference: image/detection.py
    DetAugmenter): __call__(src, label) -> (src, label) where label is
    the packed (max_objects, object_width) box array with [cls, x1, y1,
    x2, y2, ...] rows in normalised coords, -1-padded."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only augmenter into the det pipeline (reference:
    DetBorrowAug) — labels pass through untouched."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image AND boxes with probability p (reference:
    DetHorizontalFlipAug)."""

    def __init__(self, p=0.5, rng=None):
        self.p = p
        self._rng = rng or np.random.RandomState()

    def __call__(self, src, label):
        if self._rng.uniform() >= self.p:
            return src, label
        arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
        flipped = array(np.ascontiguousarray(arr[:, ::-1]))
        label = label.copy()
        valid = label[:, 0] >= 0
        x1 = label[valid, 1].copy()
        x2 = label[valid, 3].copy()
        label[valid, 1] = 1.0 - x2
        label[valid, 3] = 1.0 - x1
        return flipped, label


def CreateDetAugmenter(data_shape, resize=0, rand_mirror=False,
                       mean=None, std=None, brightness=0, contrast=0,
                       saturation=0, pca_noise=0, rand_crop=0,
                       rand_pad=0, **kwargs):
    """Detection augmentation pipeline (reference: CreateDetAugmenter).

    Geometry support here is resize + mirror (boxes move with pixels);
    the reference's IoU-sampled rand_crop/rand_pad modes are not
    implemented (documented divergence — raise rather than silently
    corrupt boxes)."""
    if rand_crop or rand_pad:
        raise MXNetError("CreateDetAugmenter: rand_crop/rand_pad (IoU-"
                         "sampled geometry) not supported; use resize + "
                         "rand_mirror + color augmenters")
    h, w = data_shape[1], data_shape[2]
    auglist = []
    if resize > 0:
        # resize-short first (uniform scale: normalised boxes unchanged)
        auglist.append(DetBorrowAug(ResizeAug(resize)))
    auglist.append(DetBorrowAug(ForceResizeAug((w, h))))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(CastAug()))
    auglist.extend(DetBorrowAug(a) for a in _color_augmenters(
        brightness=brightness, contrast=contrast, saturation=saturation,
        pca_noise=pca_noise, mean=mean, std=std))
    return auglist


def scale_down(src_size, size):
    """Shrink (w, h) to fit inside src_size keeping aspect (reference:
    image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def copyMakeBorder(src, top, bot, left, right, type=0, values=0.0):
    """Pad an HWC image with a constant border (reference:
    image.copyMakeBorder / cv2 semantics, constant mode only)."""
    if type != 0:
        raise MXNetError("copyMakeBorder: only BORDER_CONSTANT (type=0) "
                         "is supported")
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    out = np.pad(arr, ((top, bot), (left, right), (0, 0)),
                 constant_values=values)
    return array(out)


class ImageDetIter(ImageIter):
    """Detection variant (reference: image/detection.py ImageDetIter):
    labels are object lists in the reference det-record format
    [header_width, object_width, (extra header...), obj0..objN-1 fields],
    padded with -1 rows to the iterator-wide max object count.

    Geometry: boxes are normalised [0,1] coordinates, which are invariant
    under full-image resize — so the default pipeline is a plain resize to
    data_shape, never a crop/flip. Geometry-changing augmenters are
    rejected because this iterator does not transform labels (the
    reference ships DetAugmenters that move boxes with the pixels; pass
    label-preserving augmenters only)."""

    _GEOMETRIC_AUGS = None  # set after class body (needs the classes)

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 max_objects=8, object_width=5, aug_list=None, **kwargs):
        self._max_objects = max_objects
        self._object_width = object_width
        if aug_list is None:
            h, w = data_shape[1], data_shape[2]
            aug_list = [ForceResizeAug((w, h))]
        else:
            def flatten(augs):
                # look inside container augmenters: a RandomOrderAug
                # wrapping a flip would silently corrupt boxes otherwise
                for a in augs:
                    if isinstance(a, RandomOrderAug):
                        yield from flatten(a.ts)
                    elif isinstance(a, DetBorrowAug):
                        # borrowed image augs still crop/flip pixels
                        # without touching boxes — validate the wrapped
                        # augmenter, not the wrapper
                        yield from flatten([a.augmenter])
                    elif isinstance(a, DetAugmenter):
                        continue   # label-aware: moves boxes WITH pixels
                    else:
                        yield a
            bad = [a for a in flatten(aug_list)
                   if isinstance(a, ImageDetIter._GEOMETRIC_AUGS)]
            if bad:
                raise MXNetError(
                    f"ImageDetIter cannot apply geometry-changing "
                    f"augmenters {[type(a).__name__ for a in bad]}: boxes "
                    f"would no longer match the pixels. Use label-"
                    f"preserving augmenters (color, ForceResizeAug)")
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, aug_list=aug_list,
                         **kwargs)
        from .io import DataDesc
        self.provide_label = [DataDesc(
            self.provide_label[0].name,
            (batch_size, max_objects, object_width))]

    def _postprocess(self, label, img):
        label = self._convert_label(label)
        for aug in self.auglist:
            if isinstance(aug, DetAugmenter):
                img, label = aug(img, label)
            else:
                img = aug(img)
        arr = img.asnumpy() if hasattr(img, "asnumpy") else np.asarray(img)
        return label, arr.astype(np.float32).transpose(2, 0, 1)

    def _convert_label(self, flat):
        if isinstance(flat, np.ndarray) and flat.ndim == 2:
            return flat                  # already packed (post-augment)
        flat = np.asarray(flat, np.float32).ravel()
        if flat.size < 2:
            raise MXNetError(f"det record label too short ({flat.size} "
                             "floats): expected [header_width, "
                             "object_width, objects...]")
        hw, ow = int(flat[0]), int(flat[1])
        if hw < 2 or ow < 1:
            raise MXNetError(f"malformed det label header "
                             f"(header_width={hw}, object_width={ow})")
        if ow < self._object_width:
            raise MXNetError(
                f"record object_width {ow} < iterator object_width "
                f"{self._object_width}")
        body = flat[hw:]
        n = body.size // ow
        objs = body[:n * ow].reshape(n, ow)[:, :self._object_width]
        out = np.full((self._max_objects, self._object_width), -1.0,
                      np.float32)
        out[:min(n, self._max_objects)] = objs[:self._max_objects]
        return out

    def _stack_labels(self, labels):
        return np.stack(labels)


# crops/flips move pixels without moving boxes; ImageDetIter rejects
# them (see its docstring). Full-image resizes (ResizeAug/
# ForceResizeAug) are NOT here: boxes are stored normalised, and a
# whole-image rescale leaves normalised coordinates unchanged.
ImageDetIter._GEOMETRIC_AUGS = (CenterCropAug, RandomCropAug,
                                HorizontalFlipAug)
