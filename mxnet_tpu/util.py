"""Misc utilities (reference: python/mxnet/util.py)."""
from __future__ import annotations

__all__ = ["waitall", "is_np_array", "set_np", "use_np"]


def waitall():
    from .ndarray.ndarray import waitall as _w
    _w()


def is_np_array():
    return False


def set_np(shape=True, array=True):
    raise NotImplementedError(
        "numpy-semantics mode is not needed: mxnet_tpu NDArray already "
        "follows numpy broadcasting via jax.numpy")


def use_np(func):
    return func
