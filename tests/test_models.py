"""Model-family tests (SURVEY.md §2 #43-46): BERT, Transformer NMT, SSD,
Faster-RCNN at tiny scale — forward shapes, gradient flow, convergence on
toy tasks, decode paths."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.ndarray.ndarray import _apply


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------
def _tiny_bert():
    from mxnet_tpu.models.bert import BERTModel
    return BERTModel(vocab_size=64, units=32, hidden_size=64, num_layers=2,
                     num_heads=4, max_length=16, dropout=0.0)


def test_bert_forward_shapes():
    from mxnet_tpu.models.bert import BERTForPretraining
    bert = _tiny_bert()
    model = BERTForPretraining(bert)
    model.initialize(mx.init.Normal(0.02))
    B, S, P = 2, 16, 4
    tok = nd.array(np.random.randint(0, 64, (B, S)), dtype="int32")
    seg = nd.array(np.zeros((B, S)), dtype="int32")
    vl = nd.array(np.full((B,), S), dtype="int32")
    pos = nd.array(np.random.randint(0, S, (B, P)), dtype="int32")
    mlm, nsp = model(tok, seg, vl, pos)
    assert mlm.shape == (B, P, 64)
    assert nsp.shape == (B, 2)
    seq, pooled = bert(tok, seg, vl)
    assert seq.shape == (B, S, 32) and pooled.shape == (B, 32)


def test_bert_mlm_learns():
    from mxnet_tpu.models.bert import BERTForPretraining
    model = BERTForPretraining(_tiny_bert())
    model.initialize(mx.init.Normal(0.02))
    B, S, P = 4, 16, 3
    rng = np.random.RandomState(0)
    tok = nd.array(rng.randint(0, 64, (B, S)), dtype="int32")
    seg = nd.array(np.zeros((B, S)), dtype="int32")
    vl = nd.array(np.full((B,), S), dtype="int32")
    pos = nd.array(rng.randint(0, S, (B, P)), dtype="int32")
    mlm_lbl = nd.array(rng.randint(0, 64, (B, P)), dtype="int32")
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(model.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    losses = []
    for _ in range(10):
        with autograd.record():
            mlm, nsp = model(tok, seg, vl, pos)
            loss = lf(mlm.reshape((-1, 64)), mlm_lbl.reshape((-1,))).mean()
        loss.backward()
        tr.step(B)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]


def test_bert_hybridize_matches_eager():
    bert = _tiny_bert()
    bert.initialize(mx.init.Normal(0.02))
    B, S = 2, 16
    tok = nd.array(np.random.randint(0, 64, (B, S)), dtype="int32")
    seg = nd.array(np.zeros((B, S)), dtype="int32")
    seq1, pool1 = bert(tok, seg)
    bert.hybridize()
    seq2, pool2 = bert(tok, seg)
    np.testing.assert_allclose(seq1.asnumpy(), seq2.asnumpy(),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Transformer NMT
# ---------------------------------------------------------------------------
def test_transformer_copy_task_and_beam():
    from mxnet_tpu.models.transformer import TransformerNMT, beam_search
    net = TransformerNMT(vocab_size=50, units=32, hidden=64, num_layers=2,
                         num_heads=4, max_length=32, dropout=0.0)
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(0)
    src_np = rng.randint(4, 50, (16, 8))
    tgt_in = np.concatenate([np.full((16, 1), 2), src_np[:, :-1]], 1)
    srcs = nd.array(src_np, dtype="int32")
    tgts = nd.array(tgt_in, dtype="int32")
    lbl = nd.array(src_np, dtype="int32")
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 3e-3})
    losses = []
    for _ in range(40):
        with autograd.record():
            o = net(srcs, tgts)
            loss = lf(o.reshape((-1, 50)), lbl.reshape((-1,))).mean()
        loss.backward()
        tr.step(16)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.6
    toks, scores = beam_search(net, srcs[:2], beam_size=3, max_length=9)
    assert toks.shape == (2, 3, 9) and scores.shape == (2, 3)
    # best beam should reproduce a prefix of the source (copy task)
    best = toks.asnumpy()[0, 0]
    match = (best[1:5] == src_np[0][:4]).mean()
    assert match >= 0.5, (best, src_np[0])


# ---------------------------------------------------------------------------
# SSD
# ---------------------------------------------------------------------------
def test_ssd_end_to_end():
    from mxnet_tpu.models.ssd import SSD, SSDTargetGenerator, ssd_decode
    net = SSD(num_classes=3, backbone_layers=18, input_size=128)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.random.uniform(shape=(2, 128, 128, 3))
    cls_p, loc_p = net(x)
    A = net.anchors.shape[0]
    assert cls_p.shape == (2, A, 4) and loc_p.shape == (2, A * 4)
    tgen = SSDTargetGenerator(net.anchors)
    labels = nd.array(np.array(
        [[[1, 0.1, 0.1, 0.4, 0.4], [2, 0.5, 0.5, 0.9, 0.9]]] * 2),
        dtype="float32")
    ct, lt, lm = tgen(labels)
    assert int(lm.asnumpy().sum()) > 0
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.01})
    with autograd.record():
        cls_p, loc_p = net(x)
        cl = gluon.loss.SoftmaxCrossEntropyLoss()(
            cls_p.reshape((-1, 4)), ct.reshape((-1,))).mean()
        ll = gluon.loss.HuberLoss()(
            loc_p.reshape((0, -1, 4)) * lm, lt * lm).mean()
        loss = cl + ll
    loss.backward()
    tr.step(2)
    assert np.isfinite(float(loss.asnumpy()))
    det = ssd_decode(cls_p, loc_p, net.anchors, max_det=10)
    assert det.shape == (2, 10, 6)


# ---------------------------------------------------------------------------
# Faster-RCNN
# ---------------------------------------------------------------------------
def test_faster_rcnn_end_to_end():
    from mxnet_tpu.models.faster_rcnn import FasterRCNN, rcnn_targets
    net = FasterRCNN(num_classes=3, backbone_layers=18, input_size=128,
                     post_nms=50)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.random.uniform(shape=(2, 128, 128, 3))
    obj, deltas, feat = net(x)
    A = net.anchors.shape[0]
    assert obj.shape == (2, A) and deltas.shape == (2, A, 4)
    props, scores = net.rpn_proposals(obj, deltas, pre_nms=200)
    assert props.shape == (2, 50, 4)
    gt = np.array([[[1, 10, 10, 60, 60], [2, 70, 70, 120, 120]]] * 2,
                  np.float32)
    rois, cls_t, box_t, box_m = _apply(
        lambda p, g: jax.vmap(
            lambda pp, gg: rcnn_targets(pp, gg, num_samples=32))(p, g),
        [props, nd.array(gt)], n_out=4)
    assert rois.shape == (2, 32, 4)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.01})
    with autograd.record():
        obj, deltas, feat = net(x)
        cls, box = net.roi_head(feat, rois)
        loss = gluon.loss.SoftmaxCrossEntropyLoss()(
            cls.reshape((-1, 4)), cls_t.reshape((-1,))).mean()
    loss.backward()
    tr.step(2)
    assert cls.shape == (2, 32, 4) and box.shape == (2, 32, 4, 4)
    assert np.isfinite(float(loss.asnumpy()))


# ---------------------------------------------------------------------------
# detection ops unit checks
# ---------------------------------------------------------------------------
def test_detection_ops():
    from mxnet_tpu.ops import detection_ops as D
    a = jnp.array([[0, 0, 2, 2], [0, 0, 1, 1]], jnp.float32)
    b = jnp.array([[1, 1, 2, 2]], jnp.float32)
    iou = D.box_iou(a, b)
    assert abs(float(iou[0, 0]) - 0.25) < 1e-6
    anch = jnp.array([[0.1, 0.1, 0.5, 0.5], [0.3, 0.2, 0.9, 0.8]],
                     jnp.float32)
    gt = jnp.array([[0.15, 0.1, 0.55, 0.45], [0.35, 0.25, 0.8, 0.85]],
                   jnp.float32)
    np.testing.assert_allclose(
        np.asarray(D.box_decode(D.box_encode(gt, anch), anch)),
        np.asarray(gt), atol=1e-4)
    boxes = jnp.array([[0, 0, 1, 1], [0.05, 0, 1, 1], [2, 2, 3, 3]],
                      jnp.float32)
    keep = D.nms(boxes, jnp.array([0.9, 0.8, 0.7]), 0.5, 10)
    assert list(np.asarray(keep)) == [True, False, True]
    out = D.roi_align(jnp.arange(32, dtype=jnp.float32).reshape(2, 4, 4),
                      jnp.array([[0, 0, 3, 3]], jnp.float32), (2, 2))
    assert out.shape == (1, 2, 2, 2)


def test_get_bert_specs():
    """get_bert/bert_base construct from the named spec table; unknown
    names raise MXNetError (regression: NameError in get_bert)."""
    import pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.models.bert import bert_base, get_bert

    net = bert_base(vocab_size=64, max_length=32)
    assert net._units == 768 and len(net.encoder.layers._children) == 12
    net2 = get_bert("bert_24_1024_16", vocab_size=64, max_length=32)
    assert net2._units == 1024 and len(net2.encoder.layers._children) == 24
    with pytest.raises(MXNetError):
        get_bert("bert_unknown")


def test_beam_search_cached_matches_full_recompute():
    """KV-cached incremental decode (decode_step + beam_search_cached) must
    produce EXACTLY the same beams as the re-run-the-prefix decoder."""
    from mxnet_tpu.models.transformer import (TransformerNMT, beam_search,
                                              beam_search_cached)
    mx.random.seed(11)
    t = TransformerNMT(50, units=32, hidden=64, num_layers=2, num_heads=4,
                       max_length=32, dropout=0.0)
    t.initialize()
    rng = np.random.RandomState(0)
    src = mx.nd.array(rng.randint(4, 50, (2, 12)).astype(np.int32))
    svl = mx.nd.array(np.array([8, 12], np.int32))
    tok1, sc1 = beam_search(t, src, svl, beam_size=3, max_length=10)
    tok2, sc2 = beam_search_cached(t, src, svl, beam_size=3, max_length=10)
    np.testing.assert_array_equal(tok1.asnumpy(), tok2.asnumpy())
    np.testing.assert_allclose(sc1.asnumpy(), sc2.asnumpy(), rtol=1e-4,
                               atol=1e-4)
    # and without source lengths
    tok3, _ = beam_search(t, src, beam_size=2, max_length=8)
    tok4, _ = beam_search_cached(t, src, beam_size=2, max_length=8)
    np.testing.assert_array_equal(tok3.asnumpy(), tok4.asnumpy())


def test_pretrained_loads_from_local_store(tmp_path):
    """get_model(name, pretrained=True) loads upstream-format weights from
    the local model store (reference flow minus the download), including
    hash-stamped filenames and nets with deferred shapes."""
    import numpy as np
    from mxnet_tpu import nd, upstream
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    src = get_model("resnet18_v1", classes=10)
    src.initialize()
    x = nd.random.uniform(shape=(1, 3, 32, 32))
    ref = src(x).asnumpy()
    # save like an upstream download: hash-stamped, arg/aux split
    store = tmp_path / "models"
    store.mkdir()
    blob = {}
    for k, v in src.collect_params().items():
        kind = "aux" if "running_" in k else "arg"
        blob[f"{kind}:{k}"] = v.data()
    upstream.save_params(str(store / "resnet18_v1-a0666292.params"), blob)

    net = get_model("resnet18_v1", classes=10, pretrained=True,
                    root=str(store))
    got = net(x).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_pretrained_missing_raises_helpfully(tmp_path):
    import pytest
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    with pytest.raises(mx.MXNetError, match="offline"):
        get_model("alexnet", pretrained=True, root=str(tmp_path))


def test_pretrained_not_silently_ignored(tmp_path):
    """Every zoo ctor must honor pretrained=True (alexnet/vgg used to
    swallow it)."""
    import pytest
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    for ctor in [vision.alexnet, vision.vgg11, vision.squeezenet1_0,
                 vision.mobilenet0_25]:
        with pytest.raises(mx.MXNetError, match="offline"):
            ctor(pretrained=True, root=str(tmp_path))


def test_bert_classifier_finetunes():
    """BERTClassifier (gluonnlp contract): pooled -> dense, trains on a
    separable toy task; BERTRegression emits (B, 1)."""
    from mxnet_tpu.models.bert import BERTClassifier, BERTRegression
    bert = _tiny_bert()
    clf = BERTClassifier(bert, num_classes=2, dropout=0.0)
    clf.initialize(mx.init.Normal(0.05))
    clf.hybridize()
    B, S = 8, 16
    rng = np.random.RandomState(0)
    # class = whether token 3 appears first: learnable from embeddings
    tok = rng.randint(4, 64, (B, S))
    labels = rng.randint(0, 2, B)
    tok[:, 0] = np.where(labels, 3, 2)
    tok_nd = nd.array(tok, dtype="int32")
    seg = nd.array(np.zeros((B, S)), dtype="int32")
    vl = nd.array(np.full((B,), S), dtype="int32")
    lossfn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    tr = mx.gluon.Trainer(clf.collect_params(), "adam",
                          {"learning_rate": 5e-3})
    y = nd.array(labels.astype(np.float32))
    for _ in range(30):
        with mx.autograd.record():
            out = clf(tok_nd, seg, vl)
            l = lossfn(out, y)
        l.backward()
        tr.step(B)
    pred = np.argmax(clf(tok_nd, seg, vl).asnumpy(), 1)
    assert (pred == labels).mean() > 0.8

    reg = BERTRegression(bert, dropout=0.0)
    reg.regression.initialize(mx.init.Normal(0.05))
    assert reg(tok_nd, seg, vl).shape == (B, 1)


# ---------------------------------------------------------------------------
# YOLOv3
# ---------------------------------------------------------------------------
def test_yolo3_forward_decode_and_target_loss():
    """Forward shapes at a small input; decode recovers a planted box;
    target/loss pipeline produces a finite scalar that falls when the
    head emits the assigned targets."""
    from mxnet_tpu.models.yolo import (YOLOV3, YOLOV3TargetGenerator,
                                       YOLOV3Loss, yolo_decode, _ANCHORS)
    size, C = 64, 3
    net = YOLOV3(num_classes=C, input_size=size)
    net.initialize(mx.init.Normal(0.02))
    x = nd.random.uniform(shape=(2, size, size, 3))
    outs = net(x)
    assert [o.shape for o in outs] == [
        (2, size // 32, size // 32, 3 * (5 + C)),
        (2, size // 16, size // 16, 3 * (5 + C)),
        (2, size // 8, size // 8, 3 * (5 + C))]

    # plant one confident box in the raw heads: scale 0 (stride 32),
    # cell (0, 0), anchor 0 -> center (16, 16), wh = anchor size
    raws = [np.full(o.shape, -8.0, np.float32) for o in outs]
    p = np.zeros(5 + C, np.float32)
    p[:2] = 0.0          # sigmoid 0.5 -> center of the cell
    p[2:4] = 0.0         # wh = anchor
    p[4] = 8.0           # objectness ~1
    p[5] = 8.0           # class 0
    raws[0][0, 0, 0, :5 + C] = p
    ids, scores, boxes = yolo_decode(
        tuple(nd.array(r) for r in raws), C, size, conf_thresh=0.3)
    assert int(ids.asnumpy()[0, 0]) == 0
    assert scores.asnumpy()[0, 0] > 0.9
    aw, ah = _ANCHORS[0][0]
    np.testing.assert_allclose(
        boxes.asnumpy()[0, 0],
        [16 - aw / 2, 16 - ah / 2, 16 + aw / 2, 16 + ah / 2], atol=1e-3)
    assert int(ids.asnumpy()[1, 0]) == -1    # second image: all padded

    gen = YOLOV3TargetGenerator(C, size)
    gt = nd.array([[[10.0, 12, 50, 60]], [[-1.0, -1, -1, -1]]])
    gid = nd.array([[1.0], [-1.0]])
    obj_t, ctr_t, scale_t, wmask, cls_t = gen(gt, gid)
    assert float(obj_t.asnumpy()[0].sum()) == 1.0   # one anchor assigned
    assert float(obj_t.asnumpy()[1].sum()) == 0.0   # padded image: none
    lossfn = YOLOV3Loss()
    l0 = lossfn(outs, obj_t, ctr_t, scale_t, wmask, cls_t)
    assert l0.shape == () and np.isfinite(l0.asnumpy())

    # a head that EMITS the targets must beat the random head. Locate the
    # assigned position's scale segment (w=40, h=48 matches a stride-16
    # anchor, not stride-32).
    pos = int(np.argmax(obj_t.asnumpy()[0, :, 0]))
    seg_sizes = [(size // s) ** 2 * 3 for s in (32, 16, 8)]
    s_idx, off = 0, 0
    while pos >= off + seg_sizes[s_idx]:
        off += seg_sizes[s_idx]
        s_idx += 1
    hw = size // (32, 16, 8)[s_idx]
    cell, a_idx = divmod(pos - off, 3)
    gy, gx = divmod(cell, hw)
    perfect = [np.full(o.shape, -8.0, np.float32) for o in outs]
    tx, ty = ctr_t.asnumpy()[0, pos]
    tw, th = scale_t.asnumpy()[0, pos]
    vec = np.full(5 + C, -8.0, np.float32)
    vec[:2] = np.log(np.clip([tx, ty], 1e-4, 1 - 1e-4)) - \
        np.log1p(-np.clip([tx, ty], 1e-4, 1 - 1e-4))   # logit(t)
    vec[2:4] = (tw, th)
    vec[4] = 8.0
    vec[5 + 1] = 8.0                                    # class id 1
    perfect[s_idx][0, gy, gx,
                   a_idx * (5 + C):(a_idx + 1) * (5 + C)] = vec
    l1 = lossfn(tuple(nd.array(r) for r in perfect),
                obj_t, ctr_t, scale_t, wmask, cls_t)
    assert float(l1.asnumpy()) < float(l0.asnumpy())


def test_yolo3_per_class_nms_and_ignore_mask():
    """Reference semantics pinned: (a) overlapping boxes of DIFFERENT
    classes both survive NMS (force_suppress=False); (b) an unassigned
    high-IOU prediction is excluded from the objectness loss."""
    from mxnet_tpu.models.yolo import (YOLOV3TargetGenerator, YOLOV3Loss,
                                       yolo_decode, _ANCHORS)
    size, C = 64, 3
    shape32 = (1, 2, 2, 3 * (5 + C))
    raws = [np.full(shape32, -8.0, np.float32),
            np.full((1, 4, 4, 3 * (5 + C)), -8.0, np.float32),
            np.full((1, 8, 8, 3 * (5 + C)), -8.0, np.float32)]
    # same cell/anchor emits strong class-1 AND class-2 (identical box)
    v = np.full(5 + C, -8.0, np.float32)
    v[:2] = 0.0; v[2:4] = 0.0; v[4] = 8.0
    v[5 + 1] = 8.0
    v[5 + 2] = 7.5
    raws[0][0, 0, 0, :5 + C] = v
    ids, scores, boxes = yolo_decode(
        tuple(nd.array(r) for r in raws), C, size, conf_thresh=0.3,
        nms_thresh=0.45)
    got = set(int(i) for i in ids.asnumpy()[0] if i >= 0)
    assert got == {1, 2}            # both classes kept despite IOU=1
    np.testing.assert_allclose(boxes.asnumpy()[0, 0],
                               boxes.asnumpy()[0, 1], atol=1e-4)

    # ignore mask: gt box, assigned anchor at pos_a; craft a SECOND
    # prediction overlapping gt strongly at a different anchor — with
    # gt_boxes passed, its objectness penalty disappears
    gen = YOLOV3TargetGenerator(C, size)
    gt = nd.array([[[8.0, 8, 56, 56]]])     # big central box
    gid = nd.array([[0.0]])
    targets = gen(gt, gid)
    lossfn = YOLOV3Loss(input_size=size, ignore_iou_thresh=0.7)
    # build heads where the stride-32 cell (1,1) anchor 2 ALSO predicts
    # ~exactly the gt box (48x48 at center 32,32 -> IOU ~1; the ASSIGNED
    # anchor is a stride-16 one, so this one is unassigned and would be
    # penalised without the mask). tx=ty=-8 puts sigmoid ~0 -> center at
    # the cell's top-left corner (32, 32).
    aw, ah = _ANCHORS[0][2]
    hot = [np.full(r.shape, -8.0, np.float32) for r in raws]
    vec = np.full(5 + C, -8.0, np.float32)
    vec[2] = np.log(48.0 / aw); vec[3] = np.log(48.0 / ah)
    vec[4] = 8.0                            # confident objectness
    hot[0][0, 1, 1, 2 * (5 + C):3 * (5 + C)] = vec
    outs = tuple(nd.array(r) for r in hot)
    l_no_gt = lossfn(outs, *targets)
    l_with_gt = lossfn(outs, *targets, gt_boxes=gt)
    # removing the false-negative penalty must LOWER the loss
    assert float(l_with_gt.asnumpy()) < float(l_no_gt.asnumpy())


def test_get_model_detection_names():
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    from mxnet_tpu.models.yolo import YOLOV3
    from mxnet_tpu.models.ssd import SSD
    y = get_model("yolo3_darknet53", num_classes=3, input_size=64)
    assert isinstance(y, YOLOV3) and y.num_classes == 3
    yc = get_model("yolo3_darknet53_coco", input_size=64)
    assert yc.num_classes == 80
    s = get_model("ssd_512_resnet50_v1", num_classes=4, input_size=128,
                  backbone_layers=18)
    assert isinstance(s, SSD)
    with pytest.raises(ValueError, match="not in zoo"):
        get_model("not_a_model")
    with pytest.raises(ValueError, match="pretrained"):
        get_model("yolo3_darknet53", pretrained=True, input_size=64)


# ---------------------------------------------------------------------------
# FCN segmentation
# ---------------------------------------------------------------------------
def test_fcn_shapes_and_overfit_one_image():
    """FCN-8s emits per-pixel logits at input resolution and can overfit
    a single synthetic mask (reference example/fcn-xs training loop)."""
    from mxnet_tpu.models.fcn import FCN
    size, C = 64, 3
    net = FCN(num_classes=C, backbone_layers=18, input_size=size, stride=8)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.random.uniform(shape=(2, size, size, 3))
    out = net(x)
    assert out.shape == (2, size, size, C)
    # stride variants share the contract
    for s in (16, 32):
        n2 = FCN(num_classes=C, backbone_layers=18, input_size=size,
                 stride=s)
        n2.initialize(mx.init.Xavier())
        assert n2(x).shape == (2, size, size, C)

    # overfit: left half class 1, right half class 2
    mask = np.ones((1, size, size), np.float32)
    mask[:, :, size // 2:] = 2
    y = nd.array(mask)
    lf = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    img = nd.random.uniform(shape=(1, size, size, 3))
    losses = []
    for _ in range(12):
        with autograd.record():
            logits = net(img)
            loss = lf(logits.reshape((-1, C)), y.reshape((-1,))).mean()
        loss.backward()
        tr.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.7
    pred = np.argmax(net(img).asnumpy(), -1)
    acc = (pred == mask).mean()
    assert acc > 0.8, acc


def test_fcn_rejects_bad_input_size():
    from mxnet_tpu.models.fcn import FCN
    with pytest.raises(mx.base.MXNetError, match="divisible by 32"):
        FCN(num_classes=3, input_size=100)


def test_roi_align_mm_matches_gather():
    """The einsum RoIAlign (MXTPU_ROIALIGN=mm perf lever) is numerically
    identical to the gather formulation — same clipping, same corner
    weights, arbitrary sub-pixel rois."""
    import jax.numpy as jnp
    from mxnet_tpu.ops import detection_ops as D
    rs = np.random.RandomState(0)
    feat = jnp.asarray(rs.randn(16, 24, 20).astype(np.float32))
    rois = jnp.asarray(np.stack([
        rs.uniform(0, 10, 5), rs.uniform(0, 12, 5),
        rs.uniform(10, 19, 5), rs.uniform(12, 23, 5)], -1)
        .astype(np.float32))
    a = D.roi_align(feat, rois, (7, 7), spatial_scale=0.5,
                    sampling_ratio=2)
    b = D.roi_align_mm(feat, rois, (7, 7), spatial_scale=0.5,
                       sampling_ratio=2)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    # degenerate roi at the border behaves identically too
    edge = jnp.asarray(np.array([[18.5, 22.5, 19.5, 23.5]], np.float32))
    np.testing.assert_allclose(
        np.asarray(D.roi_align(feat, edge, (7, 7))),
        np.asarray(D.roi_align_mm(feat, edge, (7, 7))), atol=2e-5)
