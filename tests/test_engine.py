"""Dependency-engine tests (SURVEY.md §2 #9, §5 race detection): the native
C++ engine and the Python fallback must order ops identically — writes
serialise, reads run concurrently, errors poison dependents."""
import time

import pytest

from mxnet_tpu import engine
from mxnet_tpu.engine import Var, _PyEngine


def _engines():
    out = [_PyEngine(4)]
    try:
        from mxnet_tpu._native import NativeEngine
        out.append(NativeEngine(4))
    except Exception:
        pass
    return out


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_write_read_ordering(eng):
    order = []
    a, b = Var(), Var()

    def op(tag, t):
        def f():
            time.sleep(t)
            order.append(tag)
            return tag
        return f

    eng.push(op("w1", 0.05), write_vars=[a])
    eng.push(op("r1", 0.01), read_vars=[a])
    eng.push(op("r2", 0.01), read_vars=[a])
    eng.push(op("w2", 0.01), write_vars=[a], read_vars=[b])
    eng.wait_for_var(a)
    assert order[0] == "w1" and order[-1] == "w2"
    assert set(order) == {"w1", "r1", "r2", "w2"}


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_error_poisons_dependents(eng):
    v = Var()

    def boom():
        raise RuntimeError("boom")

    fe = eng.push(boom, write_vars=[v])
    fr = eng.push(lambda: 1, read_vars=[v])
    fw = eng.push(lambda: 2, write_vars=[v])
    try:
        eng.wait_for_all()
    except RuntimeError:
        pass  # wait may rethrow the poisoned error (ThreadedEngine::WaitForAll)
    assert fe.exception() is not None
    assert fr.exception() is not None
    assert fw.exception() is not None


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_wait_for_var_reraises_poisoned(eng):
    """WaitForVar rethrows a stored exception (ThreadedEngine parity) even
    when the caller never retained the op's future."""
    v = Var()

    def boom():
        raise RuntimeError("boom")

    eng.push(boom, write_vars=[v])
    with pytest.raises(RuntimeError, match="boom"):
        eng.wait_for_var(v)


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_duplicate_vars_no_deadlock(eng):
    """A repeated write (or read) var in one push must not self-deadlock."""
    v, r = Var(), Var()
    fut = eng.push(lambda: 42, read_vars=[r, r], write_vars=[v, v])
    assert fut.result(timeout=5) == 42
    f2 = eng.push(lambda: 7, write_vars=[v])
    assert f2.result(timeout=5) == 7
    eng.wait_for_all()


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_throughput_many_ops(eng):
    vs = [Var() for _ in range(50)]
    futs = [eng.push(lambda i=i: i, write_vars=[vs[i % 50]])
            for i in range(1000)]
    eng.wait_for_all()
    assert sum(f.result() for f in futs) == sum(range(1000))


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_concurrent_reads_overlap(eng):
    """Two readers of the same var must run concurrently (wall-clock)."""
    v = Var()
    eng.push(lambda: time.sleep(0.01), write_vars=[v])
    t0 = time.time()
    f1 = eng.push(lambda: time.sleep(0.2), read_vars=[v])
    f2 = eng.push(lambda: time.sleep(0.2), read_vars=[v])
    eng.wait_for_all()
    elapsed = time.time() - t0
    assert elapsed < 0.38, elapsed  # serial would be >= 0.4


def test_facade_push_wait():
    v = Var()
    fut = engine.push(lambda: 42, write_vars=[v])
    engine.wait_for_var(v)
    assert fut.result() == 42
    engine.wait_for_all()


def test_native_engine_loads():
    """The native engine must actually build+load in this environment."""
    assert engine.native_engine_loaded()


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_wait_for_var_raises_failed_reader(eng):
    """A failed READER's error also surfaces from wait_for_var — both
    engines share the per-var future bookkeeping."""
    v = Var()
    eng.push(lambda: 1, write_vars=[v])

    def boom():
        raise RuntimeError("reader-boom")

    eng.push(boom, read_vars=[v])
    with pytest.raises(RuntimeError, match="reader-boom"):
        eng.wait_for_var(v)


# ---------------------- debug mode: race / deadlock detection (§5) ----------
def _native():
    try:
        from mxnet_tpu._native import NativeEngine
        return NativeEngine(4)
    except Exception as e:  # no g++ / build failure: degrade like _engines()
        pytest.skip(f"native engine unavailable: {e!r}")


def test_debug_write_write_hazard_detected():
    """A bypass-push (simulated scheduler bug) makes two writers run on
    one var concurrently; the detector must name the hazard."""
    eng = _native()
    eng.set_debug(True)
    v = Var()
    import threading
    gate = threading.Event()
    eng.push(gate.wait, write_vars=[v])          # legit writer, running
    time.sleep(0.05)
    # buggy 2nd writer, held running on the same gate so both writers are
    # demonstrably concurrent when the detector scans
    eng._debug_bypass_push(gate.wait, write_vars=[v])
    time.sleep(0.05)
    assert eng.debug_check() == 1
    assert "write-write hazard" in eng.last_error()
    gate.set()
    eng.wait_for_all()
    eng.clear_error()


def test_debug_read_write_hazard_detected():
    eng = _native()
    eng.set_debug(True)
    v = Var()
    import threading
    gate = threading.Event()
    eng.push(gate.wait, read_vars=[v])           # legit reader, running
    time.sleep(0.05)
    eng._debug_bypass_push(gate.wait, write_vars=[v])  # buggy writer, held
    time.sleep(0.05)
    assert eng.debug_check() == 1
    assert "read-write hazard" in eng.last_error()
    gate.set()
    eng.wait_for_all()


def test_debug_self_dependency_deadlock_detected():
    """An op whose reads and writes overlap is a self-cycle: debug mode
    reports the deadlock and drops the read dep so the op still runs
    (the Python binding dedups, so push raw through the C ABI)."""
    eng = _native()
    eng.set_debug(True)
    v = Var()
    ran = []
    fut = eng._debug_push_raw(lambda: ran.append(1),
                              read_vars=[v], write_vars=[v])
    fut.result(timeout=5)          # stays live because the dep was dropped
    assert ran == [1]
    assert "deadlock" in eng.last_error()
    assert "self-dependency" in eng.last_error()


def test_debug_stall_watchdog():
    """wait_for_all_timeout reports instead of hanging when an op wedges."""
    eng = _native()
    eng.set_debug(True)
    import threading
    gate = threading.Event()
    eng.push(gate.wait, write_vars=[Var()])
    assert eng.wait_for_all_timeout(150) == 1
    assert "stall" in eng.last_error()
    gate.set()
    eng.wait_for_all()
    assert eng.wait_for_all_timeout(1000) == 0


def test_debug_clean_run_no_hazard():
    """Normal dependency-respecting traffic must NOT trip the detector."""
    eng = _native()
    eng.set_debug(True)
    vs = [Var() for _ in range(4)]
    for i in range(50):
        eng.push(lambda: None, read_vars=[vs[i % 4]],
                 write_vars=[vs[(i + 1) % 4]])
    eng.wait_for_all()
    assert eng.debug_check() == 0, eng.last_error()
    assert eng.last_error() == ""


def test_debug_facade_and_env(monkeypatch):
    """The engine.py facade exposes the detector; _PyEngine honors
    MXTPU_ENGINE_DEBUG and detects self-deps too."""
    monkeypatch.setenv("MXTPU_ENGINE_DEBUG", "1")
    eng = _PyEngine(2)
    assert eng.debug_enabled()
    v = Var()
    eng.push(lambda: None, read_vars=[v], write_vars=[v]).result()
    assert eng.debug_check() == 1
    assert "deadlock" in eng.last_error()
    eng.clear_error()
    assert eng.debug_check() == 0


def test_debug_detector_clean_under_concurrent_load():
    """Satellite (ISSUE 3): dependency-respecting traffic pushed from
    MANY threads at once must not trip the race detector — false
    positives under concurrency would make debug mode useless on real
    pipelines."""
    eng = _native()
    eng.set_debug(True)
    import threading
    vs = [Var() for _ in range(8)]
    stop = threading.Barrier(4)

    def pusher(tid):
        stop.wait()
        for i in range(100):
            eng.push(lambda: None,
                     read_vars=[vs[(tid + i) % 8]],
                     write_vars=[vs[(tid + i + 1) % 8]])

    threads = [threading.Thread(target=pusher, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.wait_for_all()
    assert eng.debug_check() == 0, eng.last_error()
    assert eng.last_error() == ""


def test_debug_detector_finds_hazard_amid_concurrent_load():
    """The detector must still catch a real hazard while legitimate
    concurrent traffic is in flight (no lost signal under load)."""
    eng = _native()
    eng.set_debug(True)
    import threading
    vs = [Var() for _ in range(4)]
    v_bug = Var()
    gate = threading.Event()
    done = threading.Event()

    def legit():
        for i in range(50):
            eng.push(lambda: None, read_vars=[vs[i % 4]],
                     write_vars=[vs[(i + 1) % 4]])
        done.set()

    t = threading.Thread(target=legit)
    t.start()
    eng.push(gate.wait, write_vars=[v_bug])          # legit writer, held
    time.sleep(0.05)
    eng._debug_bypass_push(gate.wait, write_vars=[v_bug])  # buggy writer
    time.sleep(0.05)
    assert eng.debug_check() == 1
    assert "write-write hazard" in eng.last_error()
    gate.set()
    done.wait(5)
    t.join()
    eng.wait_for_all()
    eng.clear_error()


def test_file_vars_order_save_load_and_recordio(tmp_path):
    """NDArray save/load and recordio writes route through per-file engine
    vars: async write then read is race-free."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, recordio
    f = str(tmp_path / "t.npz")
    a = nd.array(np.arange(6, dtype=np.float32))
    nd.save(f, [a])                  # async write
    out = nd.load(f)                 # waits on the file var
    np.testing.assert_allclose(out[0].asnumpy(), a.asnumpy())

    rec = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(rec, "w")
    payloads = [bytes([i]) * (7 * i + 1) for i in range(20)]
    offsets = []
    for p in payloads:
        offsets.append(w.tell())     # logical offset, sync with framing
        w.write(p)                   # async append
    w.close()                        # drains the file var
    r = recordio.MXRecordIO(rec, "r")
    got = []
    while True:
        item = r.read()
        if item is None:
            break
        got.append(item)
    assert got == payloads
    # offsets must match the real framing (idx sidecar correctness)
    import struct as st
    blob = open(rec, "rb").read()
    for off, p in zip(offsets, payloads):
        magic, lrec = st.unpack("<II", blob[off:off + 8])
        assert magic == 0xced7230a and (lrec & ((1 << 29) - 1)) == len(p)
