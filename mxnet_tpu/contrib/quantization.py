"""INT8/UINT8 quantization (reference: python/mxnet/contrib/quantization.py +
src/operator/quantization/*).

TPU-native: the MXU multiplies int8 x int8 into int32 natively, so int8
inference is a first-class fast path — not a GPU-only feature. The design
maps the reference's calibrated symmetric per-tensor scheme onto XLA:

  * `quantize` / `dequantize` — symmetric linear mapping
    q = clip(round(x / scale), -127, 127), x ≈ q * scale
    (reference: quantize_v2 with min/max calib -> int8).
  * `QuantizedDense` / `QuantizedConv2D` — weights stored int8 + fp scale;
    activations quantized dynamically per call (or with a calibrated
    static scale); the dot runs int8 x int8 -> int32
    (`preferred_element_type=jnp.int32`) and one fp multiply rescales.
    uint8 activations (post-ReLU ranges) use the standard zero-point
    decomposition: x_u8 in [0,255] is computed as (x_u8-128):int8 through
    the MXU plus a precomputed +128 correction term — still int8 hardware
    math, twice the effective resolution for non-negative tensors.
  * `quantize_net` / `quantize_model` — quantize ARBITRARY Gluon block
    trees (custom HybridBlocks, zoo resnets with residual blocks, ...):
    every Dense/Conv2D instance's `forward` is re-routed through a mode
    switch, so whatever call path the net takes — eager, or traced inside
    a parent's hybridize()/jit — hits the int8 twin. This replaces the
    reference's symbol-graph rewrite with the JAX-native equivalent
    (rewire at trace time, let XLA fuse the requantization chain).

Calibration (reference calib_mode semantics):
  * 'naive'   — max-abs of each layer's input over the calib batches.
  * 'entropy' — KL-divergence-optimal clipping threshold per layer
    (reference: _get_optimal_threshold): histogram |x| into 2048 bins,
    scan candidate thresholds, pick the one whose 128-level quantized
    distribution minimises KL(P||Q). Ignoring rare outliers tightens the
    scale and recovers accuracy on heavy-tailed activations.
  * None      — no calibration; activations quantize dynamically.

Excluded layers (first/last, by name) mirror the reference's
`excluded_sym_names`.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply

__all__ = ["quantize", "dequantize", "quantize_channelwise",
           "QuantizedDense", "QuantizedConv2D",
           "quantize_net", "quantize_model", "kl_optimal_threshold"]


# canonical symmetric-int8 scale shared with the op-level surface
# (ops/contrib_ops.int8_scale) — one formula, one place
from ..ops.contrib_ops import int8_scale as _scale_of  # noqa: E402


_ACTS = {
    None: lambda y: y,
    "relu": lambda y: jnp.maximum(y, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
}


def _act_fn(name, layer_name):
    if name not in _ACTS:
        raise MXNetError(
            f"quantized layer {layer_name!r}: unsupported activation "
            f"{name!r} (supported: {sorted(k for k in _ACTS if k)})")
    return _ACTS[name]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """Symmetric int8 quantization. Returns (quantized, min_range,
    max_range) like the reference's quantize op. min/max default to the
    observed +-absmax."""
    if out_type != "int8":
        raise MXNetError("TPU quantization is int8 (MXU-native)")
    def _to_float(r):
        if r is None:
            return 0.0
        return float(r.asnumpy()) if hasattr(r, "asnumpy") else float(r)

    calib = None
    if min_range is not None or max_range is not None:
        calib = max(abs(_to_float(min_range)), abs(_to_float(max_range)))

    def f(x):
        amax = jnp.float32(calib) if calib is not None \
            else jnp.max(jnp.abs(x))
        scale = _scale_of(amax)
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return q, -amax, amax

    if isinstance(data, NDArray):
        return _apply(f, [data], n_out=3)
    return f(data)


def dequantize(data, min_range, max_range):
    """int8 -> float32 (reference: dequantize op). Ranges may be NDArrays,
    jax arrays, or plain floats."""
    def f(q, mn, mx):
        scale = _scale_of(jnp.maximum(jnp.abs(mn), jnp.abs(mx)))
        return q.astype(jnp.float32) * scale

    if isinstance(data, NDArray):
        def lift(r):
            return r if isinstance(r, NDArray) else NDArray(jnp.asarray(r))
        return _apply(f, [data, lift(min_range), lift(max_range)])
    return f(data, jnp.asarray(min_range), jnp.asarray(max_range))


# ---------------------------------------------------------------------------
# KL (entropy) calibration
# ---------------------------------------------------------------------------
_HIST_BINS = 2048
_QUANT_LEVELS = 128


def kl_optimal_threshold(hist, amax, num_quantized_bins=_QUANT_LEVELS):
    """KL-divergence-optimal clipping threshold (reference:
    contrib.quantization._get_optimal_threshold; symmetric |x| variant).

    hist: counts of |x| over `len(hist)` uniform bins spanning [0, amax].
    Scans thresholds T = edge(i) for i in [num_quantized_bins, n]: P is the
    clipped distribution (outlier mass folded into the last bin), Q is P
    merged into num_quantized_bins levels and re-expanded over P's support.
    Returns the T minimising KL(P||Q)."""
    hist = np.asarray(hist, np.float64)
    n = len(hist)
    if amax <= 0 or hist.sum() == 0:
        return max(amax, 1e-12)
    if hist.sum() < 4 * num_quantized_bins:
        # too few calibration samples for a meaningful distribution: a
        # sparse histogram lets a tiny threshold reach KL~0 by capturing
        # a handful of low bins. Fall back to max-abs (naive) behaviour.
        return amax
    bin_width = amax / n
    best_i, best_kl = n, np.inf
    for i in range(num_quantized_bins, n + 1):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()           # clip: outliers -> edge bin
        nonzero = p > 0
        # quantize the i bins into num_quantized_bins merged levels
        factor = i / num_quantized_bins
        idx = np.minimum((np.arange(i) / factor).astype(np.int64),
                         num_quantized_bins - 1)
        q_merged = np.bincount(idx, weights=hist[:i],
                               minlength=num_quantized_bins)
        # expand each level uniformly over its NONZERO source bins
        counts = np.bincount(idx, weights=nonzero.astype(np.float64),
                             minlength=num_quantized_bins)
        expand = np.where(counts > 0, q_merged / np.maximum(counts, 1), 0.0)
        q = expand[idx] * nonzero
        p_sum, q_sum = p.sum(), q.sum()
        if q_sum == 0:
            continue
        p_n = p / p_sum
        q_n = q / q_sum
        mask = (p_n > 0) & (q_n > 0)
        kl = float(np.sum(p_n[mask] * np.log(p_n[mask] / q_n[mask])))
        # P mass with no Q support contributes +inf in theory; penalise
        kl += float(np.sum(p_n[(p_n > 0) & (q_n == 0)])) * 10.0
        if kl < best_kl:
            best_kl, best_i = kl, i
    return best_i * bin_width


def quantize_channelwise(w, axis=0):
    """Symmetric PER-CHANNEL int8 quantization (ISSUE 14): an independent
    scale per index along `axis` (for a Dense weight (out, in), axis=0 is
    per-OUTPUT-channel — the granularity that lets the dequant fold into
    the matmul epilogue as one per-column multiply). Returns
    (int8 array, float32 scale vector of length w.shape[axis]) with
    x ≈ q * scale broadcast along `axis`. A channel of all zeros gets the
    minimum scale (its values quantize to 0 exactly)."""
    w = jnp.asarray(w)
    axis = axis % w.ndim
    red = tuple(i for i in range(w.ndim) if i != axis)
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=red)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    shape = [1] * w.ndim
    shape[axis] = -1
    q = jnp.clip(jnp.round(wf / scale.reshape(shape)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_weight(w):
    """fp weight -> (int8 weight, fp32 scale), symmetric per-tensor."""
    amax = float(jnp.max(jnp.abs(w)))
    scale = max(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, np.float32(scale)


def _dyn_act_scale(x):
    return _scale_of(jnp.max(jnp.abs(x)))


class _QuantizedBase:
    """Common int8 layer mechanics; not a Block — forward is pure and goes
    through _apply so it records on the tape and traces under jit."""

    def __init__(self, name):
        self.name = name
        self._act_scale = None      # set by calibration; else dynamic
        self._act_unsigned = False  # uint8 activation path (zero-point 128)
        self._amax = 0.0
        self._min_seen = np.inf
        self._hist = None           # |x| histogram for entropy calib

    def observe(self, x, collect_hist=False):
        """Calibration pass 1: track max-abs (and min, for uint8 'auto').
        Pass 2 (collect_hist=True): accumulate the |x| histogram over
        [0, amax] for the KL threshold search."""
        xv = np.asarray(x._data if isinstance(x, NDArray) else x,
                        np.float32)
        if collect_hist:
            h, _ = np.histogram(np.abs(xv), bins=_HIST_BINS,
                                range=(0.0, max(self._amax, 1e-12)))
            self._hist = h if self._hist is None else self._hist + h
            return
        self._amax = max(self._amax, float(np.max(np.abs(xv))))
        self._min_seen = min(self._min_seen, float(np.min(xv)))

    def finalize_calibration(self, calib_mode, quantized_dtype):
        """Turn observed stats into a static activation scale + signedness."""
        amax = self._amax
        if calib_mode == "entropy" and self._hist is not None:
            amax = kl_optimal_threshold(self._hist, self._amax)
        unsigned = (quantized_dtype == "uint8"
                    or (quantized_dtype == "auto" and self._min_seen >= 0.0))
        self._act_unsigned = bool(unsigned)
        levels = 255.0 if unsigned else 127.0
        self._act_scale = np.float32(max(amax, 1e-12) / levels)


def _quantize_act(xf, s_x, unsigned):
    """fp activation -> (int8 array fed to the MXU, needs_correction).

    signed:   q = clip(round(x/s), -127, 127) : int8
    unsigned: q = clip(round(x/s), 0, 255) - 128 : int8, plus a +128
              correction applied by the caller (zero-point decomposition
              keeps the hardware op int8 x int8)."""
    if unsigned:
        qu = jnp.clip(jnp.round(xf / s_x), 0, 255)
        return (qu - 128).astype(jnp.int8), True
    return jnp.clip(jnp.round(xf / s_x), -127, 127).astype(jnp.int8), False


class QuantizedDense(_QuantizedBase):
    """int8 y = (x_q @ W_q^T) * (s_x * s_w) + b (reference:
    quantized_fully_connected). Weight held int8; activation quantized
    dynamically unless calibrated."""

    def __init__(self, dense):
        super().__init__(getattr(dense, "name", "dense"))
        w = dense.weight.data()._data.astype(jnp.float32)
        self.wq, self.w_scale = _quantize_weight(w)
        # zero-point correction: +128 * sum_in W_q[o, in] per output
        self._corr = 128 * jnp.sum(self.wq.astype(jnp.int32), axis=1)
        self.bias = (dense.bias.data()._data.astype(jnp.float32)
                     if getattr(dense, "bias", None) is not None else None)
        self._flatten = getattr(dense, "_flatten", True)
        self._act = _act_fn(getattr(dense, "_activation", None), self.name)

    def __call__(self, x):
        wq, w_scale, corr = self.wq, self.w_scale, self._corr
        bias, act = self.bias, self._act
        static_scale = self._act_scale
        unsigned = self._act_unsigned
        flatten = self._flatten

        def f(xv):
            if flatten and xv.ndim > 2:
                xv = xv.reshape(xv.shape[0], -1)
            xf = xv.astype(jnp.float32)
            s_x = static_scale if static_scale is not None \
                else _dyn_act_scale(xf)
            xq, needs_corr = _quantize_act(xf, s_x, unsigned)
            acc = jax.lax.dot_general(
                xq, wq, (((xq.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            if needs_corr:
                acc = acc + corr
            y = acc.astype(jnp.float32) * (s_x * w_scale)
            if bias is not None:
                y = y + bias
            return act(y)

        return _apply(f, [x] if isinstance(x, NDArray) else [NDArray(x)])


class QuantizedConv2D(_QuantizedBase):
    """int8 NHWC/NCHW conv -> int32 accum -> fp rescale (reference:
    quantized_conv)."""

    def __init__(self, conv):
        super().__init__(getattr(conv, "name", "conv"))
        w = conv.weight.data()._data.astype(jnp.float32)
        self.wq, self.w_scale = _quantize_weight(w)
        self.bias = (conv.bias.data()._data.astype(jnp.float32)
                     if getattr(conv, "bias", None) is not None else None)
        self._stride = getattr(conv, "_strides", 1)
        self._pad = getattr(conv, "_padding", 0)
        self._dilation = getattr(conv, "_dilation", 1)
        self._groups = getattr(conv, "_groups", 1)
        self._layout = getattr(conv, "_layout", None) or "NCHW"
        self._act = _act_fn(getattr(conv, "_activation", None), self.name)
        self._corr_cache = {}   # input shape -> +128 correction map

    def _conv_args(self, ndim):
        stride, pad, dilation = self._stride, self._pad, self._dilation
        st = (stride,) * ndim if isinstance(stride, int) else tuple(stride)
        pd = (pad,) * ndim if isinstance(pad, int) else tuple(pad)
        dl = (dilation,) * ndim if isinstance(dilation, int) \
            else tuple(dilation)
        return st, pd, dl

    def _correction(self, shape, dn, st, pd, dl):
        """+128 * conv(ones) int32 map (border-aware under zero padding);
        one int8 conv per distinct input shape, cached. Never caches a
        tracer: a value produced inside someone else's jit trace must not
        leak to later eager calls (UnexpectedTracerError)."""
        key = tuple(shape)
        cached = self._corr_cache.get(key)
        if cached is not None:
            return cached
        ones = jnp.ones(shape, jnp.int8)
        corr = 128 * jax.lax.conv_general_dilated(
            ones, self.wq, window_strides=st,
            padding=tuple((p, p) for p in pd), rhs_dilation=dl,
            feature_group_count=self._groups, dimension_numbers=dn,
            preferred_element_type=jnp.int32)
        if not isinstance(corr, jax.core.Tracer):
            self._corr_cache[key] = corr
        return corr

    def __call__(self, x):
        wq, w_scale = self.wq, self.w_scale
        bias, act = self.bias, self._act
        layout, groups = self._layout, self._groups
        static_scale = self._act_scale
        unsigned = self._act_unsigned
        me = self

        def f(xv):
            from jax import lax
            xf = xv.astype(jnp.float32)
            s_x = static_scale if static_scale is not None \
                else _dyn_act_scale(xf)
            xq, needs_corr = _quantize_act(xf, s_x, unsigned)
            st, pd, dl = me._conv_args(xv.ndim - 2)
            spatial = layout.replace("N", "").replace("C", "")
            rhs = ("OI" + spatial) if layout.index("C") == 1 \
                else ("O" + spatial + "I")
            dn = lax.conv_dimension_numbers(xq.shape, wq.shape,
                                            (layout, rhs, layout))
            acc = lax.conv_general_dilated(
                xq, wq, window_strides=st,
                padding=tuple((p, p) for p in pd),
                rhs_dilation=dl, feature_group_count=groups,
                dimension_numbers=dn, preferred_element_type=jnp.int32)
            if needs_corr:
                acc = acc + me._correction(xq.shape, dn, st, pd, dl)
            y = acc.astype(jnp.float32) * (s_x * w_scale)
            if bias is not None:
                c_axis = layout.index("C")
                shape = [1] * y.ndim
                shape[c_axis] = -1
                y = y + bias.reshape(shape)
            return act(y)

        return _apply(f, [x] if isinstance(x, NDArray) else [NDArray(x)])


# ---------------------------------------------------------------------------
# arbitrary-block rewiring
# ---------------------------------------------------------------------------
class _Router:
    """Mode switch installed as `instance.forward` on each quantized layer.

    Modes: 'fp32' (original math — the net behaves as if untouched),
    'observe'/'hist' (original math, feeding the twin's calibrator),
    'int8' (the quantized twin). The instance attribute shadows the class
    method, so EVERY call path — eager, container, custom hybrid_forward,
    or a parent's jit trace — routes through it."""

    def __init__(self, orig_forward, twin, ctl):
        self._orig = orig_forward
        self.twin = twin
        self._ctl = ctl

    def __call__(self, x, *args, **kwargs):
        mode = self._ctl["mode"]
        if mode == "int8":
            return self.twin(x)
        if mode == "observe":
            self.twin.observe(x)
        elif mode == "hist":
            self.twin.observe(x, collect_hist=True)
        return self._orig(x, *args, **kwargs)


def _walk_layers(block, path="", seen=None):
    """Yield (path, block) for every descendant, depth-first."""
    seen = set() if seen is None else seen
    for name, child in getattr(block, "_children", {}).items():
        if id(child) in seen:
            continue
        seen.add(id(child))
        cpath = f"{path}.{name}" if path else name
        yield cpath, child
        yield from _walk_layers(child, cpath, seen)


def _swap_caches(block, store, seen=None):
    """Temporarily swap every HybridBlock's compiled-fn cache for a
    mode-private one: a trace baked with fp32 layers must never serve an
    int8 call (and vice versa)."""
    seen = set() if seen is None else seen
    if id(block) in seen:
        return
    seen.add(id(block))
    if hasattr(block, "_cached_fns"):
        store.setdefault(id(block), {})
        block._cached_fns, store[id(block)] = \
            store[id(block)], block._cached_fns
    for child in getattr(block, "_children", {}).values():
        _swap_caches(child, store, seen)


class QuantizedNet:
    """Result of quantize_net: same call signature as the source block,
    with every quantized layer running int8 — arbitrary block trees
    included. The source network still computes fp32 when called directly
    (the routers sit idle in 'fp32' mode outside QuantizedNet calls)."""

    def __init__(self, block, routers):
        self._block = block
        self._routers = routers            # path -> _Router
        self._ctl = routers[next(iter(routers))]._ctl if routers else \
            {"mode": "fp32"}
        self._q_caches = {}

    def _run(self, args, mode):
        # internal contract: `args` is ALWAYS the tuple of net inputs —
        # a tuple-valued single input is never splatted by accident
        self._ctl["mode"] = mode
        # calibration reads concrete activation values (np.asarray) — it
        # must NEVER run inside a jit trace, so hybridization is forced
        # off for observe/hist passes
        deactivated = []
        if mode in ("observe", "hist"):
            for _, b in _walk_layers(self._block):
                if getattr(b, "_active", False):
                    b._active = False
                    deactivated.append(b)
            if getattr(self._block, "_active", False):
                self._block._active = False
                deactivated.append(self._block)
        _swap_caches(self._block, self._q_caches)
        try:
            return self._block(*args)
        finally:
            _swap_caches(self._block, self._q_caches)
            for b in deactivated:
                b._active = True
            self._ctl["mode"] = "fp32"

    def __call__(self, *args):
        # multi-input nets (BERT: token_ids, segment_ids, ...) pass
        # through as-is; single-input callers are unchanged
        if not args:
            raise MXNetError("QuantizedNet expects at least one input")
        return self._run(args, "int8")

    @property
    def quantized_layers(self):
        return [r.twin for r in self._routers.values()]


def quantize_net(network, quantized_dtype="int8", exclude_layers=None,
                 calib_data=None, num_calib_batches=None,
                 calib_mode="naive", calib_inputs=1, **kwargs):
    """Quantize a Gluon net's Dense/Conv2D layers to int8/uint8
    (reference: contrib.quantization.quantize_net). Works on ARBITRARY
    block trees — zoo models with custom residual blocks included.
    Returns a callable QuantizedNet; the original net keeps its fp32
    behaviour when called directly.

    calib_data: iterable of input batches (or (data, label) tuples) used
    to fix activation scales. calib_mode: 'naive' (max-abs) or 'entropy'
    (KL-optimal thresholds; needs calib_data). quantized_dtype: 'int8',
    'uint8' (zero-point-decomposed activations), or 'auto' (uint8 where
    the calibrated activation range is non-negative)."""
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise MXNetError("quantized_dtype must be int8, uint8, or auto")
    if calib_mode not in (None, "none", "naive", "entropy"):
        raise MXNetError("calib_mode must be 'naive', 'entropy', or None")
    if calib_mode == "entropy" and calib_data is None:
        raise MXNetError("calib_mode='entropy' requires calib_data")
    if quantized_dtype in ("uint8", "auto") and (
            calib_data is None or calib_mode not in ("naive", "entropy")):
        raise MXNetError(f"quantized_dtype={quantized_dtype!r} requires "
                         "calib_data AND calib_mode='naive'|'entropy' "
                         "(signedness is a calibration-time decision)")
    exclude = set(exclude_layers or [])
    ctl = {"mode": "fp32"}
    routers = {}
    for cpath, child in _walk_layers(network):
        cls = type(child).__name__
        if cpath in exclude or cls in exclude \
                or getattr(child, "name", None) in exclude:
            continue
        if cls == "Dense":
            twin = QuantizedDense(child)
        elif cls == "Conv2D":
            twin = QuantizedConv2D(child)
        else:
            continue
        router = _Router(child.forward, twin, ctl)
        child.forward = router       # instance attr shadows class method
        routers[cpath] = router
    if not routers:
        raise MXNetError("no quantizable (Dense/Conv2D) layers found")
    qnet = QuantizedNet(network, routers)

    if calib_data is not None and calib_mode in ("naive", "entropy"):
        batches = []
        n = 0
        for batch in calib_data:
            if isinstance(batch, (tuple, list)):
                # (data, label) convention by default; calib_inputs=k
                # feeds the first k elements as the net's inputs (multi-
                # input nets like BERT: (token_ids, segment_ids, ...))
                x = tuple(batch[:calib_inputs])
            else:
                x = (batch,)
            batches.append(x)
            qnet._run(x, "observe")       # pass 1: amax/min ranges
            n += 1
            if num_calib_batches is not None and n >= num_calib_batches:
                break
        if calib_mode == "entropy":
            for x in batches:             # pass 2: histograms in [0, amax]
                qnet._run(x, "hist")
        for r in routers.values():
            r.twin.finalize_calibration(calib_mode, quantized_dtype)
    return qnet


def quantize_model(sym_or_net, *args, **kwargs):
    """Reference-named entry: quantize a Gluon block (the Symbol/Module
    path quantizes the bound net the same way)."""
    return quantize_net(sym_or_net, *args, **kwargs)
