// Native dependency engine (reference: src/engine/threaded_engine.cc,
// threaded_engine_perdevice.cc — re-designed, not translated).
//
// Role in the TPU build: XLA/PJRT owns on-device scheduling, so this engine
// schedules HOST-side async work (data pipeline, IO, serialisation) with the
// same read/write-variable dependency semantics MXNet's ThreadedEngine gives
// kernels:
//   * ops that READ a var run concurrently with other readers;
//   * an op that WRITES a var waits for all prior readers+writer and blocks
//     later ops until it completes (program order per var);
//   * WaitForVar blocks until every op touching the var so far is done;
//   * WaitForAll blocks until the engine drains.
//
// Exposed as a plain C ABI consumed via ctypes (mxnet_tpu/_native.py).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Op;

struct VarState {
  std::deque<std::pair<Op*, bool>> queue;  // (op, is_write) in program order
  int running_reads = 0;
  bool running_write = false;
};

struct Op {
  void (*fn)(void*);
  void* arg;
  std::vector<uint64_t> reads;
  std::vector<uint64_t> writes;
  std::atomic<int> wait{0};
};

class Engine {
 public:
  explicit Engine(int workers) : workers_(workers > 0 ? workers : 1) {
    for (int i = 0; i < workers_; ++i)
      threads_.emplace_back([this] { WorkerLoop(); });
  }

  ~Engine() {
    {
      std::unique_lock<std::mutex> lk(ready_mu_);
      shutdown_ = true;
    }
    ready_cv_.notify_all();
    for (auto& t : threads_) t.join();
  }

  uint64_t NewVar() {
    std::unique_lock<std::mutex> lk(vars_mu_);
    uint64_t id = next_var_++;
    vars_.emplace(id, VarState{});
    return id;
  }

  void DelVar(uint64_t v) {
    // deferred: only erase when idle on that var (caller guarantees no
    // in-flight ops, matching Engine::DeleteVariable semantics)
    std::unique_lock<std::mutex> lk(vars_mu_);
    auto it = vars_.find(v);
    if (it != vars_.end() && it->second.queue.empty() &&
        it->second.running_reads == 0 && !it->second.running_write)
      vars_.erase(it);
  }

  void Push(void (*fn)(void*), void* arg, const uint64_t* reads, int nreads,
            const uint64_t* writes, int nwrites) {
    Op* op = new Op();
    op->fn = fn;
    op->arg = arg;
    op->reads.assign(reads, reads + nreads);
    op->writes.assign(writes, writes + nwrites);
    pending_.fetch_add(1);
    // wait on every var; each var either admits the op now or queues it
    op->wait.store(nreads + nwrites + 1);  // +1 guard against races below
    {
      std::unique_lock<std::mutex> lk(vars_mu_);
      for (uint64_t v : op->reads) AdmitOrQueue(op, v, /*is_write=*/false);
      for (uint64_t v : op->writes) AdmitOrQueue(op, v, /*is_write=*/true);
    }
    FinishDep(op);  // drop the guard
  }

  void WaitForVar(uint64_t v) {
    std::unique_lock<std::mutex> lk(vars_mu_);
    idle_cv_.wait(lk, [&] {
      auto it = vars_.find(v);
      if (it == vars_.end()) return true;
      const VarState& s = it->second;
      return s.queue.empty() && s.running_reads == 0 && !s.running_write;
    });
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(vars_mu_);
    idle_cv_.wait(lk, [&] { return pending_.load() == 0; });
  }

  int workers() const { return workers_; }

 private:
  // vars_mu_ must be held
  void AdmitOrQueue(Op* op, uint64_t v, bool is_write) {
    VarState& s = vars_[v];
    bool can_run = s.queue.empty() && !s.running_write &&
                   (!is_write || s.running_reads == 0);
    if (can_run) {
      if (is_write)
        s.running_write = true;
      else
        ++s.running_reads;
      FinishDepLocked(op);
    } else {
      s.queue.emplace_back(op, is_write);
    }
  }

  void FinishDep(Op* op) {
    if (op->wait.fetch_sub(1) == 1) Enqueue(op);
  }

  void FinishDepLocked(Op* op) { FinishDep(op); }

  void Enqueue(Op* op) {
    {
      std::unique_lock<std::mutex> lk(ready_mu_);
      ready_.push_back(op);
    }
    ready_cv_.notify_one();
  }

  void WorkerLoop() {
    for (;;) {
      Op* op;
      {
        std::unique_lock<std::mutex> lk(ready_mu_);
        ready_cv_.wait(lk, [&] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        op = ready_.front();
        ready_.pop_front();
      }
      op->fn(op->arg);
      Complete(op);
    }
  }

  void Complete(Op* op) {
    std::vector<Op*> unblocked;
    {
      std::unique_lock<std::mutex> lk(vars_mu_);
      for (uint64_t v : op->reads) Release(v, /*is_write=*/false, &unblocked);
      for (uint64_t v : op->writes) Release(v, /*is_write=*/true, &unblocked);
      pending_.fetch_sub(1);
    }
    idle_cv_.notify_all();
    for (Op* u : unblocked) FinishDep(u);
    delete op;
  }

  // vars_mu_ must be held; collects ops whose dep count on v resolves
  void Release(uint64_t v, bool is_write, std::vector<Op*>* unblocked) {
    auto it = vars_.find(v);
    if (it == vars_.end()) return;
    VarState& s = it->second;
    if (is_write)
      s.running_write = false;
    else
      --s.running_reads;
    // drain: a write runs alone; consecutive reads run together
    while (!s.queue.empty()) {
      auto [op, w] = s.queue.front();
      if (w) {
        if (s.running_reads == 0 && !s.running_write) {
          s.running_write = true;
          s.queue.pop_front();
          unblocked->push_back(op);
        }
        break;
      }
      if (s.running_write) break;
      ++s.running_reads;
      s.queue.pop_front();
      unblocked->push_back(op);
    }
  }

  const int workers_;
  std::vector<std::thread> threads_;

  std::mutex vars_mu_;
  std::unordered_map<uint64_t, VarState> vars_;
  uint64_t next_var_ = 1;
  std::atomic<int> pending_{0};
  std::condition_variable idle_cv_;  // waits on vars_mu_

  std::mutex ready_mu_;
  std::condition_variable ready_cv_;
  std::deque<Op*> ready_;
  bool shutdown_ = false;
};

}  // namespace

extern "C" {

void* MXTPUEngineCreate(int workers) { return new Engine(workers); }
void MXTPUEngineDelete(void* h) { delete static_cast<Engine*>(h); }
uint64_t MXTPUEngineNewVar(void* h) {
  return static_cast<Engine*>(h)->NewVar();
}
void MXTPUEngineDelVar(void* h, uint64_t v) {
  static_cast<Engine*>(h)->DelVar(v);
}
void MXTPUEnginePush(void* h, void (*fn)(void*), void* arg,
                     const uint64_t* reads, int nreads, const uint64_t* writes,
                     int nwrites) {
  static_cast<Engine*>(h)->Push(fn, arg, reads, nreads, writes, nwrites);
}
void MXTPUEngineWaitForVar(void* h, uint64_t v) {
  static_cast<Engine*>(h)->WaitForVar(v);
}
void MXTPUEngineWaitAll(void* h) { static_cast<Engine*>(h)->WaitAll(); }
int MXTPUEngineNumWorkers(void* h) {
  return static_cast<Engine*>(h)->workers();
}

}  // extern "C"
