"""gluon.contrib.nn (reference: python/mxnet/gluon/contrib/nn/basic_layers.py).

TPU-native SyncBatchNorm: the reference synchronises BN statistics across
GPUs with an NCCL allreduce inside a CUDA kernel (num_devices, key-based
comm). Here cross-replica reduction is `lax.pmean` over a *mesh axis name* —
inside a `shard_map`/`pjit` data-parallel step the statistics ride the ICI
allreduce XLA inserts; outside any mesh context the layer degrades to plain
BatchNorm (single-replica semantics, exactly what the reference does with
num_devices=1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ... import autograd
from ...base import MXNetError
from ...ndarray.ndarray import _apply
from ..block import HybridBlock
from ..nn.basic_layers import BatchNorm, Concurrent, Identity

__all__ = ["SyncBatchNorm", "HybridConcurrent", "Concurrent", "Identity",
           "PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D",
           "SparseEmbedding"]

# reference exposes HybridConcurrent as the hybridizable variant; the
# TPU-native Concurrent is already hybrid-safe (pure fan-out + concat)
HybridConcurrent = Concurrent


def _maybe_pmean(v, axis_name):
    """pmean over `axis_name` when bound in the current trace (i.e. inside
    shard_map over a mesh with that axis); identity otherwise."""
    if axis_name is None:
        return v
    try:
        return lax.pmean(v, axis_name)
    except NameError:
        return v


def sync_batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-5,
                    momentum=0.9, training=True, axis=1, axis_name="dp"):
    """BatchNorm with cross-replica statistics (one fused fp32 moment pass
    + pmean over the mesh axis). Returns (y, new_mean, new_var)."""
    from ...ops.nn_ops import batch_norm
    if not training:
        return batch_norm(x, gamma, beta, moving_mean, moving_var, eps,
                          momentum, False, axis)
    red = tuple(i for i in range(x.ndim) if i != axis)
    xf = x.astype(jnp.float32)
    m = _maybe_pmean(jnp.mean(xf, red), axis_name)
    m2 = _maybe_pmean(jnp.mean(xf * xf, red), axis_name)
    var = jnp.maximum(m2 - m * m, 0.0)
    shape = [1] * x.ndim
    shape[axis] = -1
    inv = lax.rsqrt(var + eps)
    scale = (gamma.astype(jnp.float32) * inv).reshape(shape)
    shift = (beta.astype(jnp.float32)
             - gamma.astype(jnp.float32) * m * inv).reshape(shape)
    y = (xf * scale + shift).astype(x.dtype)
    new_mean = (momentum * moving_mean.astype(jnp.float32)
                + (1 - momentum) * m).astype(moving_mean.dtype)
    new_var = (momentum * moving_var.astype(jnp.float32)
               + (1 - momentum) * var).astype(moving_var.dtype)
    return y, new_mean, new_var


class SyncBatchNorm(BatchNorm):
    """Cross-replica BatchNorm (reference: gluon.contrib.nn.SyncBatchNorm).

    `axis_name` names the mesh axis to reduce statistics over (the
    reference's num_devices/comm-key pair maps to a jax mesh axis). Used
    inside a data-parallel shard_map step the stats are global-batch; used
    eagerly it is a plain BatchNorm.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, axis=1, axis_name="dp", **kwargs):
        super().__init__(axis=axis, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._axis_name = axis_name  # num_devices accepted for API parity

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ..block import _report_aux_update
        training = autograd.is_training() and not self._use_global_stats
        outs = _apply(
            lambda a, g, b, mm, mv, _e=self._epsilon, _m=self._momentum,
            _t=training, _ax=self._axis, _an=self._axis_name:
            sync_batch_norm(a, g, b, mm, mv, _e, _m, _t, _ax, _an),
            [x, gamma, beta, running_mean, running_var], n_out=3)
        out, new_mean, new_var = outs
        if training:
            _report_aux_update(self.running_mean, new_mean)
            _report_aux_update(self.running_var, new_var)
        return out


def _pixel_shuffle(x, factors, ndim):
    """Rearrange (N, C*prod(f), *S) -> (N, C, *S*f) (reference:
    contrib.nn.PixelShuffle*D, NC* layouts)."""
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    cf = 1
    for f in factors:
        cf *= f
    if c % cf:
        raise MXNetError(f"channels {c} not divisible by {factors}")
    c_out = c // cf
    # (N, C_out, *factors, *S) -> interleave factor axes after each spatial
    x = x.reshape((n, c_out) + tuple(factors) + spatial)
    perm = [0, 1]
    for i in range(ndim):
        perm.extend([2 + ndim + i, 2 + i])
    x = x.transpose(perm)
    out_spatial = tuple(s * f for s, f in zip(spatial, factors))
    return x.reshape((n, c_out) + out_spatial)


class _PixelShuffle(HybridBlock):
    _ndim = None

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor,) * self._ndim
        self._factors = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        return _apply(lambda a, _f=self._factors, _n=self._ndim:
                      _pixel_shuffle(a, _f, _n), [x])

    def __repr__(self):
        return f"{type(self).__name__}(factor={self._factors})"


class PixelShuffle1D(_PixelShuffle):
    _ndim = 1


class PixelShuffle2D(_PixelShuffle):
    _ndim = 2


class PixelShuffle3D(_PixelShuffle):
    _ndim = 3


def SparseEmbedding(*args, **kwargs):
    raise MXNetError(
        "SparseEmbedding is a documented divergence (SURVEY.md §8): TPU/XLA "
        "has no sparse storage; dense gluon.nn.Embedding lowers to a "
        "take/one-hot matmul that the MXU executes efficiently")
