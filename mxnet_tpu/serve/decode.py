"""Serving decode runtime: ONE cached decode executable + ONE cached
prefill executable over device-resident paged KV state (ISSUE 6).

The decode step is compiled exactly once per server: every shape in the
program is static — `(slots, num_pages, page_size)` for the self-attention
page pools, `(slots, max_src_len)` for the per-slot encoder memory — and
everything that changes between steps (slot occupancy, page tables,
per-slot lengths, current tokens) rides as ARGUMENTS, so ragged batch
composition never retraces (`decode_traces` stays 1; enforced by
tools/check_dispatch.py's serve phase in tier-1). The K/V page pools are
DONATED to the executable, so the per-step page writes are in-place
scatters into the same device buffers — the paged cache never doubles in
HBM.

Slot conventions (shared with serve.scheduler):

  * inactive slots route their scatter writes to the pool's reserved null
    page 0 and their outputs are garbage the scheduler never reads — no
    branches on occupancy inside the program;
  * `lens[s]` is the number of cached positions BEFORE this step — also
    the position index of the token being decoded (BOS decodes at 0);
  * page tables are padded with the null page, so unused entries gather
    valid memory.

The per-layer math is `models.transformer`'s factored decode core
(`decode_embed` / `decoder_layer_*`), and the self-attention is
`ops.pallas_kernels.ragged_paged_attention` — the Pallas kernel on TPU,
the shared-math lax gather on the CPU mesh — so a paged decode is
bitwise-identical to the dense-cache `decode_step` on equal context
width (tests/test_serve.py pins this).

Int8 KV cache (ISSUE 14, ``kv_dtype="int8"``): the page pools store
int8 with PER-PAGE / PER-HEAD f32 scales in parallel ``(L, P, H)``
arrays, so a fixed HBM page budget holds ~4x the tokens of fp32 pages
(~2x bf16) — directly more concurrent requests per chip on the
bandwidth-bound decode loop. Writes keep a RUNNING-MAX scale per page:
a token whose |K| exceeds the page's current range grows the scale and
requantises the page's existing rows in the same fused scatter (exact
no-op when the scale doesn't move — ratio 1.0 round-trips int8
losslessly); a write at page offset 0 RESETS the page (a freed page's
stale scale must not leak into its next owner). Scales are indexed by
page id, so prefix-cache page sharing and `defrag` carry them for free,
and all four pool arrays are donated — the executables stay 1 dispatch
/ 0 retraces (check_dispatch's quantized-serve phase gates this).
Dequantisation happens inside `ragged_paged_attention` (in-kernel on
TPU, gathered-context-only in the lax fallback).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import profiler
from ..base import MXNetError
from ..models.transformer import (decode_embed, decode_project,
                                  decoder_layer_qkv, decoder_layer_self_post,
                                  decoder_layer_cross,
                                  decoder_layer_cross_multi,
                                  decoder_layer_ffn,
                                  encode_memory, precompute_memory_kv)
from ..observability import registry as _obs_registry
from ..observability import tracer as _tracer
from ..observability import compilex as _compilex
from ..ops.pallas_kernels import ragged_paged_attention
from .kv_pages import NULL_PAGE

__all__ = ["DecodeRuntime", "MemoryStateLost"]


def _quant_page_write(pages, scales, li, page, off, vals):
    """Quantised paged K/V write with running-max per-page/per-head
    scales (ISSUE 14). pages: (L, P, psize, H, dh) int8; scales:
    (L, P, H) f32; page/off: (...,) int32 target page ids/offsets
    (inactive rows routed to the null page by the caller); vals:
    (..., H, dh) fp token projections. Leading dims are (S,) for the
    1-wide decode program and (S, W) for the widened verify program —
    duplicate page ids within a window are safe because every duplicate
    computes identical update values (scatter-max for scales, identical
    requantised blocks for content). Returns (pages, scales)."""
    f32 = scales.dtype
    amax = jnp.max(jnp.abs(vals.astype(f32)), axis=-1)       # (..., H)
    # a write at offset 0 starts the page's life: zero the stale content
    # AND scale a previous owner left behind (scales only ever grow
    # within a life, so without the reset a hot former tenant would
    # permanently coarsen the page's quantisation grid)
    fresh_page = jnp.zeros((pages.shape[1],), bool).at[
        jnp.where(off == 0, page, NULL_PAGE)].set(True)
    sc = scales[li]                                          # (P, H)
    sc0 = jnp.where(fresh_page[:, None], jnp.float32(0), sc)
    new_sc = sc0.at[page].max(amax / 127.0)
    old_g = sc[page]                                         # (..., H)
    new_g = new_sc[page]
    safe = jnp.maximum(new_g, 1e-30)
    ratio = jnp.where(new_g > 0, old_g / safe, jnp.float32(1))
    blk = pages[li, page].astype(f32)                # (..., psize, H, dh)
    blk = jnp.round(blk * ratio[..., None, :, None])
    blk = jnp.where(fresh_page[page][..., None, None, None],
                    jnp.float32(0), blk)
    tok = jnp.clip(jnp.round(vals.astype(f32) / safe[..., None]),
                   -127, 127)
    pages = pages.at[li, page].set(blk.astype(jnp.int8))
    pages = pages.at[li, page, off].set(tok.astype(jnp.int8))
    scales = scales.at[li].set(new_sc)
    return pages, scales


class MemoryStateLost(MXNetError):
    """A prefill dispatch failed AFTER consuming its donated encoder-
    memory buffers: every slot's cross-attention state is gone, not just
    the request being admitted. The runtime has already rebuilt zeroed
    buffers; the scheduler must restart ALL in-flight requests (their
    re-admission re-prefills each slot)."""


class DecodeRuntime:
    """Device state + the two cached executables of one serving engine.

    weights / enc_weights: `models.transformer.decoder_weights` /
    `encoder_weights` snapshots. All device state (K/V page pools, per-slot
    encoder memory) lives on this object; the scheduler only ever hands it
    host-side int arrays."""

    def __init__(self, weights, enc_weights, slots, num_pages, page_size,
                 max_pages_per_slot, max_src_len, width=1, kv_dtype=None):
        u = weights["embed"].shape[1]
        h = weights["num_heads"]
        if u % h:
            raise MXNetError("units not divisible by heads")
        self._w = weights
        self._ew = enc_weights
        self.slots = int(slots)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.max_src_len = int(max_src_len)
        self._h = h
        self._dh = u // h
        self._n_layers = len(weights["layers"])
        max_pos = weights["pos"].shape[0]
        if self.max_pages_per_slot * self.page_size > max_pos:
            raise MXNetError(
                f"page budget covers {self.max_pages_per_slot * page_size} "
                f"positions but the decoder pos table has only {max_pos}")
        enc_pos = enc_weights["pos"].shape[0]
        if self.max_src_len > enc_pos:
            raise MXNetError(
                f"max_src_len {self.max_src_len} exceeds the encoder pos "
                f"table ({enc_pos}) — every prefill would fail")
        if kv_dtype not in (None, "float32", "int8"):
            raise MXNetError(f"kv_dtype must be None/'float32'/'int8', "
                             f"got {kv_dtype!r}")
        self.kv_quant = kv_dtype == "int8"
        # compute dtype from the (always-fp) pos table, NOT the embed —
        # an int8-quantised weight snapshot keeps its embed in int8
        self._dtype = weights["pos"].dtype
        self.reset_pages()
        self.reset_mem()
        self.width = int(width)
        if self.width < 1:
            raise MXNetError("decode width must be >= 1")
        # retrace telemetry: the python bodies run ONLY while jax traces,
        # so these counters are exactly the number of compilations — the
        # check_dispatch serve gate asserts they stay at 1 across every
        # slot-occupancy / page-table variation (and, for the widened
        # verify executable, across every draft-acceptance variation)
        self.decode_traces = 0
        self.prefill_traces = 0
        self.verify_traces = 0
        # compile observatory: prefill vs decode publish as separate
        # executables (`compiles{executable=serve_decode}` == number of
        # decode compilations, the same invariant decode_traces counts —
        # check_fusion budgets the decode HLO, test_serve pins zero warm
        # recompiles against these counters). int8-KV runtimes publish
        # under their own *_int8 names so the quantized-serve budgets
        # (check_fusion) and the fp budgets never shadow each other.
        if self.kv_quant:
            self._decode_fn = _compilex.instrument(
                jax.jit(self._decode_program_q,
                        donate_argnums=(0, 1, 2, 3)),
                "serve_decode_int8")
        else:
            self._decode_fn = _compilex.instrument(
                jax.jit(self._decode_program, donate_argnums=(0, 1)),
                "serve_decode")
        self._prefill_fn = _compilex.instrument(
            jax.jit(self._prefill_program, donate_argnums=(0, 1, 2)),
            "serve_prefill")
        if self.kv_quant:
            self._remap_fn = _compilex.instrument(
                jax.jit(lambda kp, vp, ks, vs, perm:
                        (kp[:, perm], vp[:, perm],
                         ks[:, perm], vs[:, perm]),
                        donate_argnums=(0, 1, 2, 3)),
                "serve_page_remap")
        else:
            self._remap_fn = _compilex.instrument(
                jax.jit(lambda kp, vp, perm: (kp[:, perm], vp[:, perm]),
                        donate_argnums=(0, 1)),
                "serve_page_remap")
        # the WIDENED verify executable (ISSUE 12): width > 1 servers run
        # every decode turn through one (slots, width) program — drafted
        # tokens verified by a single batched target pass, chunked prompt
        # prefill teacher-forced width tokens at a time. Static shapes;
        # per-slot ragged window lengths ride as arguments, so varying
        # draft acceptance never retraces (verify_traces stays 1).
        self._verify_fn = None
        if self.width > 1:
            if self.kv_quant:
                self._verify_fn = _compilex.instrument(
                    jax.jit(self._verify_program_q,
                            donate_argnums=(0, 1, 2, 3)),
                    "serve_verify_int8")
            else:
                self._verify_fn = _compilex.instrument(
                    jax.jit(self._verify_program, donate_argnums=(0, 1)),
                    "serve_verify")
        # autotune (ISSUE 20): greedy decode is bitwise-contracted — a
        # compile-space candidate that moves ONE logit bit is rejected
        # by the search guard regardless of speed; these executables are
        # unsharded (plan None is the note_plan default, nothing to note)
        from .. import tune as _tune
        for _exe in ("serve_decode", "serve_decode_int8", "serve_prefill",
                     "serve_verify", "serve_verify_int8",
                     "serve_page_remap"):
            _tune.register_contract(_exe, "bitwise")

    # ------------------------------------------------------- programs
    # ONE decode/verify core each, shared by the fp and int8-KV entry
    # points (`k_scales is None` selects the write/attention form at
    # TRACE time — the fp programs lower to exactly the pre-ISSUE-14
    # HLO, so a decode-loop fix can never reach one precision and miss
    # the other).
    def _page_write(self, pages, scales, li, page, off, vals):
        if scales is None:
            return pages.at[li, page, off].set(vals), None
        return _quant_page_write(pages, scales, li, page, off, vals)

    def _decode_core(self, k_pages, v_pages, k_scales, v_scales,
                     page_tables, lens, tok, active, mem_k, mem_v,
                     mem_vl):
        w, h, psize = self._w, self._h, self.page_size
        s_n = tok.shape[0]
        x = decode_embed(w, tok, lens)                       # (S, U)
        rows = jnp.arange(s_n)
        page = page_tables[rows, lens // psize]
        page = jnp.where(active > 0, page, NULL_PAGE)
        off = lens % psize
        for li, L in enumerate(w["layers"]):
            q, k, v = decoder_layer_qkv(L, x)
            qh = q.reshape(s_n, h, self._dh)
            kh = k.reshape(s_n, h, self._dh)
            vh = v.reshape(s_n, h, self._dh)
            k_pages, k_scales = self._page_write(
                k_pages, k_scales, li, page, off, kh)
            v_pages, v_scales = self._page_write(
                v_pages, v_scales, li, page, off, vh)
            a = ragged_paged_attention(
                qh, k_pages[li], v_pages[li], page_tables, lens + 1,
                k_scales=None if k_scales is None else k_scales[li],
                v_scales=None if v_scales is None else v_scales[li])
            x = decoder_layer_self_post(L, x, a.reshape(s_n, h * self._dh))
            x = decoder_layer_cross(L, h, x, mem_k[li], mem_v[li], mem_vl)
            x = decoder_layer_ffn(L, x)
        logits = decode_project(w, x)
        next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return k_pages, v_pages, k_scales, v_scales, next_tok, logits

    def _verify_core(self, k_pages, v_pages, k_scales, v_scales,
                     page_tables, lens, toks, qlens, active, mem_k,
                     mem_v, mem_vl):
        """The widened decode step: toks (S, W) window tokens per slot at
        positions lens..lens+W-1, qlens (S,) valid window lengths (ragged
        — rows past qlen scatter to the null page and their outputs are
        garbage the scheduler never commits). Returns logits for EVERY
        window position, so one dispatch verifies a whole drafted run.
        int8 mode: window writes that share a page combine through the
        quantised write helper's scatter-max scales."""
        w, h, psize = self._w, self._h, self.page_size
        s_n, width = toks.shape
        npages = page_tables.shape[1]
        rows = jnp.arange(s_n)
        pos = lens[:, None] + jnp.arange(width, dtype=lens.dtype)[None, :]
        x = decode_embed(w, toks, pos)                   # (S, W, U)
        slot_page = jnp.minimum(pos // psize, npages - 1)
        page = page_tables[rows[:, None], slot_page]     # (S, W)
        valid = (jnp.arange(width)[None, :] < qlens[:, None]) \
            & (active[:, None] > 0)
        page = jnp.where(valid, page, NULL_PAGE)
        off = pos % psize
        for li, L in enumerate(w["layers"]):
            q, k, v = decoder_layer_qkv(L, x)
            qh = q.reshape(s_n, width, h, self._dh)
            kh = k.reshape(s_n, width, h, self._dh)
            vh = v.reshape(s_n, width, h, self._dh)
            k_pages, k_scales = self._page_write(
                k_pages, k_scales, li, page, off, kh)
            v_pages, v_scales = self._page_write(
                v_pages, v_scales, li, page, off, vh)
            # query i sees positions 0..lens+i (its own included): the
            # ragged-query-length form of the shared paged attention
            a = ragged_paged_attention(
                qh, k_pages[li], v_pages[li], page_tables, lens + 1,
                k_scales=None if k_scales is None else k_scales[li],
                v_scales=None if v_scales is None else v_scales[li])
            x = decoder_layer_self_post(
                L, x, a.reshape(s_n, width, h * self._dh))
            x = decoder_layer_cross_multi(L, h, x, mem_k[li], mem_v[li],
                                          mem_vl)
            x = decoder_layer_ffn(L, x)
        logits = decode_project(w, x)                    # (S, W, V)
        next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return k_pages, v_pages, k_scales, v_scales, next_tok, logits

    def _decode_program(self, k_pages, v_pages, page_tables, lens, tok,
                        active, mem_k, mem_v, mem_vl):
        self.decode_traces += 1
        k_pages, v_pages, _, _, next_tok, logits = self._decode_core(
            k_pages, v_pages, None, None, page_tables, lens, tok,
            active, mem_k, mem_v, mem_vl)
        return k_pages, v_pages, next_tok, logits

    def _verify_program(self, k_pages, v_pages, page_tables, lens, toks,
                        qlens, active, mem_k, mem_v, mem_vl):
        self.verify_traces += 1
        k_pages, v_pages, _, _, next_tok, logits = self._verify_core(
            k_pages, v_pages, None, None, page_tables, lens, toks,
            qlens, active, mem_k, mem_v, mem_vl)
        return k_pages, v_pages, next_tok, logits

    def _decode_program_q(self, k_pages, v_pages, k_scales, v_scales,
                          page_tables, lens, tok, active, mem_k, mem_v,
                          mem_vl):
        """The int8-KV decode step (ISSUE 14): the shared core with
        page writes through the running-max quantiser and the attention
        launch dequantising with the per-page scales. All four pool
        arrays are donated — still ONE dispatch, still zero retraces
        across occupancy."""
        self.decode_traces += 1
        return self._decode_core(k_pages, v_pages, k_scales, v_scales,
                                 page_tables, lens, tok, active, mem_k,
                                 mem_v, mem_vl)

    def _verify_program_q(self, k_pages, v_pages, k_scales, v_scales,
                          page_tables, lens, toks, qlens, active, mem_k,
                          mem_v, mem_vl):
        """The int8-KV widened verify step (see `_verify_core`)."""
        self.verify_traces += 1
        return self._verify_core(k_pages, v_pages, k_scales, v_scales,
                                 page_tables, lens, toks, qlens, active,
                                 mem_k, mem_v, mem_vl)

    def _prefill_program(self, mem_k, mem_v, mem_vl, src, src_len, slot):
        self.prefill_traces += 1
        memory = encode_memory(self._ew, src, src_len)       # (1, Ssrc, U)
        kv = precompute_memory_kv(self._w, memory)
        mk = jnp.stack([k for k, _ in kv])   # (n_layers, 1, H, Ssrc, dh)
        mv = jnp.stack([v for _, v in kv])
        mem_k = lax.dynamic_update_slice(mem_k, mk, (0, slot, 0, 0, 0))
        mem_v = lax.dynamic_update_slice(mem_v, mv, (0, slot, 0, 0, 0))
        mem_vl = lax.dynamic_update_slice(mem_vl,
                                          src_len.astype(jnp.int32), (slot,))
        return mem_k, mem_v, mem_vl

    # ---------------------------------------------------------- calls
    def prefill(self, slot, src_tokens, src_len=None):
        """Encode one request's source into decode slot `slot`: pads to
        the static (1, max_src_len) shape, runs the cached prefill
        executable (encoder + cross-attention K/V projection + slot
        write, ONE dispatch) against the donated memory buffers."""
        src = np.asarray(src_tokens, np.int32).reshape(-1)
        if src_len is None:
            src_len = src.size
        if src.size > self.max_src_len:
            raise MXNetError(f"source length {src.size} exceeds the "
                             f"server's max_src_len {self.max_src_len}")
        padded = np.zeros((1, self.max_src_len), np.int32)
        padded[0, :src.size] = src
        profiler.record_dispatch("serve_prefill")
        old = (self.mem_k, self.mem_v, self.mem_vl)
        try:
            with _tracer.span("serve.prefill", cat="serve",
                              args={"slot": int(slot),
                                    "src_len": int(src_len)}):
                self.mem_k, self.mem_v, self.mem_vl = self._prefill_fn(
                    self.mem_k, self.mem_v, self.mem_vl,
                    jnp.asarray(padded), jnp.asarray([src_len], jnp.int32),
                    jnp.int32(slot))
        except Exception as e:
            # donation hazard (same rule as cachedop): a failure that
            # consumed the donated memory buffers loses EVERY slot's
            # encoder state, not just this request's — rebuild zeroed
            # buffers and tell the scheduler to restart the in-flight
            # requests. A failure that left the buffers alive (trace/
            # compile-stage, CPU no-op donation) stays per-request.
            if any(getattr(a, "is_deleted", lambda: False)()
                   for a in old):
                self.reset_mem()
                raise MemoryStateLost(
                    f"prefill failed after consuming donated memory "
                    f"buffers: {type(e).__name__}: {e}") from e
            raise

    def decode(self, page_tables, lens, tok, active):
        """One decode step for every slot (ONE dispatch): writes each
        active slot's K/V into its current page in place, runs the shared
        ragged-paged-attention launch, returns (next_tok (S,) host int32,
        logits (S, V) device array)."""
        profiler.record_dispatch("serve_decode")
        args = (jnp.asarray(page_tables, jnp.int32),
                jnp.asarray(lens, jnp.int32), jnp.asarray(tok, jnp.int32),
                jnp.asarray(active, jnp.int32),
                self.mem_k, self.mem_v, self.mem_vl)
        if self.kv_quant:
            (self.k_pages, self.v_pages, self.k_scales, self.v_scales,
             next_tok, logits) = self._decode_fn(
                self.k_pages, self.v_pages, self.k_scales, self.v_scales,
                *args)
        else:
            self.k_pages, self.v_pages, next_tok, logits = \
                self._decode_fn(self.k_pages, self.v_pages, *args)
        return np.asarray(next_tok), logits

    def decode_multi(self, page_tables, lens, toks, qlens, active):
        """One WIDENED decode turn for every slot (still ONE dispatch):
        writes each active slot's window K/V into its pages in place,
        runs the shared ragged-paged-attention launch with per-slot
        ragged query lengths, returns (next_tok (S, W) host int32,
        logits (S, W, V) device array). Greedy commits derived from
        these outputs are identical to `decode` run token-by-token —
        the bitwise-greedy contract tests/test_serve.py pins."""
        if self._verify_fn is None:
            raise MXNetError("decode_multi needs width > 1 (construct "
                             "DecodeRuntime(width=k+1))")
        profiler.record_dispatch("serve_decode")
        args = (jnp.asarray(page_tables, jnp.int32),
                jnp.asarray(lens, jnp.int32), jnp.asarray(toks, jnp.int32),
                jnp.asarray(qlens, jnp.int32),
                jnp.asarray(active, jnp.int32),
                self.mem_k, self.mem_v, self.mem_vl)
        if self.kv_quant:
            (self.k_pages, self.v_pages, self.k_scales, self.v_scales,
             next_tok, logits) = self._verify_fn(
                self.k_pages, self.v_pages, self.k_scales, self.v_scales,
                *args)
        else:
            self.k_pages, self.v_pages, next_tok, logits = \
                self._verify_fn(self.k_pages, self.v_pages, *args)
        return np.asarray(next_tok), logits

    def remap_pages(self, mapping):
        """Apply a `PagePool.defrag()` renumbering to the device pools
        (and, int8 mode, the parallel scale arrays — scales travel with
        their page ids): one gather-permutation dispatch (donated,
        in-place)."""
        if not mapping:
            return
        perm = np.arange(self.num_pages)
        for old, new in mapping.items():
            perm[new] = old
        profiler.record_dispatch("serve_page_remap")
        if self.kv_quant:
            (self.k_pages, self.v_pages, self.k_scales,
             self.v_scales) = self._remap_fn(
                self.k_pages, self.v_pages, self.k_scales, self.v_scales,
                jnp.asarray(perm))
        else:
            self.k_pages, self.v_pages = self._remap_fn(
                self.k_pages, self.v_pages, jnp.asarray(perm))

    def reset_pages(self):
        """Drop ALL cached KV state, scales included (construction, and
        the scheduler's catastrophic failure path after an executable
        error, when page contents can no longer be trusted)."""
        shape = (self._n_layers, self.num_pages, self.page_size, self._h,
                 self._dh)
        if self.kv_quant:
            self.k_pages = jnp.zeros(shape, jnp.int8)
            self.v_pages = jnp.zeros(shape, jnp.int8)
            sshape = (self._n_layers, self.num_pages, self._h)
            self.k_scales = jnp.zeros(sshape, jnp.float32)
            self.v_scales = jnp.zeros(sshape, jnp.float32)
            _obs_registry().gauge("kv_page_scale_bytes").set(
                2 * self.k_scales.size * 4)
        else:
            self.k_pages = jnp.zeros(shape, self._dtype)
            self.v_pages = jnp.zeros(shape, self._dtype)
            self.k_scales = self.v_scales = None

    def kv_bytes_per_page(self):
        """Device bytes one page costs in THIS runtime's layout (K + V
        across layers; int8 mode includes the per-page scale rows)."""
        from .quant import kv_page_bytes
        return kv_page_bytes(
            self._n_layers, self.page_size, self._h, self._dh,
            "int8" if self.kv_quant else str(self._dtype))

    def reset_mem(self):
        """Rebuild zeroed per-slot encoder memory (after a prefill
        failure consumed the donated buffers)."""
        shape = (self._n_layers, self.slots, self._h, self.max_src_len,
                 self._dh)
        self.mem_k = jnp.zeros(shape, self._dtype)
        self.mem_v = jnp.zeros(shape, self._dtype)
        self.mem_vl = jnp.zeros((self.slots,), jnp.int32)
