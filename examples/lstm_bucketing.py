"""Classic bucketed LSTM language model on the legacy symbolic cell API
(reference: example/rnn/bucketing/lstm_bucketing.py): a shared
mx.rnn.LSTMCell stack, a per-bucket ``sym_gen`` that unrolls it, and
BucketingModule training over mx.rnn.BucketSentenceIter.

Synthetic corpus (offline env): sentences follow w_{t+1} = (w_t + 1) % V,
so a trained model predicts the next token near-perfectly, and held-out
accuracy is the check.

Usage: python examples/lstm_bucketing.py [--epochs N] [--smoke]

TPU notes: each bucket length is ONE compiled XLA executable — the
unrolled cell chain is static-shape by construction, which is exactly
why bucketing (not padding-to-max or dynamic shapes) is the idiomatic
variable-length strategy here (SURVEY §3).
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.module import BucketingModule


def synthetic_sentences(n, vocab, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ln = rs.choice([4, 6, 8, 10])
        start = rs.randint(0, vocab)
        out.append([(start + t) % vocab for t in range(ln)])
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-hidden", type=int, default=48)
    ap.add_argument("--num-embed", type=int, default=24)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.epochs, args.num_hidden, args.num_embed = 4, 24, 12
        args.vocab = 16

    buckets = [4, 6, 8, 10]
    train_iter = mx.rnn.BucketSentenceIter(
        synthetic_sentences(600, args.vocab, seed=0), batch_size=16,
        buckets=buckets)
    val_iter = mx.rnn.BucketSentenceIter(
        synthetic_sentences(200, args.vocab, seed=1), batch_size=16,
        buckets=buckets)

    # the cell stack is built ONCE; every bucket's sym_gen re-unrolls the
    # same cells, so all buckets share one weight set (the whole point of
    # the bucketing pattern)
    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix=f"lstm_l{i}_"))

    def sym_gen(seq_len):
        with mx.name.NameManager():
            data = sym.Variable("data")
            label = sym.Variable("softmax_label")
            embed = sym.Embedding(data, input_dim=args.vocab,
                                  output_dim=args.num_embed, name="embed")
            stack.reset()
            outputs, _ = stack.unroll(seq_len, inputs=embed,
                                      merge_outputs=True)
            pred = sym.reshape(outputs, (-1, args.num_hidden))
            pred = sym.FullyConnected(pred, num_hidden=args.vocab,
                                      name="pred")
            out = sym.SoftmaxOutput(pred, sym.reshape(label, (-1,)),
                                    use_ignore=True, ignore_label=-1,
                                    name="softmax")
        return out, ["data"], ["softmax_label"]

    mod = BucketingModule(sym_gen, default_bucket_key=max(buckets))
    mod.fit(train_iter, eval_data=val_iter, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": 0.02},
            eval_metric=mx.metric.Perplexity(ignore_label=-1),
            batch_end_callback=mx.callback.Speedometer(16, 20),
            eval_end_callback=mx.callback.LogValidationMetricsCallback())

    acc = mx.metric.create("acc")
    val_iter.reset()
    for batch in val_iter:
        mod.forward(batch, is_train=False)
        mod.update_metric(acc, [nd.array(
            batch.label[0].asnumpy().reshape(-1))])
    print(f"held-out next-token accuracy: {acc.get()[1]:.3f}")
    floor = 0.4 if args.smoke else 0.6
    assert acc.get()[1] > floor, acc.get()
    print("lstm_bucketing: OK")


if __name__ == "__main__":
    main()
