"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download", "HookHandle"]


class HookHandle:
    """A removable reference to a registered hook (reference:
    mxnet.gluon.utils.HookHandle). `Block.register_forward_hook` /
    `register_forward_pre_hook` return one; `detach()` (or exiting the
    handle used as a context manager) unregisters the hook. Idempotent —
    a second detach is a no-op."""

    def __init__(self):
        self._hooks = None
        self._hook = None

    def attach(self, hooks_list, hook):
        if self._hooks is not None:
            raise MXNetError("HookHandle is already attached")
        self._hooks = hooks_list
        self._hook = hook
        hooks_list.append(hook)

    def detach(self):
        if self._hooks is not None and self._hook in self._hooks:
            self._hooks.remove(self._hook)
        self._hooks = None
        self._hook = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.detach()
        return False


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice chunks."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split axis of size {size} into {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data and load each slice onto a context.

    On TPU the idiomatic path is sharding one array over the mesh, but the
    reference API contract (list of per-ctx slices) is preserved for scripts."""
    if not isinstance(data, NDArray):
        from ..ndarray.ndarray import array
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so their joint L2 norm <= max_norm (in place)."""
    if not arrays:
        return 0.0
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a._data.astype(jnp.float32)))
                         for a in arrays))
    total_f = float(total)
    if check_isfinite and not jnp.isfinite(total).item():
        import warnings
        warnings.warn("nan or inf found in gradient norm")
    scale = max_norm / max(total_f, max_norm)
    if scale < 1.0:
        for a in arrays:
            a._rebind(a._data * scale)
    return total_f


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Offline 'download': file:// URLs and existing local paths copy to
    `path` (with sha1 verification when given); network URLs raise — this
    environment has no egress, datasets are generated locally."""
    import os
    import shutil
    src = url[len("file://"):] if url.startswith("file://") else url
    if os.path.exists(src):
        if path is None:
            fname = os.path.basename(src)
        elif os.path.isdir(path):
            fname = os.path.join(path, os.path.basename(src))
        else:
            fname = path
        if overwrite or not os.path.exists(fname) or \
                (sha1_hash and not check_sha1(fname, sha1_hash)):
            if os.path.abspath(src) != os.path.abspath(fname):
                os.makedirs(os.path.dirname(os.path.abspath(fname)),
                            exist_ok=True)
                shutil.copyfile(src, fname)
        if sha1_hash and not check_sha1(fname, sha1_hash):
            raise MXNetError(f"sha1 mismatch for {fname}")
        return fname
    raise MXNetError("network access is disabled in this environment; "
                     "datasets are generated locally (gluon.data.vision), "
                     "and download() accepts file:// or local paths")
