"""Every example script must run end-to-end in --smoke mode (subprocess,
CPU backend) — the user-facing flows stay alive."""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(_REPO, "examples"))
    if f.endswith(".py") and not f.startswith("_"))


@pytest.mark.parametrize("script", _EXAMPLES)
def test_example_smoke(script, tmp_path):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    path = os.path.join(_REPO, "examples", script)
    # force the CPU backend via jax.config BEFORE the script runs: env vars
    # alone don't stop the axon sitecustomize from grabbing the TPU
    runner = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import runpy, sys\n"
        f"sys.argv = [{path!r}, '--smoke']\n"
        f"runpy.run_path({path!r}, run_name='__main__')\n")
    out = subprocess.run(
        [sys.executable, "-c", runner],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path),
        env=env)
    assert out.returncode == 0, f"{script}:\n{out.stdout}\n{out.stderr}"
