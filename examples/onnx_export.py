"""Train a small CNN, export it to ONNX, and validate the artifact.

Usage: python examples/onnx_export.py [--smoke]

The exporter is self-contained (hand-rolled protobuf wire format in
mxnet_tpu/contrib/onnx/proto.py) — no `onnx` package needed. The script
round-trips the written file through the wire-format decoder and checks
the graph is structurally sound (reference workflow:
python/mxnet/contrib/onnx/mx2onnx export_model + onnx.checker).
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, sym, autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.contrib.onnx import export_model, proto

    # 1. a small CNN, trained a few steps so the exported weights are real
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(10))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    for _ in range(3):
        x = nd.array(rs.randn(8, 1, 16, 16).astype(np.float32))
        y = nd.array(rs.randint(0, 10, 8).astype(np.float32))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)

    # 2. symbolic trace -> ONNX file
    graph = net(sym.Variable("data"))
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = os.path.join(tempfile.gettempdir(), "cnn.onnx")
    export_model(graph, params, {"data": (1, 1, 16, 16)},
                 onnx_file_path=path)
    size = os.path.getsize(path)

    # 3. validate the artifact by decoding the wire format back
    model = proto.decode_model(open(path, "rb").read())
    g = model["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert "Conv" in ops and "Gemm" in ops, ops
    assert set(g["initializers"]) == {k for k in params}
    print(f"wrote {path} ({size} bytes), opset {model['opset']}")
    print("ops:", " -> ".join(ops))

    # 4. and back again: onnx2mx import reproduces the trained net
    from mxnet_tpu.contrib.onnx import import_model, import_to_gluon
    xv = nd.array(rs.randn(2, 1, 16, 16).astype(np.float32))
    ref = net(xv).asnumpy()
    sym2, arg_p, aux_p = import_model(path)
    ex = sym2.bind(None, {"data": xv, **arg_p}, aux_states=aux_p)
    got = ex.forward()[0].asnumpy()
    assert np.allclose(got, ref, atol=1e-5), "import diverges from source"
    block = import_to_gluon(path)
    assert np.allclose(block(xv).asnumpy(), ref, atol=1e-5)
    print("import round-trip: logits identical")

    # 5. transformers export too: a BERT-mini encoder with a RAGGED
    # valid_length batch — the attention mask ships as dynamic graph ops
    # (Shape -> Range -> Less -> Where), no baked-in mask constant
    from mxnet_tpu.models.bert import BERTModel
    bert = BERTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
                     num_heads=4, max_length=16, dropout=0.0)
    bert.initialize()
    B, S = 2, 12
    tok = nd.array(rs.randint(0, 50, (B, S)).astype(np.float32))
    seg = nd.array(np.zeros((B, S), np.float32))
    vlen = nd.array(np.array([12, 7], np.float32))
    _, ref_pool = bert(tok, seg, vlen)
    gb = sym.Group(list(bert(sym.Variable("token_ids", shape=(B, S)),
                             sym.Variable("segment_ids", shape=(B, S)),
                             sym.Variable("valid_length", shape=(B,)))))
    bparams = {k: v.data() for k, v in bert.collect_params().items()}
    bpath = os.path.join(tempfile.gettempdir(), "bert.onnx")
    export_model(gb, bparams,
                 {"token_ids": (B, S), "segment_ids": (B, S),
                  "valid_length": (B,)}, onnx_file_path=bpath)
    s3, arg3, aux3 = import_model(bpath)
    feed = dict(arg3)
    feed.update(token_ids=tok, segment_ids=seg, valid_length=vlen)
    outs = s3.bind(None, feed, aux_states=aux3).forward()
    assert np.allclose(outs[1].asnumpy(), ref_pool.asnumpy(), atol=1e-4)
    bops = [n["op_type"]
            for n in proto.decode_model(open(bpath, "rb").read())
            ["graph"]["nodes"]]
    assert "Range" in bops and "Where" in bops
    print(f"BERT encoder export+import round-trip ok "
          f"({len(bops)} nodes, dynamic attention mask)")
    print("OK")


if __name__ == "__main__":
    main()
