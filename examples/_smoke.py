"""Shared --smoke guard for the example scripts: force the CPU backend
BEFORE jax initialises so smoke runs never grab the (single, possibly
flaky) TPU tunnel. Import this FIRST in every example."""
import sys

if "--smoke" in sys.argv:
    import jax
    jax.config.update("jax_platforms", "cpu")
