"""Pallas kernel tests (SURVEY.md §2 #42). On the CPU test mesh the kernels
fall back to the XLA reference path — these tests pin the numerics and the
custom-vjp wiring; the Pallas fast path is exercised on real TPU by bench.py."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                          attention_reference,
                                          fused_layer_norm, on_tpu)
from mxnet_tpu.ops.nn_ops import layer_norm


def _qkv(b=2, h=2, s=128, d=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, h, s, d), dtype) for k in ks)


def test_attention_reference_is_softmax_attention():
    q, k, v = _qkv(s=8)
    out = attention_reference(q, k, v)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5,
                               atol=1e-6)


def test_flash_matches_reference():
    q, k, v = _qkv()
    for causal in (False, True):
        got = flash_attention(q, k, v, causal)
        want = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


def test_flash_causality():
    """Future K/V must not influence causal outputs."""
    q, k, v = _qkv(s=16)
    out1 = flash_attention(q, k, v, True)
    k2 = k.at[:, :, 8:].set(999.0)
    v2 = v.at[:, :, 8:].set(-999.0)
    out2 = flash_attention(q, k2, v2, True)
    np.testing.assert_allclose(np.asarray(out1[:, :, :8]),
                               np.asarray(out2[:, :, :8]), rtol=1e-5)


def test_flash_grad_matches_reference_grad():
    q, k, v = _qkv(s=32)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, True) ** 2).sum()

    def f_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_fused_layer_norm_matches_unfused():
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
    g = jax.random.normal(jax.random.PRNGKey(1), (128,))
    b = jax.random.normal(jax.random.PRNGKey(2), (128,))
    got = fused_layer_norm(x, g, b)
    want = layer_norm(x, g, b, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4,
                               atol=1e-5)


def test_flash_bf16():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    want = attention_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                               v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(want), rtol=0.05, atol=0.05)


def test_flash_odd_length_fallback():
    """Non-128-multiple sequence takes the XLA path but stays correct."""
    q, k, v = _qkv(s=100)
    got = flash_attention(q, k, v, True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4,
                               atol=2e-5)


# ---------------------------------------------------------------------------
# interpret-mode tests: run the REAL Pallas kernel bodies on the CPU mesh
# (MXTPU_PALLAS_INTERPRET=1) so the fwd + bwd kernel numerics are pinned
# without a chip. Slow per-call, so shapes stay minimal (1 head, S=256).
# ---------------------------------------------------------------------------
@pytest.fixture
def _pallas_interpret(monkeypatch):
    monkeypatch.setenv("MXTPU_PALLAS_INTERPRET", "1")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_fwd_interpret(_pallas_interpret, causal):
    q, k, v = _qkv(b=1, h=1, s=256, d=64)
    got = flash_attention(q, k, v, causal)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_bwd_interpret(_pallas_interpret, causal):
    """dq/dk/dv Pallas kernels (in-kernel recompute from saved lse) must
    match the XLA attention gradient."""
    q, k, v = _qkv(b=1, h=1, s=256, d=64)
    w = jax.random.normal(jax.random.PRNGKey(7), q.shape)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, causal) * w).sum()

    def f_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) * w).sum()

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4)


def test_flash_kv_lengths_matches_masked_reference():
    """kv_lengths fallback path == boolean-masked reference (CPU path)."""
    q, k, v = _qkv(s=128)
    vl = jnp.array([64, 128])
    got = flash_attention(q, k, v, kv_lengths=vl)
    pos = jnp.arange(128)[None, :]
    mask = (pos < vl[:, None])[:, None, None, :]
    want = attention_reference(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4,
                               atol=2e-5)


def test_attention_reference_additive_mask_convention():
    """Additive masks (0 = keep, -1e9 = drop) must mask the RIGHT positions
    (regression: the boolean interpretation inverted them)."""
    q, k, v = _qkv(s=8)
    vl = jnp.array([4, 8])
    pos = jnp.arange(8)[None, :]
    keep = pos < vl[:, None]
    additive = jnp.where(keep, 0.0, -1e9)[:, None, None, :]
    boolean = keep[:, None, None, :]
    got = attention_reference(q, k, v, mask=additive)
    want = attention_reference(q, k, v, mask=boolean)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-6)
    # and masked != unmasked (the mask actually does something)
    unmasked = attention_reference(q, k, v)
    assert not np.allclose(np.asarray(got), np.asarray(unmasked))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_lengths_interpret(_pallas_interpret, causal):
    """The scalar-prefetch masked kernel (fwd + bwd) == masked XLA attention,
    including combined with causal."""
    q, k, v = _qkv(b=2, h=1, s=256, d=64)
    vl = jnp.array([100, 256])
    w = jax.random.normal(jax.random.PRNGKey(7), q.shape)
    pos = jnp.arange(256)[None, :]
    mask = (pos < vl[:, None])[:, None, None, :]

    got = flash_attention(q, k, v, causal, kv_lengths=vl)
    want = attention_reference(q, k, v, causal=causal, mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4,
                               atol=2e-5)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, causal, kv_lengths=vl) * w).sum()

    def f_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal, mask=mask)
                * w).sum()

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4)


def test_flash_kernel_rectangular_interpret(_pallas_interpret):
    """Cross-attention shape: Sq != Sk rides the kernel (fwd + bwd)."""
    ks = jax.random.split(jax.random.PRNGKey(1), 4)
    q = jax.random.normal(ks[0], (1, 1, 128, 64))
    k = jax.random.normal(ks[1], (1, 1, 256, 64))
    v = jax.random.normal(ks[2], (1, 1, 256, 64))
    w = jax.random.normal(ks[3], q.shape)
    got = flash_attention(q, k, v)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4,
                               atol=2e-5)
    g1 = jax.grad(lambda *a: (flash_attention(*a) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (attention_reference(*a) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4)


def test_fused_ln_kernel_interpret(_pallas_interpret):
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
    g = jax.random.normal(jax.random.PRNGKey(1), (128,))
    b = jax.random.normal(jax.random.PRNGKey(2), (128,))
    got = fused_layer_norm(x, g, b)
    want = layer_norm(x, g, b, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4,
                               atol=1e-5)


def test_conv1x1_bn_stats_numerics(_pallas_interpret):
    """The experimental matmul+BN-stats epilogue KERNEL (interpret mode,
    not the XLA fallback) matches the two-pass reference exactly in fp32
    stats, including an M that doesn't divide the block (zero-padding
    must not leak into stats)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import conv1x1_bn_stats
    key = jax.random.PRNGKey(3)
    m, k, n = 300, 64, 128        # m % bm != 0 on purpose
    x = jax.random.normal(key, (m, k), jnp.float32)
    w = jax.random.normal(key, (k, n), jnp.float32) * 0.1
    y, mean, meansq = conv1x1_bn_stats(x, w, bm=256)
    ref = x @ w
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(ref.mean(0)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(meansq),
                               np.asarray((ref * ref).mean(0)),
                               rtol=1e-5, atol=1e-6)
