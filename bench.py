"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

One jitted train step (forward + backward + SGD-momentum update, donated
buffers), bf16 NHWC — the MXU-native layout. `vs_baseline` divides by the
reference class number from SURVEY.md §6: MXNet+cuDNN on A100 ~= 2500
images/sec/chip fp16 ResNet-50.

Prints exactly ONE JSON line on stdout.
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_S = 2500.0


def main():
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import extract_pure_fn
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    on_tpu = jax.default_backend() == "tpu"
    smoke = "--smoke" in sys.argv
    if smoke or not on_tpu:
        batch, steps = 8, 3
    else:
        batch, steps = 128, 30
    batch = int(os.environ.get("BENCH_BATCH", batch))
    steps = int(os.environ.get("BENCH_STEPS", steps))
    print(f"[bench] backend={jax.default_backend()} batch={batch} "
          f"steps={steps}", file=sys.stderr)

    net = resnet50_v1(layout="NHWC", stem_s2d=True)
    net.initialize()
    net.cast("bfloat16")
    x = mx.nd.random.uniform(shape=(batch, 224, 224, 3), dtype="bfloat16")
    net(x)  # materialise deferred-shape params
    fwd, params = extract_pure_fn(net, x, training=True)

    key = jax.random.PRNGKey(0)
    labels = jax.random.randint(key, (batch,), 0, 1000)
    images = x._data

    aux_idx = list(fwd.aux_indices)

    def loss_fn(p, xb, yb):
        logits, aux = fwd(p, xb)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1)), aux

    lr, mu = 0.1, 0.9

    def train_step(p, mom, xb, yb):
        (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(p, xb, yb)
        new_mom = [mu * m + gg.astype(m.dtype) for m, gg in zip(mom, g)]
        new_p = [pp - lr * m for pp, m in zip(p, new_mom)]
        for i, v in zip(aux_idx, aux):  # BN running stats carry through
            new_p[i] = v
        return new_p, new_mom, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    mom = [jnp.zeros_like(p) for p in params]

    # warmup: compile + one extra to stabilise. NB sync via host fetch:
    # under the axon tunnel block_until_ready does not actually block.
    params, mom, loss = step(params, mom, images, labels)
    params, mom, loss = step(params, mom, images, labels)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, mom, loss = step(params, mom, images, labels)
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    print(f"[bench] loss={final_loss:.4f} dt={dt:.3f}s", file=sys.stderr)
    result = {
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }

    # Second headline metric (BASELINE.json): BERT-base MLM tokens/sec/chip.
    # Merged into the same single JSON line so the driver's one-line parse
    # still works; a BERT failure must not take down the ResNet metric.
    if not smoke and os.environ.get("BENCH_SKIP_BERT") != "1":
        try:
            import bench_bert
            result["extra_metrics"] = [bench_bert.measure()]
        except Exception as e:  # pragma: no cover
            print(f"[bench] bert bench failed: {e!r}", file=sys.stderr)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
