"""mx.npx — numpy-extension namespace (reference:
python/mxnet/numpy_extension/ + ndarray/numpy_extension/).

Carries the operators numpy itself doesn't have (the nn set) plus the
np-mode switches. Everything delegates to the existing TPU kernels in
`ops/` — np-ness of the output follows the input through `_apply`, so
these wrappers add no second dispatch path.

np-mode semantics here: this rebuild's NDArray is numpy-shaped from birth
(0-d and 0-size arrays always work — jax.Array underneath), so
`np_shape`/`np_array` scopes don't change behaviour; `set_np` flips the
flag that `is_np_array()` reports (Gluon users branch on it, and
Parameter/DataLoader outputs convert with `.as_np_ndarray()`).
"""
from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply
from ..ops import nn_ops as _nn
from ..ops import tensor_ops as _t

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape", "np_shape",
           "np_array", "use_np", "softmax", "log_softmax", "masked_softmax",
           "relu", "sigmoid", "gelu", "one_hot", "pick", "topk", "batch_dot",
           "reshape_like", "gather_nd", "scatter_nd", "slice", "reshape",
           "batch_flatten", "fully_connected", "convolution",
           "pooling", "batch_norm", "layer_norm", "dropout", "embedding",
           "activation", "leaky_relu", "arange_like", "gamma", "sequence_mask",
           "waitall", "save", "load", "seed", "rnn", "slice_like", "smooth_l1", "multibox_prior", "multibox_target", "multibox_detection", "roi_align"]

class _Flags:
    """Process-global np-mode state (reference parity: one C++ global;
    worker threads must see the main thread's set_np)."""
    np_array = False
    np_shape = False


_state = _Flags()


def _flags():
    return _state


def set_np(shape=True, array=True):
    f = _flags()
    f.np_shape, f.np_array = bool(shape), bool(array)


def reset_np():
    set_np(False, False)


def is_np_array():
    return _flags().np_array


def is_np_shape():
    return _flags().np_shape


@contextmanager
def np_shape(active=True):
    f = _flags()
    prev = f.np_shape
    f.np_shape = bool(active)
    try:
        yield
    finally:
        f.np_shape = prev


@contextmanager
def np_array(active=True):
    f = _flags()
    prev = f.np_array
    f.np_array = bool(active)
    try:
        yield
    finally:
        f.np_array = prev


def use_np(func):
    """Decorator: run `func` with np semantics active (reference:
    npx.use_np; works on functions and Gluon forward methods)."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True), np_array(True):
            return func(*args, **kwargs)
    return wrapper


# ------------------------------------------------------------------- nn ops
def _npc(x):
    from ..numpy import _c
    return _c(x)


softmax = _nn.softmax_nd
log_softmax = _nn.log_softmax_nd
relu = _nn.relu
sigmoid = _nn.sigmoid
pick = _t.pick
one_hot = _t.one_hot
topk = _t.topk
reshape_like = _t.reshape_like
gather_nd = _t.gather_nd
scatter_nd = _t.scatter_nd
slice = _t.slice           # noqa: A001  (reference npx name)
reshape = _t.reshape


def gelu(data, approximation="erf"):
    return _apply(lambda x: jax.nn.gelu(x, approximate=(
        approximation == "tanh")), [_npc(data)])


def masked_softmax(data, mask=None, axis=-1, temperature=1.0):
    if mask is None:
        return softmax(data, axis=axis, temperature=temperature)
    return _apply(
        lambda x, m: jax.nn.softmax(
            jnp.where(m.astype(bool), x / temperature, -1e30), axis=axis),
        [_npc(data), _npc(mask)])


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    def fn(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return _apply(fn, [_npc(lhs), _npc(rhs)])


def batch_flatten(data):
    return _apply(lambda x: x.reshape(x.shape[0], -1), [_npc(data)])


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    # num_hidden is declarative in the reference symbol API; the weight
    # shape already carries it here
    if no_bias or bias is None:
        return _apply(lambda a, w: _nn.fully_connected(
            a, w, None, flatten=flatten), [_npc(x), _npc(weight)])
    return _apply(lambda a, w, b: _nn.fully_connected(
        a, w, b, flatten=flatten), [_npc(x), _npc(weight), _npc(bias)])


def convolution(data, weight, bias=None, **kwargs):
    kwargs.pop("num_filter", None)  # declarative in the reference API
    kwargs.pop("kernel", None)
    if bias is None:
        return _apply(lambda a, w: _nn.convolution(a, w, None, **kwargs),
                      [_npc(data), _npc(weight)])
    return _apply(lambda a, w, b: _nn.convolution(a, w, b, **kwargs),
                  [_npc(data), _npc(weight), _npc(bias)])


def pooling(data, kernel, **kwargs):
    return _apply(lambda a: _nn.pooling(a, kernel, **kwargs), [_npc(data)])


def slice_like(data, shape_like, axes=None):
    from ..ops.tensor_ops import slice_like as _sl
    return _sl(_npc(data), _npc(shape_like), axes=axes)


def smooth_l1(data, scalar=1.0):
    from ..ops.seq_ops import smooth_l1 as _sm
    return _sm(_npc(data), scalar=scalar)


def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), **kw):
    from ..ndarray import contrib as _ndc
    return _ndc.MultiBoxPrior(_npc(data), sizes=sizes, ratios=ratios,
                              **kw)


def multibox_target(anchor, label, cls_pred, **kw):
    from ..ndarray import contrib as _ndc
    return _ndc.MultiBoxTarget(_npc(anchor), _npc(label),
                               _npc(cls_pred), **kw)


def multibox_detection(cls_prob, loc_pred, anchor, **kw):
    from ..ndarray import contrib as _ndc
    return _ndc.MultiBoxDetection(_npc(cls_prob), _npc(loc_pred),
                                  _npc(anchor), **kw)


def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, **kw):
    from ..ndarray import contrib as _ndc
    return _ndc.ROIAlign(_npc(data), _npc(rois),
                         pooled_size=pooled_size,
                         spatial_scale=spatial_scale,
                         sample_ratio=sample_ratio, **kw)


def rnn(data, *state_and_params, **kwargs):
    """Fused multi-layer RNN (reference: npx.rnn over rnn-inl.h) — the
    same kernel as nd.RNN / sym.RNN, np-array in/out."""
    from ..ops.compat_ops import RNN as _rnn
    return _rnn(_npc(data), *[_npc(a) for a in state_and_params],
                **kwargs)


def batch_norm(data, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, training=False, axis=1):
    """Returns y; running stats are updated in place when training (the
    reference mutates its aux inputs the same way)."""
    rm, rv = _npc(running_mean), _npc(running_var)
    y, new_m, new_v = _apply(
        lambda a, g, b, m, v: _nn.batch_norm(
            a, g, b, m, v, eps=eps, momentum=momentum, training=training,
            axis=axis),
        [_npc(data), _npc(gamma), _npc(beta), rm, rv], n_out=3)
    if training:
        running_mean._assign_value(new_m._data)
        running_var._assign_value(new_v._data)
    return y


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _apply(lambda a, g, b: _nn.layer_norm(a, g, b, axis=axis,
                                                 eps=eps),
                  [_npc(data), _npc(gamma), _npc(beta)])


def dropout(data, p=0.5, training=True, **kwargs):
    from .. import random as _r
    key = _r._next_key()
    return _apply(lambda a: _nn.dropout(a, key, p=p, training=training),
                  [_npc(data)])


def embedding(data, weight, input_dim=None, output_dim=None, **kwargs):
    return _apply(lambda i, w: _nn.embedding(i, w),
                  [_npc(data), _npc(weight)])


def activation(data, act_type="relu"):
    return _apply(lambda a: _nn.activation(a, act_type=act_type),
                  [_npc(data)])


def leaky_relu(data, act_type="leaky", slope=0.25, **kwargs):
    return _apply(lambda a: _nn.leaky_relu(a, act_type=act_type,
                                           slope=slope, **kwargs),
                  [_npc(data)])


def arange_like(data, start=0.0, step=1.0, axis=None):
    from ..ndarray.contrib import arange_like as _al
    return _al(_npc(data), start=start, step=step, axis=axis)


def gamma(data):
    return _t.gamma(_npc(data))


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    from ..ops.seq_ops import SequenceMask as _sm
    if sequence_length is None:
        return _sm(_npc(data), use_sequence_length=False, value=value,
                   axis=axis)
    return _sm(_npc(data), _npc(sequence_length),
               use_sequence_length=use_sequence_length, value=value,
               axis=axis)


# ------------------------------------------------------------------- utils
def waitall():
    from ..ndarray.ndarray import waitall as _w
    _w()


def seed(seed_state):
    from .. import random as _r
    _r.seed(seed_state)


def save(file, arr):
    """Save np arrays (dict or list) — npz container like nd.save."""
    from ..ndarray.utils import save as _save
    _save(file, arr)


def load(file):
    from ..ndarray.utils import load as _load
    out = _load(file)
    if isinstance(out, dict):
        return {k: v.as_np_ndarray() for k, v in out.items()}
    return [v.as_np_ndarray() for v in out]
