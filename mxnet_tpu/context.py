"""Device contexts: mx.cpu() / mx.tpu() / mx.gpu() over JAX devices.

Reference parity: python/mxnet/context.py. The reference maps Context to a
C++ {dev_type, dev_id} consumed by the storage manager and engine; here a
Context resolves to a `jax.Device`, and placement happens through
`jax.device_put` / `jax.default_device`. `mx.gpu()` is accepted as an alias
for the accelerator so reference scripts run unmodified on TPU.
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus",
           "num_tpus", "memory_info", "gpu_memory_info"]

_context_stack = threading.local()


def _accelerator_devices():
    """This process's non-CPU JAX devices, or [] if running CPU-only.

    local_devices, not devices: under multi-process training (tools/
    launch.py, multi-host pods) the global list contains other workers'
    chips, which are not addressable from here — a Context must always
    resolve to a device this process can place data on."""
    return [d for d in jax.local_devices() if d.platform != "cpu"]


class Context:
    """A device context. device_type in {'cpu', 'tpu', 'gpu'}.

    'gpu' is an alias for the accelerator platform (TPU here) so that
    reference MXNet scripts using mx.gpu(i) map onto TPU chips.
    """

    devtype2str = {1: "cpu", 2: "tpu", 3: "gpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devstr2type:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- resolution -------------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device (raises if unavailable)."""
        if self.device_type == "cpu":
            cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
            if not cpus:
                try:
                    cpus = jax.devices("cpu")
                except RuntimeError:
                    cpus = []
            if self.device_id < len(cpus):
                return cpus[self.device_id]
            raise MXNetError(f"cpu({self.device_id}) not available")
        accels = _accelerator_devices()
        if not accels:  # CPU-only process (tests): alias accelerator -> cpu
            local = jax.local_devices()
            return local[min(self.device_id, len(local) - 1)]
        if self.device_id >= len(accels):
            raise MXNetError(
                f"{self.device_type}({self.device_id}) not available: "
                f"{len(accels)} accelerator device(s) visible")
        return accels[self.device_id]

    # -- protocol ---------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    def __enter__(self):
        if not hasattr(_context_stack, "stack"):
            _context_stack.stack = []
        _context_stack.stack.append(self)
        return self

    def __exit__(self, *exc):
        _context_stack.stack.pop()

    @classmethod
    def default_ctx(cls):
        override = getattr(cls, "_default_override", None)
        if override is not None:
            return override
        accels = _accelerator_devices()
        return cls("tpu", 0) if accels else cls("cpu", 0)


def cpu(device_id=0):
    """Return a CPU context."""
    return Context("cpu", device_id)


def tpu(device_id=0):
    """Return a TPU context — the first-class accelerator context
    (reference: mx.gpu(); BASELINE.json north star: `mx.tpu()`)."""
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Alias accepted for reference-script compatibility; maps to the
    accelerator platform (TPU)."""
    return Context("gpu", device_id)


def current_context():
    """The innermost `with mx.Context(...)` context, else the default
    (TPU if an accelerator is visible, CPU otherwise)."""
    stack = getattr(_context_stack, "stack", None)
    if stack:
        return stack[-1]
    return Context.default_ctx()


def num_gpus():
    """Number of accelerator devices (alias of num_tpus for parity)."""
    return len(_accelerator_devices())


def num_tpus():
    """Number of TPU chips visible to this process."""
    return len(_accelerator_devices())


def memory_info(ctx=None):
    """(free_bytes, total_bytes) of a context's device HBM (reference:
    context.gpu_memory_info; backed by utils/memory.py over PJRT)."""
    from .utils.memory import memory_info as _mi
    return _mi(ctx if ctx is not None else current_context())


def gpu_memory_info(device_id=0):
    """Reference-named alias: free/total for accelerator `device_id`."""
    return memory_info(Context("tpu", device_id))
