"""Host-side span tracer (reference: src/profiler/profiler.cc aggregate +
chrome tracing writer).

A low-overhead recorder for *host* time — where a training step's wall
clock goes between Python dispatch, the engine queue, collectives and the
jitted device work that `jax.profiler` already covers. Spans recorded here
export as standard Chrome-trace JSON (`{"traceEvents": [...]}`) loadable in
Perfetto / chrome://tracing, so a host trace opens side-by-side with (or
instead of) the XLA device trace.

Design constraints, in order:
  1. Disabled cost ~zero. Hot paths gate on the module-level `ACTIVE`
     bool before calling anything here; `span()` itself returns a shared
     no-op object when inactive.
  2. Enabled cost is two ring-buffer appends per span (`deque.append` is
     GIL-atomic — no lock on the record path) and one
     `time.perf_counter_ns()` call per edge. The buffer is bounded
     (`MXTPU_TRACE_BUFFER`, default 65536 events): a forgotten-running
     tracer degrades to "last N events", never to unbounded memory.
  3. Per-thread tracks: events carry the recording thread; export maps
     each thread to its own Chrome `tid` with a `thread_name` metadata
     event, so engine-worker spans land on their own rows.

Interleaving with jax.profiler: when a device trace is being captured
(`profiler.start()`), spans additionally enter a
`jax.profiler.TraceAnnotation` so the same names show up inside the XLA
trace timeline. That is opt-in per `set_jax_annotation` because the
annotation costs more than the span itself.

Clock: `time.perf_counter_ns()` — monotonic, ns resolution; exported `ts`
is microseconds relative to the tracer epoch (Chrome-trace convention).
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from time import perf_counter_ns

from .. import _env

__all__ = ["start", "stop", "clear", "enabled", "span", "instant",
           "counter", "complete", "to_chrome_trace", "dump",
           "set_jax_annotation", "events_recorded", "sample_op",
           "set_op_sample_rate"]

# Module-level fast-path flag. Hot call sites read `tracer.ACTIVE`
# directly (one module-attribute load) before touching any API below.
ACTIVE = False

def _env_int(name, default, minimum=1):
    """Env knob parse that can never break `import mxnet_tpu` (the
    shared strtol-parity parser; values below `minimum` degrade to the
    default with a one-time warning)."""
    return _env.env_int(name, default, minimum=minimum)


_DEFAULT_CAP = _env_int("MXTPU_TRACE_BUFFER", 65536)

# ring buffer of event tuples:
#   ("B", ts_ns, ident, name, cat, args)
#   ("E", ts_ns, ident)
#   ("X", ts_ns, ident, name, cat, args, dur_ns)
#   ("i", ts_ns, ident, name, cat, args)
#   ("C", ts_ns, ident, name, value)
_buf = deque(maxlen=_DEFAULT_CAP)
_thread_names = {}    # ident -> name, captured at record time (threads
                      # may exit before export)
_epoch_ns = 0
_jax_annotate = False
_lock = threading.Lock()   # guards start/stop/clear, not the record path

# imperative-op sampling (ndarray._apply): record every Nth op dispatch
_op_sample_rate = _env_int("MXTPU_TRACE_OP_SAMPLE", 16)
_op_counter = 0


class _NullSpan:
    """Shared no-op context manager returned when tracing is off."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_name", "_cat", "_args", "_ident", "_ann")

    def __init__(self, name, cat, args):
        self._name = name
        self._cat = cat
        self._args = args
        self._ann = None

    def __enter__(self):
        self._ident = threading.get_ident()
        if self._ident not in _thread_names:
            _thread_names[self._ident] = threading.current_thread().name
        if _jax_annotate:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self._name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        _buf.append(("B", perf_counter_ns(), self._ident, self._name,
                     self._cat, self._args))
        return self

    def __exit__(self, *exc):
        if ACTIVE:
            # after stop(): skip the append (export repair closes the
            # orphan B); keeps the post-stop mutation window tiny
            _buf.append(("E", perf_counter_ns(), self._ident))
        if self._ann is not None:
            self._ann.__exit__(*exc)
        return False


def start(buffer_size=None):
    """Begin recording. Clears the buffer and re-anchors the epoch."""
    global ACTIVE, _buf, _epoch_ns
    with _lock:
        cap = int(buffer_size) if buffer_size else _buf.maxlen
        _buf = deque(maxlen=cap)
        _epoch_ns = perf_counter_ns()
        ACTIVE = True


def stop():
    """Stop recording; the buffer is kept for export until the next
    start()/clear()."""
    global ACTIVE
    with _lock:
        ACTIVE = False


def pause():
    """Suspend recording without touching the buffer (profiler.pause)."""
    global ACTIVE
    ACTIVE = False


def resume():
    """Resume recording into the existing buffer (profiler.resume)."""
    global ACTIVE
    if _epoch_ns:        # never start()ed: nothing to resume into
        ACTIVE = True


def clear():
    with _lock:
        _buf.clear()


def enabled():
    return ACTIVE


def events_recorded():
    return len(_buf)


def set_jax_annotation(on):
    """Also wrap spans in jax.profiler.TraceAnnotation (device-trace
    interleave). Costs more per span; profiler.start() turns it on while a
    jax trace is being captured."""
    global _jax_annotate
    _jax_annotate = bool(on)


def set_op_sample_rate(n):
    """Record one in every `n` imperative op dispatches (ndarray._apply).
    n=1 traces every op; higher keeps always-on cost negligible."""
    global _op_sample_rate
    _op_sample_rate = max(1, int(n))
    return _op_sample_rate


def sample_op():
    """True when the current imperative op dispatch should be traced.
    Callers check `tracer.ACTIVE` first; the counter races benignly under
    threads (sampling, not accounting)."""
    global _op_counter
    _op_counter += 1
    return _op_counter % _op_sample_rate == 0


def span(name, cat="host", args=None):
    """Nestable span context manager. `with tracer.span("Trainer.step"):`.
    Returns a shared no-op when tracing is off."""
    if not ACTIVE:
        return _NULL
    return _Span(name, cat, args)


def _ident():
    ident = threading.get_ident()
    if ident not in _thread_names:
        _thread_names[ident] = threading.current_thread().name
    return ident


def instant(name, cat="host", args=None):
    """A point-in-time marker (Chrome 'i' event)."""
    if not ACTIVE:
        return
    _buf.append(("i", perf_counter_ns(), _ident(), name, cat, args))


def counter(name, value):
    """A Chrome counter-track sample ('C' event) — renders as a stacked
    area chart in Perfetto (e.g. engine queue depth over time)."""
    if not ACTIVE:
        return
    _buf.append(("C", perf_counter_ns(), _ident(), name, float(value)))


def complete(name, t0_ns, t1_ns, cat="host", args=None):
    """Record a span retroactively from measured edges ('X' complete
    event) — the sampled-op path times the dispatch first, then records
    only if the sample fired."""
    if not ACTIVE:
        return
    _buf.append(("X", t0_ns, _ident(), name, cat, args,
                 max(0, t1_ns - t0_ns)))


# ---------------------------------------------------------------- export
def _repair(events):
    """Balance B/E per thread: the ring buffer may have evicted a span's
    B while keeping its E (or recording stopped mid-span). Orphan E events
    are dropped; unclosed B events get a synthetic E at the last seen
    timestamp, so the exported trace is always well-formed."""
    out = []
    stacks = {}
    last_ts = {}
    for ev in events:
        ident = ev[2]
        last_ts[ident] = max(last_ts.get(ident, 0), ev[1])
        if ev[0] == "B":
            stacks.setdefault(ident, []).append(ev)
            out.append(ev)
        elif ev[0] == "E":
            if stacks.get(ident):
                stacks[ident].pop()
                out.append(ev)
            # else: orphan E (its B was evicted) — drop
        else:
            out.append(ev)
    for ident, stack in stacks.items():
        for _ in stack:
            out.append(("E", last_ts[ident], ident))
    return out


def to_chrome_trace():
    """Render the buffer as a Chrome-trace dict:
    {"traceEvents": [...], "displayTimeUnit": "ms"}. Events are sorted by
    timestamp; B/E balance is repaired (ring eviction, still-open spans);
    per-thread tracks get thread_name metadata."""
    with _lock:
        # the record path is deliberately lock-free, so a straggler span
        # exiting on a worker thread can append mid-snapshot; deque
        # iteration raises on concurrent mutation — retry, then fall back
        # to draining element-wise (popleft is atomic)
        for _ in range(3):
            try:
                events = list(_buf)
                break
            except RuntimeError:
                continue
        else:
            events = []
            while True:
                try:
                    events.append(_buf.popleft())
                except IndexError:
                    break
            _buf.extend(events)
    # a full ring means the oldest events were (probably) evicted — flag
    # it so a truncated capture is distinguishable from a complete one
    truncated = len(events) >= (_buf.maxlen or 1)
    events.sort(key=lambda ev: ev[1])
    events = _repair(events)
    # a stable ts sort again: synthetic E events appended by repair
    events.sort(key=lambda ev: ev[1])
    pid = os.getpid()
    epoch = _epoch_ns or (events[0][1] if events else 0)
    tids = {}
    names = {t.ident: (t.name or f"thread-{t.ident}")
             for t in threading.enumerate()}
    names.update(_thread_names)
    out = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0,
            "args": {"name": "mxnet_tpu host"
                     + (" [ring truncated]" if truncated else "")}}]

    def tid_of(ident):
        tid = tids.get(ident)
        if tid is None:
            tid = tids[ident] = len(tids)
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "ts": 0,
                        "args": {"name": names.get(ident,
                                                   f"thread-{ident}")}})
        return tid

    for ev in events:
        ph, ts_ns, ident = ev[0], ev[1], ev[2]
        e = {"ph": ph, "ts": (ts_ns - epoch) / 1e3, "pid": pid,
             "tid": tid_of(ident)}
        if ph == "E":
            e["name"] = ""      # Chrome allows nameless E; keep the key
        elif ph == "C":
            e["name"] = ev[3]
            e["args"] = {"value": ev[4]}
        else:
            e["name"] = ev[3]
            e["cat"] = ev[4]
            if ev[5]:
                e["args"] = dict(ev[5])
            if ph == "X":
                e["dur"] = ev[6] / 1e3
            if ph == "i":
                e["s"] = "t"    # instant scope: thread
        out.append(e)
    # metadata first, then by ts — keeps `ts` monotonic for validators
    out.sort(key=lambda e: (e["ph"] != "M", e["ts"]))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def dump(path):
    """Write the Chrome-trace JSON file; returns the path."""
    trace = to_chrome_trace()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
