"""Elastic fleet supervision: cross-host recovery over the kvstore
control plane.

PR 10's `TrainingSupervisor` is a single-controller state machine — it
can retry, roll back, shrink and (now) regrow ONE process. On a
multi-controller pod that is not enough: when a whole HOST dies
(SIGKILL, kernel panic, preemption without grace), its peers wedge in
the next collective and the `CollectiveTimeout` scope note used to end
with "process-level restart is out of scope". This module closes that
gap with a small, crash-only coordination layer over
`kvstore.control_plane()` (memory / shared-file / jax-coordination
backends, one duck-typed surface):

  * **Heartbeats** — every `FleetMember` stamps ``hb/<rank>`` with a
    monotonically-changing record (sequence number + wall time, the
    latter informational); a member whose stamp has not CHANGED for
    ``MXTPU_FLEET_DEADLINE_MS`` of the OBSERVER's own clock (or whose
    rank the ``host.lost`` fault point masked) is dead to the fleet.
    Liveness never compares a peer's wall-clock stamp against the local
    clock, so cross-host clock skew or an NTP step cannot declare a
    live peer dead — each observer ages a stamp from the moment it last
    saw the value change.
  * **Leader election** — no Paxos: the leader IS the lowest live rank.
    Deterministic, agreement-free, and re-election after a leader loss
    is just the next liveness read. Observed transitions count into
    ``fleet_elections``.
  * **Rollback agreement** — on a host loss the survivors converge on
    ONE fleet ``epoch`` for the incident (the bump is arbitrated by a
    put-if-absent claim keyed by the dead rank and its incarnation:
    the first detector assigns the epoch, every later detector adopts
    it), each proposes its newest locally-restorable step under
    ``rollback/<epoch>/<rank>``, and the leader publishes
    ``agreed/<epoch>`` = min over the proposals it collected before the
    deadline (a straggler that posts late simply finds the agreement
    already published). min() is the only safe pick: it is the newest
    step EVERY proposer can restore. As a backstop against the epoch
    counter still splitting (it is a plain KV key), both sides of the
    round re-poll the epoch and abandon a round the counter moved past
    — everyone re-proposes under the current max, so survivors cannot
    strand themselves waiting on ``agreed/<stale-epoch>``. Followers
    wait 2x the leader's collection window by default: a leader with a
    straggler only publishes AT its deadline, so an equal deadline
    would time prompt followers out moments before publication.

`FleetSupervisor` extends `TrainingSupervisor` with a per-step fleet
probe (beat, watch peers, fire the ``host.lost`` chaos point) and a
``host_lost`` recovery policy that runs the agreement, optionally
re-bootstraps the distributed runtime (`kvstore.reset_distributed` +
`init_distributed`, gated by ``MXTPU_FLEET_REBOOTSTRAP`` — collectives
cannot re-form around a re-spawned peer without it), and restores the
agreed step exactly. The process-level half lives in tools/launch.py
(``--max-restarts`` respawns a SIGKILL'd worker with
``MXTPU_RESTART_COUNT`` incremented); a respawned member finds the
current epoch's agreement on the control plane and resumes from it.

Observability: ``fleet_heartbeats``, ``fleet_heartbeat_failures``,
``fleet_elections``, ``fleet_rollback_agreements``, ``fleet_restarts``
(counted once by a member whose ``MXTPU_RESTART_COUNT`` says it is a
respawn), plus the supervisor's ``fault_recoveries{domain=host_lost}``.
Knobs and semantics: docs/RELIABILITY.md "Fleet recovery".
"""
from __future__ import annotations

import json
import os
import threading
import time

from ..base import MXNetError
from ..observability import registry as _obs_registry
from ..observability import tracer as _tracer
from .. import _env
from . import injection as _finj
from .injection import FaultInjected, HostLost
from .supervisor import TrainingSupervisor

__all__ = ["FleetMember", "FleetSupervisor", "run_fleet"]

_reg = _obs_registry()
_hb_counter = _reg.counter("fleet_heartbeats")
_hb_fail_counter = _reg.counter("fleet_heartbeat_failures")
_election_counter = _reg.counter("fleet_elections")
_agreement_counter = _reg.counter("fleet_rollback_agreements")
_restart_counter = _reg.counter("fleet_restarts")


def _log():
    from ..log import get_logger
    return get_logger("mxnet_tpu.fault")


class FleetMember:
    """One host's handle on the fleet control plane.

    rank/world: this worker's identity (the launcher's
    MXTPU_WORKER_ID / MXTPU_NUM_WORKERS); control: a
    `kvstore.ControlPlane` (default: `kvstore.control_plane()` — a
    shared MXTPU_FLEET_DIR selects the file backend);
    heartbeat_ms/deadline_ms: stamp cadence and staleness bound
    (defaults MXTPU_FLEET_HEARTBEAT_MS=500 /
    MXTPU_FLEET_DEADLINE_MS=2500 — the deadline should cover several
    missed beats so one slow filesystem write is not a death);
    clock/mono/sleep: injectable for deterministic tests. `clock` is
    WALL time (`time.time`) and only annotates the heartbeat payload;
    `mono` (`time.monotonic`) is what liveness ages stamps and
    agreement deadlines run on — strictly local, so cross-host clock
    skew cannot affect either."""

    def __init__(self, rank, world, control=None, *, heartbeat_ms=None,
                 deadline_ms=None, clock=time.time, mono=time.monotonic,
                 sleep=time.sleep):
        from .. import kvstore as _kv
        self.rank = int(rank)
        self.world = int(world)
        if not 0 <= self.rank < self.world:
            raise MXNetError(f"fleet rank {rank} outside world of "
                             f"{world}")
        self.control = control if control is not None \
            else _kv.control_plane()
        self.heartbeat_ms = float(heartbeat_ms) if heartbeat_ms is not None \
            else _env.env_ms("MXTPU_FLEET_HEARTBEAT_MS", 500.0)
        self.deadline_ms = float(deadline_ms) if deadline_ms is not None \
            else _env.env_ms("MXTPU_FLEET_DEADLINE_MS", 2500.0)
        self._clock = clock
        self._mono = mono
        self._sleep = sleep
        self._last_leader = None
        self._seen = set()            # ranks observed alive at least once
        self._beats = 0               # local sequence: every stamp differs
        self._hb_obs = {}             # rank -> (raw value, mono last seen)
        self._stop = threading.Event()
        self._thread = None
        self.incarnation = _env.env_int("MXTPU_RESTART_COUNT", 0,
                                        minimum=0)
        if self.incarnation:
            # this process IS a fleet restart (the launcher respawned a
            # SIGKILL'd worker): count it once, at the member that knows
            _restart_counter.inc()

    # ------------------------------------------------------ heartbeats
    def beat(self):
        """Stamp this rank's heartbeat key. The ``kv.heartbeat`` fault
        point (rank-keyed) simulates a lost/failed stamp: any firing —
        raise or stall — counts into ``fleet_heartbeat_failures`` and
        the stamp is skipped, so peers see this member age toward the
        deadline. Returns True when the stamp was written."""
        try:
            if _finj.ENABLED and _finj.check(
                    "kv.heartbeat", context=f"rank {self.rank}",
                    rank=self.rank):
                _hb_fail_counter.inc()
                return False
        except FaultInjected:
            _hb_fail_counter.inc()
            return False
        try:
            # seq guarantees the value changes every beat (peers detect
            # liveness by value CHANGE, not by comparing wall clocks);
            # t/pid ride along for humans reading the control plane
            self._beats += 1
            self.control.put(f"hb/{self.rank}", json.dumps(
                {"t": self._clock(), "seq": self._beats,
                 "pid": os.getpid(),
                 "incarnation": self.incarnation}))
        except (OSError, MXNetError) as e:
            # a failed stamp is survivable by design — peers notice the
            # stale key; crashing the member here would turn one slow
            # filesystem write into a host loss
            _hb_fail_counter.inc()
            _log().warning("fleet: rank %d heartbeat write failed (%r)",
                           self.rank, e)
            return False
        _hb_counter.inc()
        return True

    def start(self):
        """Start the background heartbeat thread (daemon, one stamp per
        `heartbeat_ms`). Idempotent. The fleet supervisor also beats
        inline once per applied step — the thread covers long steps and
        the gaps around restore/reshard."""
        if self._thread is not None:
            return self
        try:
            # a respawned incarnation retracts any previous farewell
            self.control.delete(f"bye/{self.rank}")
        except (OSError, MXNetError):
            pass
        self._stop.clear()
        self.beat()

        def loop():
            while not self._stop.wait(self.heartbeat_ms / 1000.0):
                self.beat()

        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"mxtpu-fleet-hb-{self.rank}")
        self._thread.start()
        return self

    def stop(self):
        """Stop the heartbeat thread and post a farewell (``bye/<rank>``)
        so peers classify this exit as DEPARTED, not dead: without the
        goodbye a member that finishes its run is indistinguishable from
        a SIGKILL'd one, and its peers would burn a rollback on it."""
        self._stop.set()
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=2 * self.heartbeat_ms / 1000.0)
        try:
            self.control.put(f"bye/{self.rank}", "1")
        except (OSError, MXNetError):
            pass        # peers fall back to heartbeat aging

    def departed(self):
        """Ranks that said goodbye (clean exits)."""
        out = set()
        for k in self.control.keys("bye/"):
            try:
                out.add(int(k[len("bye/"):]))
            except ValueError:
                continue
        return out

    def last_beat(self, rank):
        """The decoded heartbeat record for `rank` ({"t", "seq", "pid",
        "incarnation"}) or None (never stamped / torn JSON)."""
        raw = self.control.get(f"hb/{int(rank)}")
        if raw is None:
            return None
        try:
            rec = json.loads(raw)
        except ValueError:
            return None
        return rec if isinstance(rec, dict) else None

    # -------------------------------------------------- liveness/leader
    def live_ranks(self, now=None):
        """Ranks whose heartbeat value changed within the last
        `deadline_ms` and that are not masked by a fired ``host.lost``
        fault point (sorted). A stamp is aged from the moment THIS
        observer last saw its value change, on the observer's own
        `mono` clock — never by comparing the peer's embedded wall time
        against the local clock — so cross-host clock skew or an NTP
        step cannot declare a beating peer dead. (The flip side: a
        stamp first seen already-stale counts as fresh and takes one
        full deadline to expire — conservative, it only delays
        detection.) Also feeds `_seen`: dead-peer detection
        distinguishes "expired" from "never joined"."""
        now = self._mono() if now is None else now
        masked = set(_finj.lost_hosts())
        out = []
        for r in range(self.world):
            raw = self.control.get(f"hb/{r}")
            if raw is None:
                continue
            self._seen.add(r)
            obs = self._hb_obs.get(r)
            if obs is None or obs[0] != raw:
                obs = (raw, now)
                self._hb_obs[r] = obs
            if r in masked:
                continue
            age_ms = (now - obs[1]) * 1000.0
            if age_ms <= self.deadline_ms:
                out.append(r)
        return out

    def dead_peers(self, now=None):
        """Peers (not self) that JOINED the fleet and are now dead:
        heartbeat unchanged past the deadline, or rank masked by
        ``host.lost``. A rank never seen is absent, not dead — a fleet
        starting up must not declare unjoined peers lost — and a rank
        that posted ``bye/<rank>`` departed cleanly, which is not a
        death either."""
        now = self._mono() if now is None else now
        live = set(self.live_ranks(now))
        gone = self.departed()
        return sorted(r for r in self._seen
                      if r != self.rank and r not in live
                      and r not in gone)

    def leader(self, now=None):
        """The lowest live rank (None: nobody live — not even self).
        Leadership needs no agreement round: every member computes the
        same min over the same heartbeat keys. Observed transitions
        count into ``fleet_elections``."""
        live = self.live_ranks(now)
        lead = min(live) if live else None
        if lead is not None and lead != self._last_leader:
            _election_counter.inc()
            if self._last_leader is not None:
                _log().warning("fleet: rank %d observed leadership move "
                               "%d -> %d", self.rank, self._last_leader,
                               lead)
            self._last_leader = lead
        return lead

    def is_leader(self, now=None):
        return self.leader(now) == self.rank

    # ------------------------------------------------------- epochs
    def epoch(self):
        """The fleet epoch: bumped by whichever survivor first detects a
        host loss; namespaces one agreement round's keys."""
        raw = self.control.get("epoch")
        if raw is None:
            return 0
        try:
            return int(raw)
        except ValueError:
            return 0

    def bump_epoch(self, incident=None):
        """Advance the epoch and return the value this incident's
        survivors converge on. The counter itself is a plain KV key —
        a bare read-increment-write would let two survivors detecting
        the same loss at different moments split across epochs (the
        leader agreeing under one while followers wait on
        ``agreed/<other>`` until they crash). With `incident` (a stable
        string naming the failure — the supervisor uses
        ``rank/<dead>/<incarnation>``) the successor is claimed exactly
        once with put-if-absent: the FIRST detector assigns the epoch,
        every later detector of the same incident adopts it. A repeat
        of an identical incident name (a rank chaos-masked twice in one
        incarnation) re-joins the original epoch's agreement, which
        restores an older step — conservative, never divergent.
        Without `incident` the bump is the plain read-increment-write
        (single-caller paths and tests only)."""
        if incident is None:
            new = self.epoch() + 1
            self.control.put("epoch", str(new))
            return new
        key = f"incident/{incident}"
        new = self.epoch() + 1
        if not self.control.put_new(key, str(new)):
            try:
                new = int(self.control.get(key))
            except (TypeError, ValueError):
                pass    # torn claim: keep our own successor; the
                        # round-level epoch re-poll converges the rest
        if new > self.epoch():
            self.control.put("epoch", str(new))
        return new

    # ------------------------------------------------ rollback agreement
    def propose_rollback(self, epoch, step):
        """Post this rank's newest locally-restorable step for `epoch`."""
        self.control.put(f"rollback/{int(epoch)}/{self.rank}",
                         str(int(step)))

    def proposals(self, epoch):
        """{rank: step} posted for `epoch` so far."""
        prefix = f"rollback/{int(epoch)}/"
        out = {}
        for k in self.control.keys(prefix):
            raw = self.control.get(k)
            try:
                out[int(k[len(prefix):])] = int(raw)
            except (TypeError, ValueError):
                continue    # torn/foreign key: not a proposal
        return out

    def agree_rollback(self, epoch, expect=None, timeout_ms=None,
                       poll_ms=50.0):
        """LEADER side: wait (bounded) for proposals from `expect`
        (default: the currently-live ranks, self included), publish
        ``agreed/<epoch>`` = min over whatever was posted by the
        deadline, and return it. A straggler that never posts cannot
        block the fleet — the deadline converts it into "agreed without
        you" (its own proposal, had it arrived, could only have LOWERED
        the step; min over a subset is still restorable by every
        subset member, and the straggler restores the published step or
        dies trying). Returns None when the fleet epoch moves past
        `epoch` mid-collection: the round is stale — another survivor
        of the same incident raced the counter higher — and the caller
        must re-propose and re-agree under the current epoch."""
        timeout_ms = self.deadline_ms if timeout_ms is None \
            else float(timeout_ms)
        expect = set(self.live_ranks() if expect is None else expect)
        expect.add(self.rank)
        deadline = self._mono() + timeout_ms / 1000.0
        while True:
            if self.epoch() > int(epoch):
                return None
            got = self.proposals(epoch)
            if expect <= set(got) or self._mono() >= deadline:
                break
            self._sleep(poll_ms / 1000.0)
        if not got:
            raise MXNetError(
                f"fleet: no rollback proposals for epoch {epoch} within "
                f"{timeout_ms:g}ms — cannot agree a rollback step")
        agreed = min(got.values())
        self.control.put(f"agreed/{int(epoch)}", str(agreed))
        _agreement_counter.inc()
        missing = sorted(expect - set(got))
        _log().warning(
            "fleet: epoch %d rollback agreed at step %d over proposals "
            "%s%s", epoch, agreed, got,
            f" (stragglers {missing} missed the deadline)" if missing
            else "")
        return agreed

    def agreed_rollback(self, epoch):
        """The published agreement for `epoch`, or None."""
        raw = self.control.get(f"agreed/{int(epoch)}")
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None

    def wait_rollback(self, epoch, timeout_ms=None, poll_ms=50.0):
        """FOLLOWER side: poll for the leader's published agreement.
        The DEFAULT deadline is 2x `deadline_ms` — strictly longer than
        the leader's collection window, because a leader with a
        straggler only publishes AT its own deadline; an equal deadline
        would time a prompt follower out moments before publication and
        crash it against an imminent agreement. Returns the agreed
        step, or None when either the deadline passes with nothing
        published (leader died mid-agreement — the caller re-enters
        detection, where the next liveness read elects a new leader) or
        the fleet epoch moved past `epoch` (stale round — re-propose
        and wait under the current epoch)."""
        timeout_ms = 2.0 * self.deadline_ms if timeout_ms is None \
            else float(timeout_ms)
        deadline = self._mono() + timeout_ms / 1000.0
        while True:
            step = self.agreed_rollback(epoch)
            if step is not None:
                return step
            if self.epoch() > int(epoch):
                return None
            if self._mono() >= deadline:
                return None
            self._sleep(poll_ms / 1000.0)


class FleetSupervisor(TrainingSupervisor):
    """`TrainingSupervisor` + fleet membership. Adds to the per-step
    probe: an inline heartbeat, peer liveness (a newly-dead peer raises
    `HostLost` into the CLASSIFY → RECOVER loop), and the rank-keyed
    ``host.lost`` chaos point. The ``host_lost`` recovery policy is the
    cross-host rollback agreement described in the module docstring.

    Extra keyword arguments over the base: `member` (a prebuilt
    `FleetMember`) or `rank`/`world`/`control` to build one;
    `rebootstrap` (None reads MXTPU_FLEET_REBOOTSTRAP, default off) to
    tear down + re-init the jax distributed runtime after an agreement
    so collectives re-form around the respawned peer."""

    def __init__(self, trainer, step_fn, data, *, member=None, rank=None,
                 world=None, control=None, rebootstrap=None, **kwargs):
        super().__init__(trainer, step_fn, data, **kwargs)
        if member is None:
            member = FleetMember(0 if rank is None else rank,
                                 1 if world is None else world,
                                 control=control)
        self.member = member
        self._known_dead = set()
        if rebootstrap is None:
            rebootstrap = _env.env_int("MXTPU_FLEET_REBOOTSTRAP", 0,
                                       minimum=0) > 0
        self._rebootstrap = bool(rebootstrap)

    # ------------------------------------------------------- per-step
    def _probe(self):
        self.member.beat()
        self._check_peers()
        if _finj.ENABLED:
            _finj.check_host_loss(self.member.rank,
                                  context=f"step {self._applied}")
        super()._probe()

    def _check_peers(self):
        dead = set(self.member.dead_peers())
        returned = self._known_dead - dead
        if returned:
            # a respawned worker re-joined (fresh heartbeat under a new
            # incarnation): clear it so a LATER death is detected again
            self._known_dead -= returned
            _log().warning("fleet: rank %d sees peers %s back",
                           self.member.rank, sorted(returned))
        fresh = sorted(dead - self._known_dead)
        if fresh:
            self._known_dead |= dead
            raise HostLost(
                fresh[0],
                context=f"peer heartbeat expired (dead={sorted(dead)}, "
                        f"observer rank {self.member.rank})")

    # -------------------------------------------------- host_lost policy
    def _host_lost_recover(self, exc):
        """Survivor-side host-loss recovery: converge on the incident's
        epoch, run the rollback agreement, optionally re-bootstrap the
        distributed runtime, and restore the agreed step exactly.

        Epoch convergence is two-layered. The bump is arbitrated by a
        put-if-absent claim keyed by the dead rank and its incarnation,
        so survivors detecting the same loss at different moments adopt
        the first detector's epoch instead of splitting the counter.
        And should the counter still move past a round (the claim key
        differs — e.g. two distinct deaths overlap), both sides of the
        agreement re-poll the epoch, abandon the stale round (None),
        and this loop re-proposes under the current epoch — so a
        follower can never strand itself waiting on
        ``agreed/<stale-epoch>`` while the leader agrees elsewhere."""
        if self._mgr is None:
            self._crash(exc, "host_lost",
                        "no checkpoint manager configured — cross-host "
                        "rollback impossible")
        m = self.member
        dead = getattr(exc, "rank", None)
        if dead == m.rank:
            # OUR own death (the rank-keyed host.lost chaos point): this
            # rollback IS the in-place restart, so the member unmasks
            # itself — leaving the mask on would exclude it from its own
            # liveness reads and it could never lead (or even join) the
            # agreement. Genuinely dead peers stay masked.
            _finj.reset_lost_hosts(m.rank)
            m.beat()
        incident = None
        if dead is not None:
            # the dead peer's record is stable (it stopped writing at
            # least a deadline ago), so every detector derives the same
            # incident name from it
            rec = m.last_beat(dead) or {}
            incident = f"rank/{dead}/{rec.get('incarnation', 0)}"
        epoch = m.bump_epoch(incident=incident)
        healthy = self._mgr.healthy_steps()
        own = max(healthy) if healthy else 0
        agreed = None
        rounds = 0
        while agreed is None:
            rounds += 1
            if rounds > max(4, 2 * m.world):
                self._crash(exc, "host_lost",
                            f"rollback agreement failed to converge "
                            f"after {rounds - 1} rounds (epoch {epoch})")
            m.beat()    # rounds can outlast the deadline; stay live
            m.propose_rollback(epoch, own)
            if m.is_leader():
                agreed = m.agree_rollback(epoch)
            else:
                agreed = m.wait_rollback(epoch)
            if agreed is not None:
                break
            cur = m.epoch()
            if cur > epoch:
                # the counter moved past this round: converge on the
                # incident's final epoch and re-run under it
                epoch = cur
                continue
            if m.is_leader():
                # the leader died mid-agreement and WE are its
                # successor: publish (None again = epoch moved, loop)
                agreed = m.agree_rollback(epoch)
                if agreed is not None:
                    break
                cur = m.epoch()
                if cur > epoch:
                    epoch = cur
                    continue
            self._crash(exc, "host_lost",
                        f"no rollback agreement published for "
                        f"epoch {epoch} within the deadline")
        if _tracer.ACTIVE:
            _tracer.instant("fault.fleet_rollback", cat="fault",
                            args={"epoch": epoch, "agreed": int(agreed),
                                  "rank": m.rank})
        if self._rebootstrap:
            self._rebootstrap_distributed()
        self._restore_agreed(agreed, cause=exc)
        _log().warning(
            "fleet: rank %d recovered from host loss (%r) — epoch %d, "
            "agreed rollback step %d", m.rank, exc, epoch, agreed)

    def _rebootstrap_distributed(self):
        """Re-form the multi-host runtime around the survivors (and any
        respawned member): tear down the old client, then
        `init_distributed` — which already wraps the rendezvous in the
        MXTPU_DIST retry/backoff policy, because a respawned peer may
        arrive seconds later."""
        from .. import kvstore as _kv
        _kv.reset_distributed()
        _kv.init_distributed()

    def _restore_agreed(self, agreed, cause=None):
        """Restore the agreed step. The agreement is min() over newest
        locally-restorable steps, so it exists here unless retention
        pruned it between propose and restore — then fall back to the
        newest local healthy step at or below it (strictly older =
        strictly safer; it replays more, it cannot diverge)."""
        candidates = [s for s in self._mgr.healthy_steps() if s <= agreed]
        if not candidates:
            self._crash(cause, "host_lost",
                        f"agreed rollback step {agreed} has no local "
                        f"checkpoint at or below it")
        local = max(candidates)
        if local != agreed:
            _log().warning(
                "fleet: agreed step %d not on local disk — restoring "
                "older healthy step %d (pruned between propose and "
                "restore?)", agreed, local)
        params = self._mgr.restore_step(local, self._template())
        self._apply_restored(local, params, cause=cause,
                             domain="host_lost")

    def _restore(self, initial, cause=None, domain=None):
        """On an INITIAL (re)start — the respawned-worker path — honor
        an already-published agreement for the current epoch instead of
        this host's own newest step: the fleet already decided where
        everyone resumes."""
        if initial and self._mgr is not None:
            epoch = self.member.epoch()
            agreed = self.member.agreed_rollback(epoch) \
                if epoch > 0 else None
            if agreed is not None and \
                    any(s <= agreed for s in self._mgr.healthy_steps()):
                self._restore_agreed(agreed, cause=cause)
                _log().warning(
                    "fleet: rank %d resumed from agreed step %d "
                    "(epoch %d) after restart", self.member.rank,
                    self._applied, epoch)
                return self._applied
        return super()._restore(initial, cause=cause, domain=domain)


def run_fleet(trainer, step_fn, data, num_steps, *, rank=None, world=None,
              control=None, resume=None, **kwargs):
    """Convenience: build a `FleetSupervisor` (rank/world default to the
    launcher env — MXTPU_WORKER_ID / MXTPU_NUM_WORKERS — else a
    single-member fleet), run it with the background heartbeat thread
    up, and return (report, supervisor)."""
    if rank is None or world is None:
        from ..kvstore import _cluster_env
        _, n, r = _cluster_env()
        rank = (r or 0) if rank is None else rank
        world = (n or 1) if world is None else world
    sup = FleetSupervisor(trainer, step_fn, data, rank=rank, world=world,
                          control=control, **kwargs)
    sup.member.start()
    try:
        report = sup.run(num_steps, resume=resume)
    finally:
        sup.member.stop()
    return report, sup
