"""YOLOv3 (GluonCV parity: gluoncv/model_zoo/yolo/yolo3.py — darknet53
backbone, 3-scale FPN neck, per-scale anchor heads).

TPU-first design decisions:
- NHWC everywhere, bf16-castable: convs land on the MXU in its native
  layout (same policy as models/ssd.py).
- Static decode: grid offsets and anchor tables are precomputed numpy
  constants folded into the jitted program — no data-dependent shapes.
  Predictions from all 3 scales concatenate to one (B, N, 5+C) tensor.
- Static-shape NMS: predictions pre-select the top `nms_topk` positions
  by score (the SSD path's trick), then run ops/detection_ops.nms
  (fori_loop mask, fixed max_out) — the whole predict path compiles once
  and the IOU matrix stays (topk, topk), not (N, N).
- Training: YOLOV3TargetGenerator runs HOST-side in the data pipeline,
  exactly like the reference's YOLOV3PrefetchTargetGenerator (targets
  ride in with the batch); the loss + forward + backward then jit as one
  program over those precomputed target tensors.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ops.detection_ops import nms as _nms

__all__ = ["DarkNet53", "YOLOV3", "YOLOV3TargetGenerator", "YOLOV3Loss",
           "yolo3_darknet53", "yolo_decode"]

# COCO-style anchor pixel sizes per scale (stride 32, 16, 8)
_ANCHORS = (((116, 90), (156, 198), (373, 326)),
            ((30, 61), (62, 45), (59, 119)),
            ((10, 13), (16, 30), (33, 23)))
_STRIDES = (32, 16, 8)


def _conv(ch, k, stride=1, prefix=None):
    blk = nn.HybridSequential(prefix=prefix)
    with blk.name_scope():
        blk.add(nn.Conv2D(ch, k, strides=stride, padding=k // 2,
                          use_bias=False, layout="NHWC"),
                nn.BatchNorm(axis=3, epsilon=1e-5),
                nn.LeakyReLU(0.1))
    return blk


class _Residual(HybridBlock):
    def __init__(self, ch, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential()
            with self.body.name_scope():
                self.body.add(_conv(ch // 2, 1), _conv(ch, 3))

    def hybrid_forward(self, F, x):
        return x + self.body(x)


class DarkNet53(HybridBlock):
    """The YOLOv3 backbone (reference: gluoncv darknet.py). Returns the
    stride-8/16/32 maps for the neck."""

    # (channels, residual-blocks) per stage after the stride-2 conv
    _SPEC = ((64, 1), (128, 2), (256, 8), (512, 8), (1024, 4))

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.stem = _conv(32, 3)
            self.stages = nn.HybridSequential()
            with self.stages.name_scope():
                for ch, n_res in self._SPEC:
                    stage = nn.HybridSequential()
                    with stage.name_scope():
                        stage.add(_conv(ch, 3, stride=2))
                        for _ in range(n_res):
                            stage.add(_Residual(ch))
                    self.stages.add(stage)

    def hybrid_forward(self, F, x):
        x = self.stem(x)
        outs = []
        for i, stage in enumerate(self.stages):
            x = stage(x)
            if i >= 2:          # strides 8, 16, 32
                outs.append(x)
        return tuple(outs)


class _Neck(HybridBlock):
    """5-conv detection block + branch conv (reference: YOLODetectionBlockV3)."""

    def __init__(self, ch, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential()
            with self.body.name_scope():
                for i in range(2):
                    self.body.add(_conv(ch, 1), _conv(ch * 2, 3))
                self.body.add(_conv(ch, 1))
            self.tip = _conv(ch * 2, 3)

    def hybrid_forward(self, F, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOV3(HybridBlock):
    """forward(x NHWC (B, S, S, 3)) -> raw head outputs, one per scale:
    (B, H, W, A*(5+C)) for strides (32, 16, 8). Use `yolo_decode` (or
    `predict`) for boxes; `YOLOV3TargetGenerator`+`YOLOV3Loss` to train."""

    def __init__(self, num_classes=20, input_size=416, **kwargs):
        super().__init__(**kwargs)
        if input_size % 32:
            raise MXNetError("yolo3: input_size must be divisible by 32")
        self.num_classes = num_classes
        self.input_size = input_size
        ch = (512, 256, 128)
        na = len(_ANCHORS[0])
        with self.name_scope():
            self.backbone = DarkNet53()
            self.necks = nn.HybridSequential()
            self.trans = nn.HybridSequential()   # 1x1 before upsample
            self.heads = nn.HybridSequential()
            with self.necks.name_scope():
                for c in ch:
                    self.necks.add(_Neck(c))
            with self.trans.name_scope():
                for c in ch[1:]:
                    self.trans.add(_conv(c, 1))
            with self.heads.name_scope():
                for _ in ch:
                    self.heads.add(nn.Conv2D(na * (5 + num_classes), 1,
                                             layout="NHWC"))

    def hybrid_forward(self, F, x):
        c3, c4, c5 = self.backbone(x)       # strides 8, 16, 32
        feats = [c5, c4, c3]
        outs, route = [], None
        for i, (neck, head) in enumerate(zip(self.necks, self.heads)):
            f = feats[i]
            if route is not None:
                up = self.trans[i - 1](route)
                up = _apply(lambda u: jnp.repeat(
                    jnp.repeat(u, 2, axis=1), 2, axis=2), [up])
                f = _apply(lambda a, b: jnp.concatenate([a, b], -1),
                           [up, f])
            route, tip = neck(f)
            outs.append(head(tip))
        return tuple(outs)                   # strides 32, 16, 8

    # ------------------------------------------------------------ inference
    def predict(self, x, conf_thresh=0.1, nms_thresh=0.45, max_out=100,
                nms_topk=400):
        """Decoded + NMS'd detections: (ids (B,K), scores (B,K),
        boxes (B,K,4)) with K = max_out, -1 padding (gluoncv contract)."""
        outs = self(x)
        return yolo_decode(outs, self.num_classes, self.input_size,
                           conf_thresh=conf_thresh, nms_thresh=nms_thresh,
                           max_out=max_out, nms_topk=nms_topk)


def _grids_and_anchors(input_size):
    """Static per-scale decode tables: grid xy offsets (H*W*A, 2) and
    anchor wh (H*W*A, 2), concatenated over scales."""
    gs, anc, strides = [], [], []
    for (stride, anchors) in zip(_STRIDES, _ANCHORS):
        hw = input_size // stride
        ys, xs = np.meshgrid(np.arange(hw), np.arange(hw), indexing="ij")
        grid = np.stack([xs, ys], -1).astype(np.float32)       # (H, W, 2)
        grid = np.repeat(grid[:, :, None, :], len(anchors), 2)  # (H,W,A,2)
        a = np.broadcast_to(np.asarray(anchors, np.float32),
                            (hw, hw, len(anchors), 2))
        gs.append(grid.reshape(-1, 2))
        anc.append(a.reshape(-1, 2))
        strides.append(np.full((hw * hw * len(anchors), 1), stride,
                               np.float32))
    return (np.concatenate(gs), np.concatenate(anc),
            np.concatenate(strides))


def yolo_decode(outs, num_classes, input_size, conf_thresh=0.1,
                nms_thresh=0.45, max_out=100, nms_topk=400):
    """Raw heads -> (ids, scores, boxes) with static shapes (reference:
    YOLOOutputV3 decode + box NMS). Top-`nms_topk` score preselection
    keeps the NMS IOU matrix (topk, topk) instead of (N, N) — at 416 px
    N is 10647, so unpreselected NMS would be ~450 MB/image."""
    grid, anchors, stride = _grids_and_anchors(input_size)

    def fn(*raw):
        flat = [r.reshape(r.shape[0], -1, 5 + num_classes) for r in raw]
        p = jnp.concatenate(flat, 1).astype(jnp.float32)   # (B, N, 5+C)
        xy = (jax.nn.sigmoid(p[..., :2]) + grid) * stride
        wh = jnp.exp(jnp.clip(p[..., 2:4], -10, 8)) * anchors
        obj = jax.nn.sigmoid(p[..., 4:5])
        cls = jax.nn.sigmoid(p[..., 5:])
        scores_all = obj * cls                              # (B, N, C)
        boxes = jnp.concatenate([xy - wh / 2, xy + wh / 2], -1)
        n, c = scores_all.shape[1], scores_all.shape[2]
        k = min(nms_topk, n * c)

        def per_image(bx, sc):
            # reference box_nms contract (force_suppress=False): every
            # (position, class) pair is a candidate, and only same-class
            # boxes suppress each other — a rider and their horse both
            # survive even at high IOU
            flat_scores = sc.reshape(-1)                    # (N*C,)
            flat_cls = jnp.tile(jnp.arange(c), n).astype(jnp.float32)
            top = jnp.argsort(-flat_scores)[:k]             # preselect
            bx_k = bx[top // c]
            best_k = flat_scores[top]
            cid_k = flat_cls[top]
            keep = _nms(bx_k, best_k, iou_threshold=nms_thresh,
                        max_out=max_out, class_ids=cid_k)
            best_k = jnp.where(jnp.logical_and(keep, best_k > conf_thresh),
                               best_k, 0.0)
            order = jnp.argsort(-best_k)[:max_out]
            t_scores = best_k[order]
            valid = t_scores > 0
            return (jnp.where(valid, cid_k[order], -1).astype(jnp.float32),
                    jnp.where(valid, t_scores, -1.0),
                    jnp.where(valid[:, None], bx_k[order], -1.0))
        return jax.vmap(per_image)(boxes, scores_all)

    return _apply(fn, list(outs), n_out=3)


class YOLOV3TargetGenerator:
    """Assign each gt box to its best-IOU anchor (over all 9) and emit
    per-position targets, concatenated over scales to match the flattened
    prediction layout. HOST-side, for the data pipeline — same contract
    as the reference YOLOV3PrefetchTargetGenerator (targets arrive with
    the batch; the jitted step consumes them as plain tensors)."""

    def __init__(self, num_classes, input_size):
        self.num_classes = num_classes
        self.input_size = input_size
        # per-scale segment offsets in the flat N dimension
        self._seg = []
        off = 0
        for s in _STRIDES:
            hw = input_size // s
            self._seg.append((off, hw))
            off += hw * hw * len(_ANCHORS[0])
        self.total = off

    def __call__(self, gt_boxes, gt_ids):
        """gt_boxes (B, M, 4) corner pixels (-1 pad), gt_ids (B, M) ->
        (obj_t (B,N,1), ctr_t (B,N,2), scale_t (B,N,2), wmask (B,N,1),
        cls_t (B,N,C))."""
        if isinstance(gt_boxes, NDArray):
            gt_boxes = gt_boxes.asnumpy()
        if isinstance(gt_ids, NDArray):
            gt_ids = gt_ids.asnumpy()
        B, M, _ = gt_boxes.shape
        N, C = self.total, self.num_classes
        obj = np.zeros((B, N, 1), np.float32)
        ctr = np.zeros((B, N, 2), np.float32)
        scale = np.zeros((B, N, 2), np.float32)
        wmask = np.zeros((B, N, 1), np.float32)
        cls = np.zeros((B, N, C), np.float32)
        flat_anchors = np.concatenate(
            [np.asarray(a, np.float32) for a in _ANCHORS])   # (9, 2)
        na = len(_ANCHORS[0])
        for b in range(B):
            for m in range(M):
                x0, y0, x1, y1 = gt_boxes[b, m]
                if x1 <= x0 or y1 <= y0:
                    continue
                w, h = x1 - x0, y1 - y0
                cx, cy = (x0 + x1) / 2, (y0 + y1) / 2
                # best anchor by shape IOU (centered)
                inter = (np.minimum(flat_anchors[:, 0], w)
                         * np.minimum(flat_anchors[:, 1], h))
                iou = inter / (flat_anchors[:, 0] * flat_anchors[:, 1]
                               + w * h - inter)
                best = int(np.argmax(iou))
                s_idx, a_idx = divmod(best, na)
                off, hw = self._seg[s_idx]
                stride = _STRIDES[s_idx]
                gx, gy = int(cx // stride), int(cy // stride)
                gx, gy = min(gx, hw - 1), min(gy, hw - 1)
                pos = off + (gy * hw + gx) * na + a_idx
                obj[b, pos, 0] = 1.0
                ctr[b, pos] = (cx / stride - gx, cy / stride - gy)
                aw, ah = flat_anchors[best]
                scale[b, pos] = (np.log(max(w, 1.0) / aw),
                                 np.log(max(h, 1.0) / ah))
                # small boxes get larger weight (reference 2 - w*h/S^2)
                wmask[b, pos, 0] = 2.0 - (w * h) / (self.input_size ** 2)
                cid = int(gt_ids[b, m])
                if 0 <= cid < C:
                    cls[b, pos, cid] = 1.0
        from ..ndarray.ndarray import array
        return tuple(array(t) for t in (obj, ctr, scale, wmask, cls))


class YOLOV3Loss:
    """Objectness BCE + center BCE + scale L1 + class BCE, masked by the
    assignment (reference: YOLOV3Loss). With `gt_boxes` (and the loss
    constructed with `input_size`), unassigned predictions whose decoded
    box overlaps ANY gt above `ignore_iou_thresh` are EXCLUDED from the
    objectness loss — the reference's dynamic ignore mask, which stops
    training from suppressing near-duplicate detections."""

    def __init__(self, input_size=None, ignore_iou_thresh=0.7):
        self._ignore = ignore_iou_thresh
        if input_size is not None:
            self._tables = _grids_and_anchors(input_size)
        else:
            self._tables = None

    def __call__(self, outs, obj_t, ctr_t, scale_t, wmask, cls_t,
                 gt_boxes=None):
        nc = cls_t.shape[-1]
        tables = self._tables
        ignore_thresh = self._ignore
        use_ignore = gt_boxes is not None and tables is not None

        def fn(o1, o2, o3, obj, ctr, sc, wm, cl, *maybe_gt):
            flat = [r.reshape(r.shape[0], -1, 5 + nc) for r in (o1, o2, o3)]
            p = jnp.concatenate(flat, 1).astype(jnp.float32)

            def bce(logit, label):
                return (jax.nn.relu(logit) - logit * label
                        + jnp.log1p(jnp.exp(-jnp.abs(logit))))

            obj_weight = jnp.ones_like(obj)
            if use_ignore:
                grid, anchors, stride = tables
                gt = maybe_gt[0].astype(jnp.float32)        # (B, M, 4)
                xy = (jax.nn.sigmoid(p[..., :2]) + grid) * stride
                wh = jnp.exp(jnp.clip(p[..., 2:4], -10, 8)) * anchors
                pb = jnp.concatenate([xy - wh / 2, xy + wh / 2], -1)
                from ..ops.detection_ops import box_iou
                max_iou = jax.vmap(
                    lambda bx, g: box_iou(bx, g).max(-1))(pb, gt)
                ignore = jnp.logical_and(max_iou[..., None] > ignore_thresh,
                                         obj < 0.5)
                obj_weight = jnp.where(ignore, 0.0, 1.0)

            denom = jnp.maximum(obj.sum(), 1.0)
            l_obj = (bce(p[..., 4:5], obj) * obj_weight).mean() \
                * obj.shape[1]
            l_ctr = (bce(p[..., :2], ctr) * obj * wm).sum() / denom
            l_scale = (jnp.abs(p[..., 2:4] - sc) * obj * wm).sum() / denom
            l_cls = (bce(p[..., 5:], cl) * obj).sum() / denom
            return l_obj + l_ctr + l_scale + l_cls
        ins = list(outs) + [obj_t, ctr_t, scale_t, wmask, cls_t]
        if use_ignore:
            ins.append(gt_boxes)
        return _apply(fn, ins)


def yolo3_darknet53(num_classes=20, input_size=416, **kwargs):
    """GluonCV constructor name (yolo3_darknet53_voc/coco families)."""
    return YOLOV3(num_classes=num_classes, input_size=input_size, **kwargs)
