"""DLRM-style recommender bench: sharded embedding tables vs the
replicated dense-take layout (ISSUE 15; docs/PERFORMANCE.md "Sharded
embeddings").

The model is deliberately EMBEDDING-DOMINATED — several categorical
tables holding ~99% of the parameter bytes over a thin dense tower —
because that is the recommender workload's shape: memory capacity, not
FLOPs, is the binding constraint, and the headline metric is
`embed_param_bytes_per_dev` (~= 1/tp of the replicated footprint), not
step time. Categorical index batches are drawn from a Poisson-ish
per-feature distribution (a few hot rows, a long tail — Poisson around
a per-feature hot centre, folded into range), which is what makes the
sparse path's dedup/unique pass earn its keep: hot rows cross the
interconnect once per step no matter how many batch positions hit them.

Two arms on the same model, data and captured-step protocol:

  * sharded — `ShardedEmbedding` tables row-sharded over 'tp' on the
    (2,2) ('dp','tp') DEFAULT_RULES mesh: the captured step lowers the
    lookup to the bucketed all-to-all exchange and the backward to the
    (unique_rows, D) sparse fast path (`sharded_embed_step`);
  * replicated — the same tower with plain `Embedding` tables on a 1-D
    'dp' mesh: tables whole on every device, dense take, dense O(vocab)
    gradient. This is the SURVEY §8 layout the sharded arm retires.

A third arm (ISSUE 19, `--tiered` / `measure_tiered`) trains a tiered
table at a FIXED HBM budget: per-shard rows exceed `hbm_rows`, so the
full table cannot be device-resident and every step runs through the
host tier + engine-prefetched hot cache (`shard/tiered.py`), fed by the
`RowPrefetcher`.

Needs >= 4 devices (a (2,2) mesh); below that `value: None` so the
bench.py supervisor fields (`rec_step_throughput`,
`rec_embed_bytes_per_dev`, `rec_vs_replicated`, and the `rec_tiered_*`
set) are omitted honestly rather than faked — the BENCH_SHARD=0
pattern.

Standalone: `python bench_rec.py` prints ONE JSON line;
`python bench_rec.py --tiered` runs the fixed-HBM tiered arm instead.
"""
from __future__ import annotations

import json
import os
import sys
import time

# per-chip samples/s denominator for vs_baseline on a recommender step:
# a DLRM step this size is all-to-all/latency-bound, not compute-bound;
# same spirit as bench_mlp's dispatch-bound denominator
BASELINE_SAMPLES_S = 100_000.0


def _setup():
    """Shared fixture: (tables, dim, batch, steps, index batches, dense
    features, labels). Embedding-dominated: 4 tables x 2048 rows x 32
    dims = 1 MiB of table bytes vs a ~17 KiB dense tower."""
    import jax
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    vocabs = (2048, 2048, 2048, 2048)
    dim = 32
    batch = 256 if on_tpu else 32
    steps = 30 if on_tpu else 4

    rng = np.random.RandomState(0)
    # Poisson-ish categorical traffic: each feature has a hot centre;
    # ids are Poisson around it folded into the vocab range, so a few
    # rows are hit many times per batch and most rows rarely
    idx = []
    for f, V in enumerate(vocabs):
        lam = 16 * (f + 1)
        draws = rng.poisson(lam, size=(8, batch)) % V
        idx.append(draws.astype(np.int32))
    Xd = rng.randn(8, batch, 8).astype(np.float32)
    yb = rng.randn(8, batch).astype(np.float32)
    return vocabs, dim, batch, steps, idx, Xd, yb


def _build(vocabs, dim, batch, sharded):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    class _DLRM(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                cls = (gluon.nn.ShardedEmbedding if sharded
                       else gluon.nn.Embedding)
                self.tables = []
                for V in vocabs:
                    t = cls(V, dim)
                    self.register_child(t)
                    self.tables.append(t)
                self.bot = gluon.nn.Dense(dim, activation="relu",
                                          in_units=8)
                self.top = gluon.nn.Dense(
                    1, in_units=(len(vocabs) + 1) * dim)

        def hybrid_forward(self, F, i0, i1, i2, i3, xd):
            embs = [t(i) for t, i in zip(self.tables, (i0, i1, i2, i3))]
            return self.top(F.concat(*embs, self.bot(xd), dim=1))

    mx.random.seed(0)
    net = _DLRM()
    net.initialize(mx.init.Xavier())
    return net


def measure(on_result=None):
    """The supervisor arm: sharded-vs-replicated captured DLRM steps.
    Returns the `rec_*` contract fields; `value: None` below 4
    devices."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.observability import registry
    from mxnet_tpu.shard import embedding as semb

    if len(jax.devices()) < 4:
        res = {"metric": "rec_step_throughput", "value": None,
               "unit": "samples/sec/chip",
               "skipped": "needs >= 4 devices"}
        print("[bench_rec] skipped (needs >= 4 devices)",
              file=sys.stderr)
        if on_result is not None:
            on_result(res)
        return res

    vocabs, dim, batch, steps, idx, Xd, yb = _setup()
    lossf = gluon.loss.L2Loss()
    a2a = registry().counter("kv_collective_bytes",
                             op="embed_all_to_all")

    def run(sharded):
        net = _build(vocabs, dim, batch, sharded)
        nb = [nd.array(i[0], dtype=np.int32) for i in idx]
        net(*nb, nd.array(Xd[0]))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore="ici")
        if sharded:
            plan = tr.shard(mesh={"dp": 2, "tp": 2})
        else:
            from mxnet_tpu.parallel.mesh import make_mesh
            tr._kvstore.set_mesh(make_mesh({"dp": 4}))
            plan = None
        step = tr.capture(
            lambda i0, i1, i2, i3, xd, y:
            lossf(net(i0, i1, i2, i3, xd), y).mean())

        def feed(k):
            k = k % 8
            return ([nd.array(i[k], dtype=np.int32) for i in idx]
                    + [nd.array(Xd[k]), nd.array(yb[k])])

        for k in range(2):
            step(*feed(k))                      # compile + warm
        fallback = step.last_fallback_reason
        t0 = time.monotonic()
        for k in range(steps):
            L = step(*feed(k))
        float(L.asnumpy())
        dt = time.monotonic() - t0

        import re
        from mxnet_tpu.shard.rules import EMBED_WEIGHT_PATTERN
        pat = re.compile(EMBED_WEIGHT_PATTERN)
        embed = {p.name: p.data()._data
                 for p in net.collect_params().values()
                 if pat.search(p.name)}
        total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                    for a in embed.values())
        if plan is not None:
            per_dev = plan.param_bytes_per_device(embed)[0]
            frac = semb.embed_param_bytes_frac(
                plan, {p.name: p.data()._data
                       for p in net.collect_params().values()})
        else:
            per_dev, frac = total, 1.0
        return steps / dt, per_dev, total, frac, fallback

    a2a0 = a2a.value
    sh_steps_s, sh_per_dev, embed_total, sh_frac, sh_fb = run(True)
    a2a_bytes = a2a.value - a2a0
    re_steps_s, re_per_dev, _, _, re_fb = run(False)
    if sh_fb is not None:
        print(f"[bench_rec] WARNING: sharded arm fell back ({sh_fb}); "
              f"the ratio measures the imperative path", file=sys.stderr)

    res = {
        "metric": "rec_step_throughput",
        "value": round(sh_steps_s * batch / 4, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sh_steps_s * batch / 4
                             / BASELINE_SAMPLES_S, 4),
        "mesh": {"dp": 2, "tp": 2},
        "rec_steps_s": round(sh_steps_s, 3),
        "replicated_steps_s": round(re_steps_s, 3),
        "rec_vs_replicated": round(sh_steps_s / re_steps_s, 3),
        "rec_embed_bytes_per_dev": int(sh_per_dev),
        "replicated_embed_bytes_per_dev": int(re_per_dev),
        "embed_bytes_total": int(embed_total),
        "embed_param_bytes_frac": round(sh_frac, 4),
        "embed_a2a_bytes_per_step": (None if a2a_bytes == 0
                                     else int(a2a_bytes // (steps + 2))),
        "fallback": sh_fb,
        "replicated_fallback": re_fb,
    }
    print(f"[bench_rec] sharded {sh_steps_s:.2f} steps/s vs "
          f"{re_steps_s:.2f} replicated "
          f"({res['rec_vs_replicated']}x); embed bytes/dev "
          f"{sh_per_dev} vs {re_per_dev} replicated "
          f"({sh_frac:.2f}x of total); "
          f"{res['embed_a2a_bytes_per_step']} all-to-all B/step",
          file=sys.stderr)
    if on_result is not None:
        on_result(res)
    return res


def measure_tiered(on_result=None):
    """The fixed-HBM arm (ISSUE 19): ONE tiered `ShardedEmbedding`
    table whose per-shard rows EXCEED its hbm_rows budget — the full
    table cannot be device-resident, which is the tier's reason to
    exist — trained end-to-end through the `RowPrefetcher`-fed captured
    step (host-resident cold rows, engine-prefetched hot cache;
    docs/PERFORMANCE.md "Tiered embeddings"). Headline is samples/sec/
    chip AT the fixed HBM budget, alongside the cache hit rate the
    Poisson-ish traffic earns and the async H2D row-staging bytes each
    step costs. `value: None` below 4 devices — the omit-honestly
    pattern."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.prefetch import RowPrefetcher
    from mxnet_tpu.shard import tiered as _tiered

    if len(jax.devices()) < 4:
        res = {"metric": "rec_tiered_step_throughput", "value": None,
               "unit": "samples/sec/chip",
               "skipped": "needs >= 4 devices"}
        print("[bench_rec] tiered arm skipped (needs >= 4 devices)",
              file=sys.stderr)
        if on_result is not None:
            on_result(res)
        return res

    on_tpu = jax.default_backend() == "tpu"
    V, D, F = 8192, 32, 4
    HBM_ROWS = 256            # per-'tp'-shard rows = V/2 = 4096 >> 256
    batch = 256 if on_tpu else 32
    steps = 30 if on_tpu else 6

    rng = np.random.RandomState(7)
    # Poisson-ish categorical traffic (hot centre + long tail) so the
    # cache hit rate is a property of the workload, not of uniform draws
    idx = (rng.poisson(64, size=(8, batch, F)) % V).astype(np.int32)
    yb = rng.randn(8, batch, 1).astype(np.float32)

    class _TieredDLRM(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.ShardedEmbedding(
                    V, D, tiered=True, hbm_rows=HBM_ROWS)
                self.top = gluon.nn.Dense(1, in_units=F * D)

        def hybrid_forward(self, F_, i):
            return self.top(self.embed(i).reshape((i.shape[0], -1)))

    mx.random.seed(0)
    net = _TieredDLRM()
    net.initialize(mx.init.Xavier())
    lossf = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2})
    step = tr.capture(lambda i, y: lossf(net(i), y).mean())
    shard_rows = V // 2       # rows each 'tp' shard owns in the host tier

    def feed(n):
        for k in range(n):
            j = k % 8
            yield nd.array(idx[j], dtype=np.int32), nd.array(yb[j])

    # compile + warm THROUGH the prefetcher: tiered steps only dispatch
    # behind a RowPrefetcher (the loud no-prefetcher error is the point)
    with RowPrefetcher(feed(2), tr, tables={0: net.embed}) as pf:
        for ib, y in pf:
            L = step(ib, y)
    fallback = step.last_fallback_reason

    h2d0 = _tiered._h2d_b.value
    hits0, miss0 = _tiered._hits_c.value, _tiered._miss_c.value
    t0 = time.monotonic()
    with RowPrefetcher(feed(steps), tr, tables={0: net.embed}) as pf:
        for ib, y in pf:
            L = step(ib, y)
    float(L.asnumpy())
    dt = time.monotonic() - t0
    hits = _tiered._hits_c.value - hits0
    miss = _tiered._miss_c.value - miss0
    hit_rate = hits / max(1, hits + miss)
    h2d_step = (_tiered._h2d_b.value - h2d0) / steps
    steps_s = steps / dt
    if fallback is not None:
        print(f"[bench_rec] WARNING: tiered arm fell back ({fallback})",
              file=sys.stderr)

    res = {
        "metric": "rec_tiered_step_throughput",
        "value": round(steps_s * batch / 4, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(steps_s * batch / 4 / BASELINE_SAMPLES_S,
                             4),
        "mesh": {"dp": 2, "tp": 2},
        "rec_tiered_steps_s": round(steps_s, 3),
        "rec_tiered_hit_rate": round(hit_rate, 4),
        "rec_tiered_h2d_bytes_per_step": int(h2d_step),
        "rec_tiered_hbm_rows": HBM_ROWS,
        "rec_tiered_shard_rows": shard_rows,
        "rec_tiered_resident_frac": round(HBM_ROWS / shard_rows, 4),
        "fallback": fallback,
    }
    print(f"[bench_rec] tiered {steps_s:.2f} steps/s at a "
          f"{HBM_ROWS}/{shard_rows}-row HBM budget "
          f"({res['rec_tiered_resident_frac']:.3f}x resident); hit "
          f"rate {hit_rate:.2f}; {int(h2d_step)} async H2D B/step",
          file=sys.stderr)
    if on_result is not None:
        on_result(res)
    return res


def main():
    # fork CPU devices BEFORE jax imports so the (2,2) mesh exists on a
    # laptop/CI run (no-op when jax is already in, e.g. under bench.py)
    if "jax" not in sys.modules \
            and os.environ.get("JAX_PLATFORMS", "") == "cpu" \
            and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_"
                                     "device_count=4")
    if "--tiered" in sys.argv[1:]:
        res = measure_tiered()
    else:
        res = measure()
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
