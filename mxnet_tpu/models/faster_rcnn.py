"""Faster R-CNN (GluonCV faster_rcnn_resnet50_v1b parity — RPN, proposal
NMS, ROIAlign, two-stage head; rebuilt TPU-first from gluoncv behavior).

TPU-first choices:
  * every stage has STATIC shapes: fixed top-k pre-NMS proposals, fixed
    post-NMS budget (invalid slots flagged, not dropped), fixed fg/bg sample
    counts — so the full two-stage pipeline jits into one XLA program;
  * ROIAlign is the vectorised bilinear gather from ops.detection_ops
    (vmap over rois), not a per-roi loop;
  * NHWC backbone (MXU conv layout).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..ndarray.ndarray import NDArray, _apply
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.model_zoo.vision.resnet import get_resnet
from ..ops import detection_ops as D

__all__ = ["FasterRCNN", "faster_rcnn_resnet50_v1", "rpn_anchors",
           "generate_proposals", "rcnn_targets"]


def rpn_anchors(feat_h, feat_w, stride=16, scales=(8, 16, 32),
                ratios=(0.5, 1, 2)):
    """Anchors in input-pixel corner coords, (feat_h*feat_w*K, 4)."""
    base = []
    for s in scales:
        for r in ratios:
            w = s * stride * np.sqrt(r)
            h = s * stride / np.sqrt(r)
            base.append([-w / 2, -h / 2, w / 2, h / 2])
    base = np.asarray(base, np.float32)                    # (K, 4)
    cy = (np.arange(feat_h) + 0.5) * stride
    cx = (np.arange(feat_w) + 0.5) * stride
    cyx = np.stack(np.meshgrid(cy, cx, indexing="ij"), -1).reshape(-1, 1, 2)
    shift = np.concatenate([cyx[..., ::-1], cyx[..., ::-1]], -1)  # (HW,1,4)
    return (base[None] + shift).reshape(-1, 4).astype(np.float32)


def generate_proposals(obj_logits, deltas, anchors, im_size, pre_nms=2000,
                       post_nms=300, nms_thresh=0.7, min_size=4.0):
    """RPN outputs -> fixed post_nms proposal boxes per image.

    obj_logits (A,), deltas (A, 4), anchors (A, 4) -> (post_nms, 4) boxes +
    (post_nms,) validity scores (0 for suppressed slots).
    """
    boxes = D.box_decode(deltas, anchors, variances=(1, 1, 1, 1))
    h, w = im_size
    boxes = jnp.stack([
        jnp.clip(boxes[:, 0], 0, w), jnp.clip(boxes[:, 1], 0, h),
        jnp.clip(boxes[:, 2], 0, w), jnp.clip(boxes[:, 3], 0, h)], -1)
    wh = boxes[:, 2:] - boxes[:, :2]
    score = jax.nn.sigmoid(obj_logits)
    score = jnp.where(jnp.min(wh, -1) >= min_size, score, 0.0)
    k = min(pre_nms, boxes.shape[0])
    top_s, top_i = lax.top_k(score, k)
    top_b = boxes[top_i]
    keep = D.nms(top_b, top_s, nms_thresh, post_nms)
    kept_s = jnp.where(keep, top_s, 0.0)
    order_s, order_i = lax.top_k(kept_s, post_nms)
    return top_b[order_i], order_s


def rcnn_targets(proposals, gt, num_samples=128, fg_fraction=0.25,
                 fg_iou=0.5, key=None):
    """Sample proposals against gt (M, 5) [cls, box] rows (cls=-1 pad).

    Static shapes: returns (rois (S,4), cls_t (S,) int32 0=bg,
    box_t (S,4), box_mask (S,1)). Highest-IoU-first deterministic sampling
    (the reference samples randomly; deterministic top-k keeps this a pure
    function of inputs — rng can be layered on by shuffling proposals).
    """
    gt_boxes, gt_cls = gt[:, 1:], gt[:, 0]
    valid = gt_cls >= 0
    # append gt boxes as candidate rois (reference does this in training)
    cand = jnp.concatenate([proposals, gt_boxes], 0)
    iou = jnp.where(valid[None, :], D.box_iou(cand, gt_boxes), 0.0)
    best_iou = jnp.max(iou, 1)
    best_gt = jnp.argmax(iou, 1)
    n_fg = int(num_samples * fg_fraction)
    fg_score = jnp.where(best_iou >= fg_iou, best_iou, 0.0)
    fg_s, fg_i = lax.top_k(fg_score, n_fg)
    bg_score = jnp.where(best_iou < fg_iou, 1.0 - best_iou, 0.0)
    bg_s, bg_i = lax.top_k(bg_score, num_samples - n_fg)
    idx = jnp.concatenate([fg_i, bg_i])
    is_fg = jnp.concatenate([fg_s > 0, jnp.zeros(num_samples - n_fg, bool)])
    rois = cand[idx]
    assigned = best_gt[idx]
    cls_t = jnp.where(is_fg, gt_cls[assigned].astype(jnp.int32) + 1, 0)
    box_t = D.box_encode(gt_boxes[assigned], rois, variances=(1, 1, 1, 1))
    box_t = jnp.where(is_fg[:, None], box_t, 0.0)
    return rois, cls_t, box_t, is_fg[:, None].astype(box_t.dtype)


class FasterRCNN(HybridBlock):
    """Two-stage detector.

    forward(x NHWC) -> (obj_logits (B, A), rpn_deltas (B, A, 4),
    features NHWC). Proposals/targets/head run through `rpn_proposals`,
    `roi_head` — split so training can sample targets between stages, same
    structure as the reference's training loop.
    """

    def __init__(self, num_classes=20, backbone_layers=50, input_size=512,
                 roi_size=(7, 7), post_nms=300, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.input_size = input_size
        self.stride = 16
        self.post_nms = post_nms
        self._roi_size = roi_size
        f = input_size // self.stride
        self.anchors = rpn_anchors(f, f, self.stride)
        with self.name_scope():
            base = get_resnet(1, backbone_layers, layout="NHWC")
            feats = list(base.features._children.values())
            self.backbone = nn.HybridSequential(prefix="backbone_")
            with self.backbone.name_scope():
                for b in feats[:7]:         # through stage3: stride 16
                    self.backbone.add(b)
            self.rpn_conv = nn.Conv2D(512, 3, padding=1, activation="relu",
                                      layout="NHWC", prefix="rpn_conv_")
            self.rpn_obj = nn.Conv2D(9, 1, layout="NHWC", prefix="rpn_obj_")
            self.rpn_box = nn.Conv2D(36, 1, layout="NHWC", prefix="rpn_box_")
            self.head = nn.HybridSequential(prefix="head_")
            with self.head.name_scope():
                self.head.add(nn.Dense(1024, activation="relu"),
                              nn.Dense(1024, activation="relu"))
            self.cls_score = nn.Dense(num_classes + 1, prefix="cls_")
            self.box_pred = nn.Dense((num_classes + 1) * 4, prefix="box_")

    def hybrid_forward(self, F, x):
        feat = self.backbone(x)
        r = self.rpn_conv(feat)
        obj = self.rpn_obj(r).reshape((0, -1))            # (B, A)
        deltas = self.rpn_box(r).reshape((0, -1, 4))      # (B, A, 4)
        return obj, deltas, feat

    def rpn_proposals(self, obj, deltas, pre_nms=2000):
        size = (self.input_size, self.input_size)
        anchors = jnp.asarray(self.anchors)
        post = self.post_nms

        def fn(o, d):
            return jax.vmap(lambda oo, dd: generate_proposals(
                oo, dd, anchors, size, pre_nms, post))(o, d)

        return _apply(fn, [obj, deltas], n_out=2)

    def roi_head(self, feat, rois):
        """feat (B, H, W, C) NHWC + rois (B, R, 4) input coords ->
        (cls_scores (B, R, C+1), box_deltas (B, R, C+1, 4))."""
        scale = 1.0 / self.stride
        oh, ow = self._roi_size
        # perf lever (MXTPU_ROIALIGN=mm): einsum RoIAlign — the pool as
        # two MXU contractions instead of a gather (A/B on chip; numerics
        # identical, pinned by test_detection parity)
        import os
        align_k = D.roi_align_mm if os.environ.get(
            "MXTPU_ROIALIGN") == "mm" else D.roi_align

        def align(f, r):
            fc = jnp.moveaxis(f, -1, 0)                   # NCHW per image
            return align_k(fc, r, (oh, ow), spatial_scale=scale)

        pooled = _apply(lambda f, r: jax.vmap(align)(f, r), [feat, rois])
        b, rn = pooled.shape[0], pooled.shape[1]
        flat = pooled.reshape((b * rn, -1))
        h = self.head(flat)
        cls = self.cls_score(h).reshape((b, rn, self.num_classes + 1))
        box = self.box_pred(h).reshape((b, rn, self.num_classes + 1, 4))
        return cls, box


def faster_rcnn_resnet50_v1(num_classes=20, **kwargs):
    return FasterRCNN(num_classes=num_classes, backbone_layers=50, **kwargs)
