"""mx.optimizer namespace (reference: python/mxnet/optimizer/)."""
from .optimizer import (Optimizer, SGD, NAG, Adam, AdaBelief, AdamW, Adamax, Nadam,
                        AdaGrad, AdaDelta, RMSProp, Ftrl, Ftml, LAMB, LARS,
                        Signum, SGLD, DCASGD, create, register)
from . import optimizer as opt
from .updater import Updater, get_updater
from . import multi_tensor
from .multi_tensor import FusedUpdater, build_buckets
